"""Kernel registry: one dispatch seam for accelerated (Pallas) kernels.

The reference framework discovers per-backend "helper" implementations
(`ConvolutionHelper`/`LSTMHelper`, PAPER.md layer 1) with a portable
fallback when no accelerated helper applies. This module is the JAX
port's equivalent: each kernel name maps to an ORDERED list of candidate
implementations, each declaring `is_available(backend, shapes, dtypes)`,
and `resolve()` picks the first available one — memoized per
(kernel, mode, backend, signature) so the probe runs once per distinct
jit signature, not once per dispatch (the superstep block-restack path
calls into the seam for every block; a memo hit must not re-probe).

Selection is part of the PROGRAM IDENTITY: `nn/jit_cache.py` folds
`config_key()` into every cache key and `compilation/store.py` folds
`config_fingerprint()` into the AOT fingerprint document, so flipping a
kernel knob can never serve a stale cached program or executable.

Env knobs (read at resolve time, so tests can monkeypatch):

- ``DL4J_TPU_KERNELS=auto|xla|pallas`` — global mode. ``auto`` (default)
  picks the first candidate whose availability probe passes — Pallas on
  TPU when the shape/dtype/activation constraints hold, the bit-stable
  XLA fallback otherwise. ``xla`` forces the fallback everywhere (the CI
  default contract: bit-identical to the pre-registry inline code).
  ``pallas`` forces the Pallas candidate where structurally possible
  (interpret mode off-TPU — numerics float-close, speed irrelevant;
  parity tests run this way on the CPU mesh).
- ``DL4J_TPU_KERNEL_<NAME>`` (e.g. ``DL4J_TPU_KERNEL_LSTM_CELL``) —
  per-kernel override, same values, wins over the global mode.

``python -m deeplearning4j_tpu.kernels`` prints what resolves and why.

Registration is lazy: kernel modules self-register at import, and
`resolve()`/`describe()` import them on demand, so importing the
registry (which every jit-cache key construction does) stays cheap.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

from deeplearning4j_tpu import observability as _obs

MODES = ("auto", "xla", "pallas")

# Kernel name -> module that registers its candidates at import.
KERNEL_MODULES = {
    # Fused ResNet bottleneck chain (PR 19): conv1x1/BN/act x3 + residual
    # in one VMEM residency; XLA fallback is the unfused vertex chain.
    "bottleneck_block": "deeplearning4j_tpu.kernels.bottleneck_block",
    "lstm_cell": "deeplearning4j_tpu.kernels.lstm_cell",
    "fused_update": "deeplearning4j_tpu.kernels.fused_update",
    "norm_act": "deeplearning4j_tpu.kernels.norm_act",
    "flash_attention": "deeplearning4j_tpu.kernels.flash_attention",
    # Paged decode-attention gather variant (PR 15): registered by the
    # same module; auto off-TPU resolves to the XLA dense-gather
    # composite, which is bit-identical to the dense stepper.
    "flash_attention_paged": "deeplearning4j_tpu.kernels.flash_attention",
}


class KernelImpl(NamedTuple):
    """One candidate implementation of a kernel.

    `is_available(backend, shapes, dtypes, meta=(), forced=False)`
    returns `(ok, reason)`. `forced` relaxes backend/tiling requirements
    that Pallas interpret mode does not need (a forced impl must still
    refuse structurally impossible cases, e.g. an activation the kernel
    cannot express — resolution then falls back with the reason in the
    `Resolution`)."""

    name: str
    is_available: Callable[..., Tuple[bool, str]]


class Resolution(NamedTuple):
    kernel: str
    impl: str
    reason: str


_REGISTRY: dict = {}
_MEMO: dict = {}
_LOCK = threading.Lock()
_PROBES = 0  # is_available invocations, for the hoisting counter assertion

# Per-JX008 convention: family at import, children cached, `.inc()` in the
# (trace-time) dispatch path.
_M_DISPATCH = _obs.metrics.counter(
    "dl4j_kernel_dispatch_total",
    "kernel dispatch-seam resolutions by kernel name and resolved impl",
    label_names=("kernel", "impl"))
_DISPATCH_CHILDREN: dict = {}


def register(kernel: str, impls: Sequence[KernelImpl]) -> None:
    """Register the ordered candidate list for `kernel` (first available
    wins in `auto` mode). Re-registration replaces — module reload safe."""
    _REGISTRY[kernel] = tuple(impls)


def _ensure(kernel: str) -> None:
    if kernel not in _REGISTRY:
        mod = KERNEL_MODULES.get(kernel)
        if mod is None:
            raise KeyError(f"unknown kernel {kernel!r}; known: "
                           f"{sorted(KERNEL_MODULES)}")
        importlib.import_module(mod)  # self-registers


def kernel_names() -> Tuple[str, ...]:
    return tuple(sorted(KERNEL_MODULES))


def mode_for(kernel: str) -> Tuple[str, str]:
    """(mode, source) for one kernel: the per-kernel env override if set,
    else the global `DL4J_TPU_KERNELS`, else `auto`."""
    per = os.environ.get("DL4J_TPU_KERNEL_" + kernel.upper())
    if per:
        if per not in MODES:
            raise ValueError(
                f"DL4J_TPU_KERNEL_{kernel.upper()}={per!r}: want one of {MODES}")
        return per, "DL4J_TPU_KERNEL_" + kernel.upper()
    glob = os.environ.get("DL4J_TPU_KERNELS")
    if glob:
        if glob not in MODES:
            raise ValueError(
                f"DL4J_TPU_KERNELS={glob!r}: want one of {MODES}")
        return glob, "DL4J_TPU_KERNELS"
    return "auto", "default"


def config_key() -> Tuple:
    """The kernel-selection identity of the process env: folded into every
    jit-cache key (`nn/jit_cache.py`) so a knob flip can never reuse a
    program traced under a different selection."""
    return tuple((k, mode_for(k)[0]) for k in kernel_names())


def config_fingerprint() -> dict:
    """JSON-able form of `config_key()` for the AOT fingerprint document
    (`compilation/store.py::build_fingerprint_doc`)."""
    return {k: mode_for(k)[0] for k in kernel_names()}


def probe_count() -> int:
    """Total `is_available` probe invocations this process — the hoisting
    contract (tests): repeated same-signature blocks add ZERO probes."""
    return _PROBES


def clear_cache() -> None:
    """Drop the resolution memo (tests flip env knobs between asserts)."""
    with _LOCK:
        _MEMO.clear()


def _probe(impl: KernelImpl, backend, shapes, dtypes, meta, forced) -> Tuple[bool, str]:
    global _PROBES
    _PROBES += 1
    return impl.is_available(backend, shapes, dtypes, meta=meta, forced=forced)


def _count_dispatch(kernel: str, impl: str) -> None:
    child = _DISPATCH_CHILDREN.get((kernel, impl))
    if child is None:
        child = _DISPATCH_CHILDREN.setdefault(
            (kernel, impl), _M_DISPATCH.labels(kernel=kernel, impl=impl))
    child.inc()


def _default_backend() -> str:
    import jax

    return jax.default_backend()


def resolve(kernel: str, *, backend: Optional[str] = None,
            shapes: Tuple = (), dtypes: Tuple = (), meta: Tuple = ()) -> Resolution:
    """Pick the implementation for `kernel` under the current env mode.

    `shapes`/`dtypes`/`meta` are hashable tuples describing the call
    signature (layer dims, leaf dtypes, activation names, ...); they key
    the memo together with (kernel, mode, backend), so resolution — and
    its `is_available` probes — runs once per distinct jit signature.
    Called at trace time only; the result feeds static Python dispatch,
    never a traced value."""
    if backend is None:
        backend = _default_backend()
    _ensure(kernel)
    mode, source = mode_for(kernel)
    key = (kernel, mode, backend, shapes, dtypes, meta)
    with _LOCK:
        res = _MEMO.get(key)
    if res is None:
        res = _resolve_uncached(kernel, mode, source, backend, shapes,
                                dtypes, meta)
        with _LOCK:
            res = _MEMO.setdefault(key, res)
    _count_dispatch(kernel, res.impl)
    return res


def _resolve_uncached(kernel, mode, source, backend, shapes, dtypes,
                      meta) -> Resolution:
    candidates = _REGISTRY[kernel]
    note = ""
    if mode != "auto":
        forced = next((c for c in candidates if c.name == mode), None)
        if forced is not None:
            ok, reason = _probe(forced, backend, shapes, dtypes, meta,
                                forced=True)
            if ok:
                return Resolution(kernel, mode,
                                  f"forced via {source}: {reason}")
            note = f"{mode} forced via {source} but unavailable ({reason}); "
        else:
            note = f"{mode} forced via {source} but not a candidate; "
    last = None
    for c in candidates:
        ok, reason = _probe(c, backend, shapes, dtypes, meta, forced=False)
        last = Resolution(kernel, c.name, note + reason)
        if ok:
            return last
    # No candidate available (should not happen: every kernel registers an
    # unconditional XLA fallback) — surface the last probe's reason.
    return last


def probe(kernel: str, *, backend: Optional[str] = None, shapes: Tuple = (),
          dtypes: Tuple = (), meta: Tuple = ()):
    """Dry-run every candidate of `kernel` at a hypothetical signature —
    the ``--probe`` CLI's payload for debugging forced-kernel rollouts.

    Unlike `resolve()` this is NOT memoized and probes ALL candidates
    (each with `forced=True` when the active mode names it, mirroring
    `_resolve_uncached`'s semantics), so the report shows the refusal
    reason per candidate, not just the winner. No jit, no trace — pure
    availability checks. Returns ``(selected_impl, rows)``."""
    if backend is None:
        backend = _default_backend()
    _ensure(kernel)
    mode, source = mode_for(kernel)
    rows = []
    for c in _REGISTRY[kernel]:
        forced = mode == c.name
        ok, reason = c.is_available(backend, shapes, dtypes, meta=meta,
                                    forced=forced)
        rows.append({"impl": c.name, "available": bool(ok),
                     "forced": forced, "reason": reason})
    selected = None
    if mode != "auto":
        selected = next((r["impl"] for r in rows
                         if r["impl"] == mode and r["available"]), None)
    if selected is None:
        selected = next((r["impl"] for r in rows if r["available"]),
                        rows[-1]["impl"] if rows else None)
    return selected, rows


def describe(backend: Optional[str] = None):
    """Resolution table for every registered kernel at a generic (shapeless)
    signature — the CLI's payload and the smoke tests' hook."""
    rows = []
    for name in kernel_names():
        mode, source = mode_for(name)
        res = resolve(name, backend=backend)
        rows.append({"kernel": name, "mode": mode, "mode_source": source,
                     "impl": res.impl, "reason": res.reason})
    return rows
