"""Fused normalize + affine + activation for BatchNorm / LayerNorm.

`nn/layers/normalization.py` computes batch statistics (a reduction XLA
already does well, and whose single-pass form is part of the bit-
exactness contract) and then runs an elementwise chain — normalize,
scale/shift, activation — that re-reads the activation tensor from HBM
between fusion boundaries. The Pallas path runs that chain in one VMEM
pass over the `[rows, features]` view: BatchNorm takes the (XLA-computed)
mean/var as operands; LayerNorm computes its per-row stats in-kernel.

The XLA fallbacks are the LITERAL pre-registry expressions moved here
verbatim — same ops, same order — so `DL4J_TPU_KERNELS=xla` (and auto
off-TPU) produces bit-identical jaxprs to the pre-PR layers.

Availability (auto): TPU backend, float32 or bfloat16, activation in the
in-kernel set, feature dim a lane (128) multiple and row count a sublane
(8) multiple. Forced `pallas` keeps the structural constraints and runs
interpret mode off-TPU (the CPU parity tests' path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.kernels import registry

_ACTS = {
    "identity": lambda x: x,
    "relu": lambda x: jnp.maximum(x, 0.0),
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}


def _pallas_available(backend, shapes, dtypes, meta=(), forced=False):
    m = dict(meta)
    act = m.get("act")
    if act is not None and act not in _ACTS:
        return False, f"activation {act!r} not expressible in-kernel"
    if dtypes and not set(dtypes) <= {"float32", "bfloat16"}:
        return False, f"dtype {sorted(set(dtypes))} not in (float32, bfloat16)"
    if forced and backend != "tpu":
        return True, "forced (interpret mode off-TPU)"
    if backend != "tpu":
        return False, (f"Pallas norm+act needs the TPU backend, have "
                       f"{backend} (DL4J_TPU_KERNEL_NORM_ACT=pallas forces "
                       "interpret mode)")
    if not shapes:
        return True, "TPU backend (shapes unknown: assumed tile-aligned)"
    rows, feats = shapes
    if feats % 128 or rows % 8:
        return False, (f"rows={rows}, features={feats} not tile-aligned "
                       "(need features % 128 == 0 and rows % 8 == 0)")
    return True, ("forced (TPU, tile-aligned)" if forced
                  else "TPU fused normalize+affine+activation")


def _xla_available(backend, shapes, dtypes, meta=(), forced=False):
    return True, "XLA elementwise chain (bit-identical to the pre-registry layers)"


registry.register("norm_act", [
    registry.KernelImpl("pallas", _pallas_available),
    registry.KernelImpl("xla", _xla_available),
])


# ------------------------------------------------------- XLA fallbacks
# Moved VERBATIM from nn/layers/normalization.py (bit-exactness contract).


def batchnorm_xla(x, mean, var, gamma, beta, eps, activation):
    from deeplearning4j_tpu.nn import activations

    xhat = (x - mean) / jnp.sqrt(var + eps)
    out = gamma * xhat + beta
    return activations.resolve(activation)(out)


def layernorm_xla(x, gamma, beta, eps, activation):
    from deeplearning4j_tpu.nn import activations

    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    out = out * gamma + beta
    return activations.resolve(activation)(out)


# -------------------------------------------------------- Pallas path


def _bn_kernel(eps, act_name, x_ref, mu_ref, var_ref, g_ref, b_ref, o_ref):
    xhat = (x_ref[...] - mu_ref[...]) / jnp.sqrt(var_ref[...] + eps)
    o_ref[...] = _ACTS[act_name](g_ref[...] * xhat + b_ref[...])


def _ln_kernel(eps, act_name, x_ref, g_ref, b_ref, o_ref):
    x = x_ref[...]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + eps)
    o_ref[...] = _ACTS[act_name](out * g_ref[...] + b_ref[...])


@functools.lru_cache(maxsize=64)
def _norm_call(op: str, rows: int, feats: int, eps: float, act_name: str,
               dtype: str, interpret: bool):
    from jax.experimental import pallas as pl

    body = functools.partial(
        _bn_kernel if op == "batchnorm" else _ln_kernel, eps, act_name)
    return pl.pallas_call(
        body, out_shape=jax.ShapeDtypeStruct((rows, feats), jnp.dtype(dtype)),
        interpret=interpret)


def _row_view(a):
    """Feature-last tensors of any rank as [rows, features]."""
    return a.reshape(-1, a.shape[-1])


def _vec(v, feats, dtype):
    """gamma/beta/mean/var as a broadcastable [1, features] row — scalars
    (the `lock_gamma_beta` constants) are materialized."""
    return jnp.broadcast_to(jnp.asarray(v, dtype), (feats,)).reshape(1, feats)


def _signature(op, x, activation):
    rows = 1
    for d in x.shape[:-1]:
        rows *= int(d)
    return dict(shapes=(rows, int(x.shape[-1])), dtypes=(str(x.dtype),),
                meta=(("op", op), ("act", str(activation))))


def batchnorm_norm_act(x, mean, var, gamma, beta, eps, activation):
    """`nn/layers/normalization.py::batchnorm_apply`'s seam: normalize
    with the given (already-reduced) statistics, apply scale/shift, then
    the conf activation."""
    res = registry.resolve("norm_act", **_signature("batchnorm", x, activation))
    if res.impl != "pallas":
        return batchnorm_xla(x, mean, var, gamma, beta, eps, activation)
    from deeplearning4j_tpu.kernels import _diff

    feats = x.shape[-1]
    call = _norm_call("batchnorm", _row_view(x).shape[0], int(feats),
                      float(eps), str(activation), str(x.dtype),
                      interpret=jax.default_backend() != "tpu")
    # Pallas forward, XLA-reference backward: the seam sits inside the
    # engines' value_and_grad (kernels/_diff.py).
    f = _diff.pallas_fwd_ref_bwd(
        call, lambda xv, mu, vr, g, b: batchnorm_xla(xv, mu, vr, g, b,
                                                     eps, activation))
    out = f(_row_view(x), _vec(mean, feats, x.dtype),
            _vec(var, feats, x.dtype), _vec(gamma, feats, x.dtype),
            _vec(beta, feats, x.dtype))
    return out.reshape(x.shape)


def layernorm_norm_act(x, gamma, beta, eps, activation):
    """`nn/layers/normalization.py::layernorm_apply`'s seam: per-row stats
    + normalize + affine + activation."""
    res = registry.resolve("norm_act", **_signature("layernorm", x, activation))
    if res.impl != "pallas":
        return layernorm_xla(x, gamma, beta, eps, activation)
    from deeplearning4j_tpu.kernels import _diff

    feats = x.shape[-1]
    call = _norm_call("layernorm", _row_view(x).shape[0], int(feats),
                      float(eps), str(activation), str(x.dtype),
                      interpret=jax.default_backend() != "tpu")
    f = _diff.pallas_fwd_ref_bwd(
        call, lambda xv, g, b: layernorm_xla(xv, g, b, eps, activation))
    out = f(_row_view(x), _vec(gamma, feats, x.dtype),
            _vec(beta, feats, x.dtype))
    return out.reshape(x.shape)
