"""Accelerated-kernel helper layer (Pallas) behind one dispatch seam.

JAX-port equivalent of the reference's per-backend helper discovery
(`ConvolutionHelper`/`LSTMHelper`, PAPER.md layer 1): `registry.py` maps
kernel names to ordered candidates — a Pallas TPU implementation and a
bit-stable XLA fallback that is the literal pre-registry inline code —
and resolves once per jit signature. Kernels:

- ``lstm_cell``       — fused LSTM cell (recurrent matmul + gates + state
                        update), the `nn/layers/recurrent.py::_lstm_scan`
                        body for standard/Graves/bidirectional paths;
- ``fused_update``    — Adam/Nesterov/RMSProp over the stacked flattened
                        param leaves in one elementwise kernel
                        (`ops/updaters.py`, superstep carry);
- ``norm_act``        — BatchNorm/LayerNorm normalize+affine+activation
                        (`nn/layers/normalization.py`);
- ``flash_attention`` — the PERF.md §6 flash kernel, migrated here from
                        `ops/flash_attention.py` (shim kept);
- ``bottleneck_block``— the fused ResNet bottleneck chain (conv1x1/BN/act
                        x3 + residual in one VMEM residency, PERF.md §27),
                        `nn/layers/bottleneck.py`'s seam, with an
                        int8-weight inference variant for serving.

`DL4J_TPU_KERNELS=auto|xla|pallas` (+ per-kernel
`DL4J_TPU_KERNEL_<NAME>`) select the mode; `python -m
deeplearning4j_tpu.kernels` lists what resolved and why. tpulint JX010
keeps Pallas imports confined to this package. PERF.md §19 documents the
design, fallback matrix, and parity/bench methodology.
"""

from __future__ import annotations

from deeplearning4j_tpu.kernels import registry
from deeplearning4j_tpu.kernels.registry import (
    KernelImpl,
    Resolution,
    config_fingerprint,
    config_key,
    describe,
    kernel_names,
    probe_count,
    resolve,
)

__all__ = [
    "registry", "KernelImpl", "Resolution", "config_fingerprint",
    "config_key", "describe", "kernel_names", "probe_count", "resolve",
]
