"""Fused ResNet bottleneck block: conv1x1 -> BN+act -> conv3x3 -> BN+act
-> conv1x1 -> BN -> residual add -> act, one registry-dispatched unit.

PERF.md SS2/SS3: resnet50 training sits at its HBM roofline (~0.281 MFU)
because every sub-layer of the bottleneck writes its activation back to
HBM just for the next sub-layer to read it again. The Pallas path runs
the whole chain in one VMEM residency per block: the conv1x1s are
channel matmuls on the MXU, the SAME-padded 3x3 is nine shifted matmuls,
normalization reuses the `norm_act` kernel's normalize/scale/shift/act
machinery in-register, and the residual never round-trips. Batch stats
are emitted as side outputs in train mode (f32, computed in-kernel) so
the EMA update stays engine-side in `nn/layers/bottleneck.py` — training
semantics are untouched.

The XLA fallback is the unfused vertex chain moved here verbatim — the
same `lax.conv_general_dilated` calls, the same single-pass stats, the
normalize going through `norm_act.batchnorm_norm_act`'s own seam — so
`DL4J_TPU_KERNELS=xla` is bit-identical to a resnet built from per-layer
vertices, and it doubles as the VJP reference via `kernels/_diff.py`
(forced-pallas nets train with the fallback's gradient math).

Inference additionally supports int8 weights (per-channel `__scale`
siblings, PR 8 convention): `nn/params.py::prep_layer_params` passes the
quantized leaves through untouched for this layer, the Pallas body
dequantizes in-register (`q.astype(f32) * scale`), so the serving tier
moves one byte per weight instead of four. Training on int8 weights is
refused structurally.

Availability (auto): TPU backend, float32/bfloat16 activations, conf
activation in the `norm_act` in-kernel set, and the block's working set
(whole batch for train, one image per grid step for inference) within
the VMEM budget. Forced `pallas` runs interpret mode off-TPU — the CPU
parity tests' path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.kernels import registry
from deeplearning4j_tpu.kernels import norm_act as _norm

_ACTS = _norm._ACTS  # normalize/scale/shift/act machinery is shared

_VMEM_BUDGET = 12 * 1024 * 1024  # same headroom convention as lstm_cell

_BRANCHES = ("a", "b", "c")
_STAT_KEYS = ("mean_a", "var_a", "mean_b", "var_b", "mean_c", "var_c")
_STAT_KEYS_PROJ = _STAT_KEYS + ("mean_proj", "var_proj")


def _working_set_bytes(b, h, w, cin, f1, f3, sh, sw, project):
    """f32 elements resident at once in one kernel invocation (coarse:
    input + each intermediate + weights; bf16 inputs still compute f32)."""
    ho, wo = -(-h // sh), -(-w // sw)
    acts = b * h * w * cin + b * ho * wo * (cin + 3 * f1 + 3 * f3)
    weights = cin * f1 + 9 * f1 * f1 + f1 * f3 + (cin * f3 if project else 0)
    return 4 * (acts + weights)


def _pallas_available(backend, shapes, dtypes, meta=(), forced=False):
    m = dict(meta)
    act = m.get("act")
    if act is not None and act not in _ACTS:
        return False, f"activation {act!r} not expressible in-kernel"
    if m.get("int8") and m.get("train"):
        return False, "int8 weights are inference-only (no quantized grads)"
    fdts = set(dtypes) - {"int8"}
    if fdts and not fdts <= {"float32", "bfloat16"}:
        return False, f"dtype {sorted(fdts)} not in (float32, bfloat16)"
    if forced and backend != "tpu":
        return True, "forced (interpret mode off-TPU)"
    if backend != "tpu":
        return False, ("Pallas bottleneck block needs the TPU backend, have "
                       f"{backend} (DL4J_TPU_KERNEL_BOTTLENECK_BLOCK=pallas "
                       "forces interpret mode)")
    if not shapes:
        return True, "TPU backend (shapes unknown: assumed within VMEM budget)"
    b, h, w, cin, f1, f3, sh, sw = shapes
    train = bool(m.get("train"))
    need = _working_set_bytes(b if train else 1, h, w, cin, f1, f3, sh, sw,
                              bool(m.get("project")))
    if need > _VMEM_BUDGET:
        return False, (f"block working set ~{need / 2**20:.1f} MB exceeds the "
                       f"{_VMEM_BUDGET / 2**20:.0f} MB VMEM budget "
                       f"({'whole-batch train' if train else 'per-image'} "
                       "residency)")
    return True, ("forced (TPU, fits VMEM)" if forced
                  else "TPU fused bottleneck chain")


def _xla_available(backend, shapes, dtypes, meta=(), forced=False):
    return True, ("XLA per-layer composite (bit-identical to the unfused "
                  "bottleneck vertices)")


registry.register("bottleneck_block", [
    registry.KernelImpl("pallas", _pallas_available),
    registry.KernelImpl("xla", _xla_available),
])


# ------------------------------------------------------- XLA fallback
# The unfused vertex chain moved VERBATIM: `_conv` is
# nn/layers/convolution.py::conv2d_apply's call (no bias, SAME mode —
# models/resnet.py::_conv_bn builds exactly that), `_bn_stats` is
# nn/layers/normalization.py::batchnorm_apply's single-pass stats, and
# normalization goes through norm_act's own dispatch seam, so the
# fallback inherits that kernel's behaviour too (bit-exactness contract).


def _conv(x, w, stride):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=stride, padding="SAME",
        rhs_dilation=(1, 1), dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn_stats(x):
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes)
    var = jnp.mean(x * x, axis=axes) - mean * mean
    return mean, var


def xla_train(x, wa, ga, ba, wb, gb, bb, wc, gc, bc, wp, gp, bp,
              *, stride, eps, act):
    """Train-mode composite: returns (y, stats) where stats is the flat
    (mean_a, var_a, ..) tuple; the caller owns the EMA. Pass wp/gp/bp as
    None for the identity shortcut."""
    from deeplearning4j_tpu.nn import activations

    a = _conv(x, wa, stride)
    ma, va = _bn_stats(a)
    a = _norm.batchnorm_norm_act(a, ma, va, ga, ba, eps, act)
    h = _conv(a, wb, (1, 1))
    mb, vb = _bn_stats(h)
    h = _norm.batchnorm_norm_act(h, mb, vb, gb, bb, eps, act)
    c = _conv(h, wc, (1, 1))
    mc, vc = _bn_stats(c)
    c = _norm.batchnorm_norm_act(c, mc, vc, gc, bc, eps, "identity")
    stats = (ma, va, mb, vb, mc, vc)
    if wp is None:
        shortcut = x
    else:
        p = _conv(x, wp, stride)
        mp, vp = _bn_stats(p)
        shortcut = _norm.batchnorm_norm_act(p, mp, vp, gp, bp, eps, "identity")
        stats = stats + (mp, vp)
    return activations.resolve(act)(c + shortcut), stats


def xla_infer(x, wa, ga, ba, wb, gb, bb, wc, gc, bc, wp, gp, bp, stats,
              *, stride, eps, act):
    """Inference composite: `stats` is the running-stat dict from the
    layer state (same chain as xla_train, given statistics)."""
    from deeplearning4j_tpu.nn import activations

    a = _conv(x, wa, stride)
    a = _norm.batchnorm_norm_act(a, stats["mean_a"], stats["var_a"],
                                 ga, ba, eps, act)
    h = _conv(a, wb, (1, 1))
    h = _norm.batchnorm_norm_act(h, stats["mean_b"], stats["var_b"],
                                 gb, bb, eps, act)
    c = _conv(h, wc, (1, 1))
    c = _norm.batchnorm_norm_act(c, stats["mean_c"], stats["var_c"],
                                 gc, bc, eps, "identity")
    if wp is None:
        shortcut = x
    else:
        p = _conv(x, wp, stride)
        shortcut = _norm.batchnorm_norm_act(
            p, stats["mean_proj"], stats["var_proj"], gp, bp, eps, "identity")
    return activations.resolve(act)(c + shortcut)


# -------------------------------------------------------- Pallas path
# All in-kernel math is f32 (matmuls via preferred_element_type on the
# MXU); the activation output is cast back to the input dtype, batch
# stats stay f32. Train runs the whole batch in one block so the stats
# reduce in-kernel; inference grids over the batch (one image per step)
# so real serving shapes fit VMEM, with running stats as operands.


def _in_kernel_norm(v, mean, var, gamma, beta, eps, act):
    # norm_act._bn_kernel's expression, on values instead of refs.
    xhat = (v - mean) / jnp.sqrt(var + eps)
    return _ACTS[act](gamma * xhat + beta)


def _f32(ref):
    return ref[...].astype(jnp.float32)


def _load_w(ref, scale_ref):
    """Weight load, dequantizing int8 in-register when a per-channel
    scale operand is present (quantize.py contract: scale over the last
    axis, `q.astype(f32) * scale`)."""
    w = _f32(ref)
    if scale_ref is not None:
        w = w * scale_ref[...].reshape(1, -1) if w.ndim == 2 \
            else w * scale_ref[...].reshape(1, 1, 1, -1)
    return w


def _conv1x1(x, w, sh, sw):
    return jnp.dot(x[:, ::sh, ::sw, :], w, preferred_element_type=jnp.float32)


def _conv3x3_same(x, w):
    ho, wo = x.shape[1], x.shape[2]
    pad = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    out = jnp.zeros(x.shape[:3] + (w.shape[-1],), jnp.float32)
    for i in range(3):
        for j in range(3):
            out = out + jnp.dot(pad[:, i:i + ho, j:j + wo, :], w[i, j],
                                preferred_element_type=jnp.float32)
    return out


def _kernel_stats(v):
    mean = jnp.mean(v, axis=(0, 1, 2))
    var = jnp.mean(v * v, axis=(0, 1, 2)) - mean * mean
    return mean, var


def _train_body(sh, sw, eps, act, project, x_ref, *refs):
    nw = 12 if project else 9
    win, outs = refs[:nw], refs[nw:]
    (wa, ga, ba, wb, gb, bb, wc, gc, bc) = win[:9]
    x = _f32(x_ref)

    a = _conv1x1(x, _f32(wa), sh, sw)
    ma, va = _kernel_stats(a)
    a = _in_kernel_norm(a, ma, va, _f32(ga), _f32(ba), eps, act)
    h = _conv3x3_same(a, _f32(wb))
    mb, vb = _kernel_stats(h)
    h = _in_kernel_norm(h, mb, vb, _f32(gb), _f32(bb), eps, act)
    c = _conv1x1(h, _f32(wc), 1, 1)
    mc, vc = _kernel_stats(c)
    c = _in_kernel_norm(c, mc, vc, _f32(gc), _f32(bc), eps, "identity")
    stats = [ma, va, mb, vb, mc, vc]
    if project:
        wp, gp, bp = win[9:]
        p = _conv1x1(x, _f32(wp), sh, sw)
        mp, vp = _kernel_stats(p)
        shortcut = _in_kernel_norm(p, mp, vp, _f32(gp), _f32(bp), eps,
                                   "identity")
        stats += [mp, vp]
    else:
        shortcut = x

    y_ref = outs[0]
    y_ref[...] = _ACTS[act](c + shortcut).astype(y_ref.dtype)
    for ref, s in zip(outs[1:], stats):
        ref[...] = s.reshape(1, -1)


def _infer_body(sh, sw, eps, act, project, int8, x_ref, *refs):
    # Per-branch operand groups: (w, [scale], gamma, beta, mean, var).
    per = 6 if int8 else 5
    groups = [refs[i * per:(i + 1) * per]
              for i in range(4 if project else 3)]
    y_ref = refs[per * (4 if project else 3)]

    def unpack(g):
        if int8:
            w, s, gm, bt, mu, vr = g
            return _load_w(w, s), _f32(gm), _f32(bt), _f32(mu), _f32(vr)
        w, gm, bt, mu, vr = g
        return _load_w(w, None), _f32(gm), _f32(bt), _f32(mu), _f32(vr)

    x = _f32(x_ref)
    wa, ga, ba, ma, va = unpack(groups[0])
    a = _in_kernel_norm(_conv1x1(x, wa, sh, sw), ma, va, ga, ba, eps, act)
    wb, gb, bb, mb, vb = unpack(groups[1])
    h = _in_kernel_norm(_conv3x3_same(a, wb), mb, vb, gb, bb, eps, act)
    wc, gc, bc, mc, vc = unpack(groups[2])
    c = _in_kernel_norm(_conv1x1(h, wc, 1, 1), mc, vc, gc, bc, eps,
                        "identity")
    if project:
        wp, gp, bp, mp, vp = unpack(groups[3])
        shortcut = _in_kernel_norm(_conv1x1(x, wp, sh, sw), mp, vp, gp, bp,
                                   eps, "identity")
    else:
        shortcut = x
    y_ref[...] = _ACTS[act](c + shortcut).astype(y_ref.dtype)


@functools.lru_cache(maxsize=32)
def _train_call(b, h, w, cin, f1, f3, sh, sw, eps, act, project, xdtype,
                interpret):
    from jax.experimental import pallas as pl

    ho, wo = -(-h // sh), -(-w // sw)
    stat_dims = (f1, f1, f1, f1, f3, f3) + ((f3, f3) if project else ())
    outs = [jax.ShapeDtypeStruct((b, ho, wo, f3), jnp.dtype(xdtype))]
    outs += [jax.ShapeDtypeStruct((1, d), jnp.float32) for d in stat_dims]
    body = functools.partial(_train_body, sh, sw, eps, act, project)
    return pl.pallas_call(body, out_shape=outs, interpret=interpret)


@functools.lru_cache(maxsize=32)
def _infer_call(b, h, w, cin, f1, f3, sh, sw, eps, act, project, int8,
                xdtype, interpret):
    from jax.experimental import pallas as pl

    ho, wo = -(-h // sh), -(-w // sw)

    def full(shape):
        nd = len(shape)
        return pl.BlockSpec(shape, lambda i, _n=nd: (0,) * _n)

    branch_dims = [(cin, f1), (f1, f1), (f1, f3)]
    if project:
        branch_dims.append((cin, f3))
    in_specs = [pl.BlockSpec((1, h, w, cin), lambda i: (i, 0, 0, 0))]
    for bi, (ci, fo) in enumerate(branch_dims):
        wshape = (3, 3, f1, f1) if bi == 1 else (ci, fo)
        in_specs.append(full(wshape))               # weight
        if int8:
            in_specs.append(full((1, fo)))          # __scale
        in_specs += [full((1, fo))] * 4             # gamma, beta, mean, var
    body = functools.partial(_infer_body, sh, sw, eps, act, project, int8)
    return pl.pallas_call(
        body,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, ho, wo, f3), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, ho, wo, f3), jnp.dtype(xdtype)),
        interpret=interpret)


# ----------------------------------------------------- dispatch seam


def _branches(project):
    return _BRANCHES + (("proj",) if project else ())


def stat_keys(project):
    return _STAT_KEYS_PROJ if project else _STAT_KEYS


def _dequant(q, scale, dtype):
    # prep_layer_params' exact dequant expression (bit-for-bit the PR 8
    # serving contract) for paths that can't keep int8 in-kernel.
    return q.astype(dtype) * scale.astype(dtype)


def _signature(x, f1, f3, stride, train, project, act, int8):
    b, h, w, cin = (int(d) for d in x.shape)
    dtypes = (str(x.dtype),) + (("int8",) if int8 else ())
    return dict(shapes=(b, h, w, cin, int(f1), int(f3),
                        int(stride[0]), int(stride[1])),
                dtypes=dtypes,
                meta=(("train", bool(train)), ("project", bool(project)),
                      ("act", str(act)), ("int8", bool(int8))))


def bottleneck_forward(x, params, state, *, stride, project, eps,
                       activation, train):
    """`nn/layers/bottleneck.py::bottleneck_apply`'s seam. Returns
    `(y, stats)`: stats is the batch-stat dict (keyed like the state) in
    train mode, None in inference — the EMA update stays in the layer."""
    eps, act = float(eps), str(activation)
    names = _branches(project)
    qscales = {n: params.get(f"W_{n}__scale") for n in names}
    int8 = all(params[f"W_{n}"].dtype == jnp.int8 and qscales[n] is not None
               for n in names)
    if train and int8:
        raise ValueError(
            "bottleneck_block: training on int8 weights is unsupported "
            "(quantized checkpoints are inference-only)")
    weights = {}
    for n in names:
        wq = params[f"W_{n}"]
        if not int8 and wq.dtype == jnp.int8:
            wq = _dequant(wq, qscales[n], x.dtype)  # mixed trees: engine-side
        weights[n] = wq
    f1 = int(weights["a"].shape[-1])
    f3 = int(weights["c"].shape[-1])
    res = registry.resolve(
        "bottleneck_block",
        **_signature(x, f1, f3, stride, train, project, act, int8))

    if res.impl != "pallas":
        wflat = []
        for n in names:
            wv = weights[n]
            if int8:
                wv = _dequant(wv, qscales[n], x.dtype)
            wflat += [wv, params[f"gamma_{n}"], params[f"beta_{n}"]]
        if not project:
            wflat += [None, None, None]
        if train:
            y, stats = xla_train(x, *wflat, stride=tuple(stride), eps=eps,
                                 act=act)
            return y, dict(zip(stat_keys(project), stats))
        return xla_infer(x, *wflat, state, stride=tuple(stride), eps=eps,
                         act=act), None

    from deeplearning4j_tpu.kernels import _diff

    interpret = jax.default_backend() != "tpu"
    b, h, w, cin = (int(d) for d in x.shape)
    sh, sw = int(stride[0]), int(stride[1])

    def row(v, feats):
        return jnp.broadcast_to(
            jnp.asarray(v, jnp.float32), (int(feats),)).reshape(1, -1)

    if train:
        call = _train_call(b, h, w, cin, f1, f3, sh, sw, eps, act,
                           bool(project), str(x.dtype), interpret)
        nstat = len(stat_keys(project))

        def pallas_fn(xv, *wflat):
            # HWIO 1x1 kernels flatten to channel matmuls; gamma/beta
            # ride as (1, F) rows (norm_act._vec convention).
            kin = []
            for bi, n in enumerate(names):
                wv, gv, bv = wflat[3 * bi:3 * bi + 3]
                feats = wv.shape[-1]
                if n != "b":
                    wv = wv.reshape(wv.shape[-2], feats)
                kin += [wv, row(gv, feats), row(bv, feats)]
            out = call(xv, *kin)
            return out[0], tuple(s.reshape(-1) for s in out[1:1 + nstat])

        def ref_fn(xv, *wflat):
            pad = wflat if project else wflat + (None, None, None)
            y, stats = xla_train(xv, *pad, stride=(sh, sw), eps=eps, act=act)
            # Match the Pallas output pytree: stats are (F,) f32 (they
            # only feed the EMA — value semantics, no gradient path).
            return y, tuple(s.astype(jnp.float32) for s in stats)

        args = []
        for n in names:
            args += [weights[n], params[f"gamma_{n}"], params[f"beta_{n}"]]
        y, stats = _diff.pallas_fwd_ref_bwd(pallas_fn, ref_fn)(x, *args)
        return y, dict(zip(stat_keys(project), stats))

    call = _infer_call(b, h, w, cin, f1, f3, sh, sw, eps, act,
                       bool(project), int8, str(x.dtype), interpret)

    def kernel_inputs(xv, *wflat):
        kin = []
        for bi, n in enumerate(names):
            wv, gv, bv = wflat[3 * bi:3 * bi + 3]
            feats = int(f1 if n in ("a", "b") else f3)
            if n != "b":
                wv = wv.reshape(wv.shape[-2], feats)
            kin.append(wv)
            if int8:
                kin.append(row(qscales[n], feats))
            kin += [row(gv, feats), row(bv, feats),
                    row(state[f"mean_{n}"], feats),
                    row(state[f"var_{n}"], feats)]
        return call(xv, *kin)

    args = []
    for n in names:
        args += [weights[n], params[f"gamma_{n}"], params[f"beta_{n}"]]
    if int8:
        # int8 weights carry no gradients; call the kernel directly.
        return kernel_inputs(x, *args), None

    def ref_fn(xv, *wflat):
        pad = wflat if project else wflat + (None, None, None)
        return xla_infer(xv, *pad, state, stride=(sh, sw), eps=eps, act=act)

    return _diff.pallas_fwd_ref_bwd(kernel_inputs, ref_fn)(x, *args), None
