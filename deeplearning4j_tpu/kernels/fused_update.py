"""Fused optimizer update: one elementwise kernel over stacked leaves.

`ops/updaters.py` applies Adam/Nesterov/RMSProp with one
`jax.tree_util.tree_map` per state field — per-leaf ops that XLA mostly
fuses, but each leaf is its own kernel launch chain and small leaves
(biases, norm scales) never saturate a lane. The Pallas path ravels the
gradient/state pytrees into single flat vectors (`ravel_pytree`), pads to
an (8, 128) tile multiple, and runs ONE elementwise kernel producing the
new state vectors and the delta vector, which is then unraveled back to
the param tree — the superstep carry (`nn/superstep.py`) threads through
this exact seam, so all K fused iterations share one update kernel per
step.

The XLA fallbacks below are the LITERAL pre-registry `ops/updaters.py`
bodies moved here verbatim (bit-exactness contract): same tree_maps, same
bias-correction branch, so `DL4J_TPU_KERNELS=xla` (and auto off-TPU)
trains bit-identically to the pre-PR engines. Hyperparameters stay
Python floats baked into the trace; `lr`/`step` may be traced scalars and
are passed into the kernel as a tiny (1, 3) operand.

Scope: `adam`, `nesterovs`, `rmsprop` (the issue's set). Other updaters
never enter the seam. Mixed-dtype or non-float32 trees fall back.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from deeplearning4j_tpu.kernels import registry

_KINDS = ("adam", "nesterovs", "rmsprop")
_TILE = 8 * 128


def _pallas_available(backend, shapes, dtypes, meta=(), forced=False):
    m = dict(meta)
    kind = m.get("kind")
    if kind is None and backend == "tpu":
        # Generic (shapeless) probe, e.g. the CLI: the fused path exists
        # for the _KINDS set; per-signature probes decide per updater.
        return True, f"TPU fused update for {'/'.join(_KINDS)}"
    if kind not in _KINDS:
        return False, f"updater {kind!r} has no fused kernel (fused: {_KINDS})"
    if shapes == () and dtypes == ():
        return False, "empty gradient tree"
    if dtypes and any(d != "float32" for d in set(dtypes)):
        return False, f"non-float32 leaves {sorted(set(dtypes))}"
    if forced:
        return True, ("forced" + ("" if backend == "tpu"
                                  else " (interpret mode off-TPU)"))
    if backend != "tpu":
        return False, (f"Pallas fused update needs the TPU backend, have "
                       f"{backend} (DL4J_TPU_KERNEL_FUSED_UPDATE=pallas "
                       "forces interpret mode)")
    return True, "TPU fused elementwise update over stacked flat leaves"


def _xla_available(backend, shapes, dtypes, meta=(), forced=False):
    return True, "per-leaf tree_map (bit-identical to the pre-registry code)"


registry.register("fused_update", [
    registry.KernelImpl("pallas", _pallas_available),
    registry.KernelImpl("xla", _xla_available),
])


# ------------------------------------------------------- XLA fallbacks
# Moved VERBATIM from ops/updaters.py — the op order is the bit-exactness
# contract with the pre-registry engines.


def adam_xla(state, grads, lr, step, beta1, beta2, eps):
    t = step + 1
    m = jax.tree_util.tree_map(lambda m0, g: beta1 * m0 + (1 - beta1) * g, state["m"], grads)
    v = jax.tree_util.tree_map(lambda v0, g: beta2 * v0 + (1 - beta2) * g * g, state["v"], grads)
    bc1 = 1.0 - beta1 ** t.astype(jnp.float32) if hasattr(t, "astype") else 1.0 - beta1 ** t
    bc2 = 1.0 - beta2 ** t.astype(jnp.float32) if hasattr(t, "astype") else 1.0 - beta2 ** t
    deltas = jax.tree_util.tree_map(
        lambda m1, v1: lr * (m1 / bc1) / (jnp.sqrt(v1 / bc2) + eps), m, v
    )
    return {"m": m, "v": v}, deltas


def nesterovs_xla(state, grads, lr, step, momentum):
    v_prev = state["v"]
    v = jax.tree_util.tree_map(lambda v0, g: momentum * v0 - lr * g, v_prev, grads)
    # ND4J semantics: applied update = -(mu*vPrev) + (1+mu)*v, negated here
    # because the caller subtracts deltas.
    deltas = jax.tree_util.tree_map(
        lambda v0, v1: momentum * v0 - (1.0 + momentum) * v1, v_prev, v
    )
    return {"v": v}, deltas


def rmsprop_xla(state, grads, lr, step, decay, eps):
    g2 = jax.tree_util.tree_map(lambda a, g: decay * a + (1 - decay) * g * g, state["g2"], grads)
    deltas = jax.tree_util.tree_map(lambda a, g: lr * g / jnp.sqrt(a + eps), g2, grads)
    return {"g2": g2}, deltas


# -------------------------------------------------------- Pallas path


def _adam_kernel(beta1, beta2, eps, m_ref, v_ref, g_ref, s_ref, mo, vo, do):
    lr = s_ref[0, 0]
    bc1 = s_ref[0, 1]
    bc2 = s_ref[0, 2]
    g = g_ref[...]
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * g * g
    mo[...] = m
    vo[...] = v
    do[...] = lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)


def _nesterovs_kernel(momentum, v_ref, g_ref, s_ref, vo, do):
    lr = s_ref[0, 0]
    v0 = v_ref[...]
    v = momentum * v0 - lr * g_ref[...]
    vo[...] = v
    do[...] = momentum * v0 - (1.0 + momentum) * v


def _rmsprop_kernel(decay, eps, a_ref, g_ref, s_ref, ao, do):
    lr = s_ref[0, 0]
    a = decay * a_ref[...] + (1.0 - decay) * g_ref[...] * g_ref[...]
    ao[...] = a
    do[...] = lr * g_ref[...] / jnp.sqrt(a + eps)


@functools.lru_cache(maxsize=64)
def _flat_call(kind: str, rows: int, hyper: tuple, interpret: bool):
    from jax.experimental import pallas as pl

    body = {
        "adam": functools.partial(_adam_kernel, *hyper),
        "nesterovs": functools.partial(_nesterovs_kernel, *hyper),
        "rmsprop": functools.partial(_rmsprop_kernel, *hyper),
    }[kind]
    n_out = {"adam": 3, "nesterovs": 2, "rmsprop": 2}[kind]
    out = jax.ShapeDtypeStruct((rows, 128), jnp.float32)
    return pl.pallas_call(body, out_shape=(out,) * n_out,
                          interpret=interpret)


def _to_tiles(vec):
    n = vec.shape[0]
    pad = (-n) % _TILE
    if pad:
        vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
    return vec.reshape(-1, 128)


def _scalars(lr, step, kind, hyper):
    lr = jnp.asarray(lr, jnp.float32)
    if kind == "adam":
        beta1, beta2, _ = hyper
        t = jnp.asarray(step, jnp.float32) + 1.0
        bc1 = 1.0 - beta1 ** t
        bc2 = 1.0 - beta2 ** t
        return jnp.stack([lr, bc1, bc2]).reshape(1, 3)
    return jnp.stack([lr, lr, lr]).reshape(1, 3)


def pallas_update(kind, state, grads, lr, step, hyper):
    """Fused update over the raveled trees; returns `(new_state, deltas)`
    with the same tree structure as the XLA fallbacks."""
    gflat, unravel = ravel_pytree(grads)
    n = gflat.shape[0]
    fields = {"adam": ("m", "v"), "nesterovs": ("v",), "rmsprop": ("g2",)}[kind]
    sflat = [ravel_pytree(state[f])[0] for f in fields]
    tiles = _to_tiles(gflat)
    call = _flat_call(kind, tiles.shape[0], hyper,
                      interpret=jax.default_backend() != "tpu")
    outs = call(*[_to_tiles(s) for s in sflat], tiles,
                _scalars(lr, step, kind, hyper))
    outs = [o.reshape(-1)[:n] for o in outs]
    new_state = {f: unravel(outs[i]) for i, f in enumerate(fields)}
    return new_state, unravel(outs[-1])


# ------------------------------------------------------- dispatch seam


def dispatch(kind, state, grads, lr, step, hyper):
    """`ops/updaters.py`'s seam: `hyper` is the positional hyperparameter
    tuple of the kind's XLA fallback (Python floats — part of the trace,
    and of the resolution memo key)."""
    leaves = jax.tree_util.tree_leaves(grads)
    res = registry.resolve(
        "fused_update",
        shapes=tuple(tuple(l.shape) for l in leaves),
        dtypes=tuple(str(l.dtype) for l in leaves),
        meta=(("kind", kind), ("hyper", tuple(hyper))))
    if res.impl == "pallas":
        return pallas_update(kind, state, grads, lr, step, tuple(hyper))
    if kind == "adam":
        return adam_xla(state, grads, lr, step, *hyper)
    if kind == "nesterovs":
        return nesterovs_xla(state, grads, lr, step, *hyper)
    return rmsprop_xla(state, grads, lr, step, *hyper)
