"""Autodiff seam for forward-only Pallas kernels.

`pl.pallas_call` carries no JVP/VJP rule, so a Pallas kernel sitting in a
layer's forward pass would fail the engines' `jax.value_and_grad` trace.
The flash-attention kernel hand-writes its backward; the simpler fused
kernels (LSTM cell, norm+activation) instead pair the Pallas FORWARD with
the VJP of their XLA reference: residuals are the primal inputs, and the
backward recomputes the reference forward to transpose it (standard
rematerialization — the backward math is exactly the fallback's, so
gradients are float-close to the XLA path by construction while the
forward value comes from the fused kernel).
"""

from __future__ import annotations

import jax


def pallas_fwd_ref_bwd(pallas_fn, ref_fn):
    """`pallas_fn` and `ref_fn` share one signature (pytree args allowed,
    None for absent operands). Returns a differentiable callable running
    `pallas_fn` forward and `ref_fn`'s VJP backward."""

    @jax.custom_vjp
    def f(*args):
        return pallas_fn(*args)

    def fwd(*args):
        return pallas_fn(*args), args

    def bwd(args, g):
        _, vjp = jax.vjp(ref_fn, *args)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f
