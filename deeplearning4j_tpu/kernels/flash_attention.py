"""Pallas flash-attention forward kernel (TPU).

The reference predates attention entirely; this backs the framework's
long-context extension (`parallel/sequence.py`). Online-softmax
accumulation in fp32 — no [T, T] score matrix ever exists — with a hybrid
of two layouts chosen by K/V footprint: a K/V-resident kernel (K/V
fetched once per batch-head, reused across q-block programs, causal loop
stops at the diagonal) while they fit VMEM, and a streaming kernel
(k-blocks as the innermost grid dim, VMEM scratch accumulators, O(block)
memory at any T) beyond it.

Measured on the driver's v5e chip (bf16, BH=8, D=64, blocks 256):
1.2x XLA dense at T=2k, 1.6x at 8k, 3.1x at 16k, and still running at
T=65k where dense attention no longer fits at all (PERF.md §6). Reached
via `parallel.sequence.attention(..., impl="auto")`, the framework's
default attention entry.

The streaming layout enumerates its (q-block, k-block) pairs through a
SCALAR-PREFETCHED index sequence (`_pair_arrays`): for causal attention
the sequence is exactly the lower triangle, so above-diagonal k-blocks
are never DMA'd at all — at long causal T this halves the streamed
bandwidth relative to a rectangular grid with compute-only gating (the
round-4 "known headroom", closed in round 5).

Differentiation: `flash_attention` carries a custom_vjp with a Pallas
backward in BOTH regimes — the standard two-kernel flash formulation
(dq over q-blocks; dk/dv over k-blocks) recomputing p from the saved lse
per block, O(T·D) memory. While K/V fit VMEM the backward kernels keep
them resident (fetched once per batch-head; measured fwd+bwd 1.5x the XLA
dense VJP at T=8k bf16); beyond that they stream k/v (dq) and q/do (dkv)
blocks through the same triangular prefetch sequences, so TRAINING at any
block-multiple T never materializes a [T, T] matrix. Only non-multiple T
falls back to the XLA dense VJP. For sequence-sharded long-T training use
ring attention (`parallel/sequence.py`); this kernel is the single-device
path.

On non-TPU backends the kernel runs in Pallas interpret mode (numerics
identical, speed irrelevant) so the CPU test mesh exercises the same code.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.kernels import registry as _registry

_NEG = -1e30


def _resident_softmax_loop(q_ref, k_ref, v_ref, *, block_k: int,
                           causal: bool, scale: float):
    """The resident online-softmax accumulation shared by the plain and
    lse-emitting forward kernels: returns (acc [BQ, D], m [BQ, 1],
    l [BQ, 1]) with l clamped positive."""
    BQ, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    i = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    q_off = i * BQ

    nk = T // block_k
    if causal:
        nk = jnp.minimum(nk, (q_off + BQ - 1) // block_k + 1)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (BQ, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (BQ, block_k), 1)
            s = jnp.where(kpos > qpos, _NEG, s)
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, new_m, l

    acc = jnp.zeros((BQ, D), jnp.float32)
    m = jnp.full((BQ, 1), _NEG, jnp.float32)
    l = jnp.zeros((BQ, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m, l))
    return acc, m, jnp.maximum(l, 1e-30)


def _flash_kernel_resident(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                           causal: bool, scale: float):
    """Fast path while K/V fit in VMEM: one program per (bh, q-block),
    K/V BlockSpec'd whole — their index map doesn't change across the
    q-block grid steps of one bh, so Pallas fetches them ONCE per
    batch-head and every q-block reuses the resident copy (measured ~1.5x
    the streaming kernel at T<=16k). The fori_loop bound stops at the
    causal diagonal, skipping both compute and reads of future blocks."""
    acc, m, l = _resident_softmax_loop(q_ref, k_ref, v_ref, block_k=block_k,
                                       causal=causal, scale=scale)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def _pair_arrays(nq: int, nk: int, block_q: int, block_k: int, causal: bool,
                 order: str):
    """The streamed (q-block i, k-block j) visit sequence, scalar-prefetched
    into the kernels. Causal sequences cover ONLY the lower triangle —
    above-diagonal blocks are never DMA'd. `order="row"` (i-major: forward,
    dq — scratch accumulates along j) or `"col"` (j-major: dk/dv — scratch
    accumulates along i)."""
    import numpy as np

    pairs = []
    if order == "row":
        for i in range(nq):
            jm = min(nk - 1, ((i + 1) * block_q - 1) // block_k) \
                if causal else nk - 1
            pairs += [(i, j) for j in range(jm + 1)]
    else:
        for j in range(nk):
            i0 = (j * block_k) // block_q if causal else 0
            pairs += [(i, j) for i in range(i0, nq)]
    i_idx = np.asarray([p[0] for p in pairs], np.int32)
    j_idx = np.asarray([p[1] for p in pairs], np.int32)
    return i_idx, j_idx


def _flash_stream_kernel(i_ref, j_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                         acc_ref, m_ref, l_ref, *, block_q: int,
                         block_k: int, nk: int, causal: bool, scale: float):
    """One streamed step: fold k/v block j into q block i's accumulator.

    TPU grids run sequentially, so the VMEM scratch (acc/m/l) persists
    across the j steps of one (bh, i) pair (the prefetched sequence is
    i-major) and Pallas double-buffers the next block's DMA against this
    block's compute. Emits lse = m + log(l) for the backward."""
    BQ, D = q_ref.shape[1], q_ref.shape[2]
    BK = k_ref.shape[1]
    t = pl.program_id(1)
    i, j = i_ref[t], j_ref[t]

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_off, k_off = i * BQ, j * BK
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
        kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
        s = jnp.where(kpos > qpos, _NEG, s)
    m = m_ref[:]
    blk_max = jnp.max(s, axis=1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    p = jnp.exp(s - new_m)
    corr = jnp.exp(m - new_m)
    l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = new_m

    if causal:
        jmax = jnp.minimum(((i + 1) * block_q - 1) // block_k, nk - 1)
    else:
        jmax = nk - 1

    @pl.when(j == jmax)
    def _():
        l = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l)


def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


# Above this K/V footprint the resident kernel would oversubscribe VMEM
# (~16 MB/core, shared with q/out blocks and double buffering).
_RESIDENT_KV_LIMIT = 6 * 1024 * 1024


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "block_q", "block_k"))
def _flash_fwd_stream_bhtd(q, k, v, causal, scale, block_q, block_k):
    """Streaming forward via the prefetched block sequence: (o, lse)."""
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    nq, nk = T // block_q, T // block_k
    i_idx, j_idx = _pair_arrays(nq, nk, block_q, block_k, causal, "row")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, len(i_idx)),
        in_specs=[
            pl.BlockSpec((1, block_q, D),
                         lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, t, ii, jj: (b, jj[t], 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, t, ii, jj: (b, jj[t], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, t, ii, jj: (b, ii[t], 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_flash_stream_kernel, block_q=block_q,
                          block_k=block_k, nk=nk, causal=causal, scale=scale),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((BH, T, 1), jnp.float32)],
        interpret=not _on_tpu(),
    )(jnp.asarray(i_idx), jnp.asarray(j_idx), q, k, v)


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "block_q", "block_k"))
def _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k):
    """q/k/v: [BH, T, D] -> [BH, T, D]."""
    BH, T, D = q.shape
    kv_bytes = 2 * T * D * q.dtype.itemsize
    if kv_bytes <= _RESIDENT_KV_LIMIT:
        return pl.pallas_call(
            functools.partial(_flash_kernel_resident, block_k=block_k,
                              causal=causal, scale=scale),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            grid=(BH, T // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            interpret=not _on_tpu(),
        )(q, k, v)
    o, _ = _flash_fwd_stream_bhtd(q, k, v, causal, scale, block_q, block_k)
    return o


def _dense_ref(q, k, v, causal, scale):
    """XLA dense attention on [B, T, H, D] — the single shared dense
    implementation (`parallel/sequence.py`), also the VJP donor."""
    from deeplearning4j_tpu.parallel.sequence import dense_attention

    return dense_attention(q, k, v, causal=causal, scale=scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_pallas(q, k, v, causal: bool = True,
                            scale: Optional[float] = None,
                            block_q: int = 256, block_k: int = 256):
    """Flash multi-head attention. q/k/v: [B, T, H, Dh] -> [B, T, H, Dh].

    Falls back to the XLA dense path when T is not a block multiple (the
    kernel requires T % block == 0)."""
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    B, T, H, D = q.shape
    if T % block_q or T % block_k:
        return _dense_ref(q, k, v, causal, scale)
    to_bhtd = lambda a: jnp.swapaxes(a, 1, 2).reshape(B * H, T, D)
    o = _flash_fwd_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, scale,
                        block_q, block_k)
    return jnp.swapaxes(o.reshape(B, H, T, D), 1, 2)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 256, block_k: int = 256):
    """Registry-dispatched entry (kernel name ``flash_attention``): the
    Pallas kernel above (interpret off-TPU, its historical behavior under
    ``auto``) or the XLA dense reference under ``DL4J_TPU_KERNELS=xla`` /
    a per-kernel override. Same [B, T, H, Dh] contract either way."""
    res = _registry.resolve("flash_attention",
                            shapes=(tuple(int(d) for d in q.shape),),
                            dtypes=(str(q.dtype),))
    if res.impl != "pallas":
        s = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
        return _dense_ref(q, k, v, causal, s)
    return _flash_attention_pallas(q, k, v, causal, scale, block_q, block_k)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    scale_v = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    B, T, H, D = q.shape
    if T % block_q or T % block_k:
        # Non-multiple T: dense XLA forward AND backward.
        return (_flash_attention_pallas(q, k, v, causal, scale, block_q,
                                        block_k),
                (q, k, v, None, None))
    to_bhtd = lambda a: jnp.swapaxes(a, 1, 2).reshape(B * H, T, D)
    if 2 * T * D * q.dtype.itemsize <= _RESIDENT_KV_LIMIT:
        o, lse = _flash_fwd_lse_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v),
                                     causal, scale_v, block_q, block_k)
    else:
        o, lse = _flash_fwd_stream_bhtd(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, scale_v,
            block_q, block_k)
    return (jnp.swapaxes(o.reshape(B, H, T, D), 1, 2), (q, k, v, o, lse))


def _bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, o_bhtd, lse = res
    scale_v = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if lse is None:
        _, vjp = jax.vjp(
            lambda q, k, v: _dense_ref(q, k, v, causal, scale_v), q, k, v)
        return vjp(g)
    B, T, H, D = q.shape
    to_bhtd = lambda a: jnp.swapaxes(a, 1, 2).reshape(B * H, T, D)
    if 2 * T * D * q.dtype.itemsize <= _RESIDENT_KV_LIMIT:
        dq, dk, dv = _flash_bwd_bhtd(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), to_bhtd(g), o_bhtd, lse,
            causal, scale_v, block_q, block_k)
    else:
        dq, dk, dv = _flash_bwd_stream_bhtd(
            to_bhtd(q), to_bhtd(k), to_bhtd(v), to_bhtd(g), o_bhtd, lse,
            causal, scale_v, block_q, block_k)
    back = lambda a: jnp.swapaxes(a.reshape(B, H, T, D), 1, 2)
    return (back(dq).astype(q.dtype), back(dk).astype(k.dtype),
            back(dv).astype(v.dtype))


_flash_attention_pallas.defvjp(_fwd, _bwd)


def _pallas_available(backend, shapes, dtypes, meta=(), forced=False):
    if backend == "tpu":
        return True, "TPU flash kernel (resident/streaming hybrid, PERF.md §6)"
    return True, ("interpret mode off-TPU (numerics identical, speed "
                  "irrelevant — the CPU test mesh's path)")


def _xla_available(backend, shapes, dtypes, meta=(), forced=False):
    return True, "XLA dense attention (parallel.sequence.dense_attention)"


_registry.register("flash_attention", [
    _registry.KernelImpl("pallas", _pallas_available),
    _registry.KernelImpl("xla", _xla_available),
])


# ----------------------------------------------------------------- backward
#
# Flash backward (resident regime): recompute p from (q, k, lse) per block
# instead of keeping the [T, T] probability matrix — the standard
# two-kernel formulation (dq over q-blocks; dk/dv over k-blocks), O(T·D)
# memory. The forward saves lse = m + log(l) per row. Outside the resident
# regime (or non-multiple T) the custom_vjp falls back to the XLA dense
# VJP exactly as before.


def _flash_fwd_lse_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                          block_k: int, causal: bool, scale: float):
    """Resident forward that also emits lse = m + log(l) (the backward's
    softmax normalizer), sharing `_resident_softmax_loop`."""
    acc, m, l = _resident_softmax_loop(q_ref, k_ref, v_ref, block_k=block_k,
                                       causal=causal, scale=scale)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)          # [BQ, 1]


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, d_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         scale: float):
    """dq for one (bh, q-block): loop k/v blocks, recompute p from lse."""
    BQ, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    i = pl.program_id(1)
    q_off = i * BQ
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]                 # [BQ]
    d_row = d_ref[0, :, 0]                 # [BQ] = rowsum(do * o)

    nk = T // block_k
    if causal:
        nk = jnp.minimum(nk, (q_off + BQ - 1) // block_k + 1)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (BQ, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (BQ, block_k), 1)
            s = jnp.where(kpos > qpos, _NEG, s)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - d_row[:, None])
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((BQ, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, d_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          scale: float):
    """dk/dv for one (bh, k-block): loop q blocks (from the diagonal when
    causal), recompute p from lse."""
    BK, D = k_ref.shape[1], k_ref.shape[2]
    T = q_ref.shape[1]
    j = pl.program_id(1)
    k_off = j * BK
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    nq = T // block_q
    i0 = (k_off // block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), 0]
        d_row = d_ref[0, pl.ds(i * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, BK), 0)
            kpos = k_off + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, BK), 1)
            s = jnp.where(kpos > qpos, _NEG, s)
        p = jnp.exp(s - lse[:, None])                    # [BQ, BK]
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - d_row[:, None])
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk = jnp.zeros((BK, D), jnp.float32)
    dv = jnp.zeros((BK, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(i0, nq, body, (dk, dv))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "block_q", "block_k"))
def _flash_fwd_lse_bhtd(q, k, v, causal, scale, block_q, block_k):
    """Resident forward emitting (o, lse). [BH, T, D] ->
    ([BH, T, D], [BH, T, 1] fp32)."""
    BH, T, D = q.shape
    return pl.pallas_call(
        functools.partial(_flash_fwd_lse_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((BH, T, 1), jnp.float32)],
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0))],
        interpret=not _on_tpu(),
    )(q, k, v)


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "block_q", "block_k"))
def _flash_bwd_bhtd(q, k, v, do, o, lse, causal, scale, block_q, block_k):
    """Resident backward: (dq, dk, dv) each [BH, T, D]."""
    BH, T, D = q.shape
    d_row = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, T, 1]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
        grid=(BH, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        interpret=not _on_tpu(),
    )(q, k, v, do, lse, d_row)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale),
        out_shape=[jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)],
        grid=(BH, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0)),
                   pl.BlockSpec((1, block_k, D), lambda b, j: (b, j, 0))],
        interpret=not _on_tpu(),
    )(k, v, q, do, lse, d_row)
    return dq, dk, dv


# ------------------------------------------------- streaming backward
#
# Beyond the resident K/V limit the backward streams blocks through the
# same scalar-prefetched sequences as the forward: dq walks the causal
# triangle row-major (k/v blocks stream; dq accumulates in VMEM scratch
# per q-block), dk/dv walk it column-major (q/do blocks stream; dk/dv
# accumulate per k-block). O(block) VMEM at any T — long-T training never
# materializes [T, T].


def _flash_bwd_dq_stream_kernel(i_ref, j_ref, q_ref, k_ref, v_ref, do_ref,
                                lse_ref, d_ref, dq_ref, dq_acc, *,
                                block_q: int, block_k: int, nk: int,
                                causal: bool, scale: float):
    BQ = q_ref.shape[1]
    BK = k_ref.shape[1]
    t = pl.program_id(1)
    i, j = i_ref[t], j_ref[t]

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    d_row = d_ref[0, :, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (BQ, BK), 0)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (BQ, BK), 1)
        s = jnp.where(kpos > qpos, _NEG, s)
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - d_row[:, None])
    dq_acc[:] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        jmax = jnp.minimum(((i + 1) * block_q - 1) // block_k, nk - 1)
    else:
        jmax = nk - 1

    @pl.when(j == jmax)
    def _():
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_stream_kernel(i_ref, j_ref, k_ref, v_ref, q_ref, do_ref,
                                 lse_ref, d_ref, dk_ref, dv_ref, dk_acc,
                                 dv_acc, *, block_q: int, block_k: int,
                                 nq: int, causal: bool, scale: float):
    BK = k_ref.shape[1]
    BQ = q_ref.shape[1]
    t = pl.program_id(1)
    i, j = i_ref[t], j_ref[t]
    i0 = (j * block_k) // block_q if causal else 0

    @pl.when(i == i0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    d_row = d_ref[0, :, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (BQ, BK), 0)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (BQ, BK), 1)
        s = jnp.where(kpos > qpos, _NEG, s)
    p = jnp.exp(s - lse[:, None])
    dv_acc[:] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - d_row[:, None])
    dk_acc[:] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "block_q", "block_k"))
def _flash_bwd_stream_bhtd(q, k, v, do, o, lse, causal, scale, block_q,
                           block_k):
    """Streaming backward: (dq, dk, dv) each [BH, T, D], O(block) VMEM."""
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    nq, nk = T // block_q, T // block_k
    d_row = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, T, 1]

    ir, jr = _pair_arrays(nq, nk, block_q, block_k, causal, "row")
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, len(ir)),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_k, D), lambda b, t, ii, jj: (b, jj[t], 0)),
            pl.BlockSpec((1, block_k, D), lambda b, t, ii, jj: (b, jj[t], 0)),
            pl.BlockSpec((1, block_q, D), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, t, ii, jj: (b, ii[t], 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda b, t, ii, jj: (b, ii[t], 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_stream_kernel, block_q=block_q,
                          block_k=block_k, nk=nk, causal=causal, scale=scale),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
        interpret=not _on_tpu(),
    )(jnp.asarray(ir), jnp.asarray(jr), q, k, v, do, lse, d_row)

    ic, jc = _pair_arrays(nq, nk, block_q, block_k, causal, "col")
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, len(ic)),
        in_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, t, ii, jj: (b, jj[t], 0)),
            pl.BlockSpec((1, block_k, D), lambda b, t, ii, jj: (b, jj[t], 0)),
            pl.BlockSpec((1, block_q, D), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_q, D), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, t, ii, jj: (b, ii[t], 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, t, ii, jj: (b, ii[t], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, t, ii, jj: (b, jj[t], 0)),
            pl.BlockSpec((1, block_k, D), lambda b, t, ii, jj: (b, jj[t], 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_stream_kernel, block_q=block_q,
                          block_k=block_k, nq=nq, causal=causal, scale=scale),
        grid_spec=dkv_spec,
        out_shape=[jax.ShapeDtypeStruct(k.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v.shape, jnp.float32)],
        interpret=not _on_tpu(),
    )(jnp.asarray(ic), jnp.asarray(jc), k, v, q, do, lse, d_row)
    return dq, dk, dv


# ------------------------------------------------------------------ paged
# Decode-step attention against the paged KV pool (PagedAttention, Kwon
# et al. SOSP 2023): k/v live as [P, page, H, D] pools, each batch row
# reads through its [NP] row of the int32 page table. Inference-only and
# deliberately VJP-EXEMPT: the decode path never differentiates (the
# engines refuse training with decode caches), so no custom_vjp is
# defined — differentiating through it is a loud error, not a silent
# dense fallback.


def _paged_gather_dense(q, k_pages, v_pages, page_table, pos, causal):
    """XLA fallback: gather the pages into the dense [B, L, H, D] cache
    layout and reuse `_cached_decode_attention` VERBATIM. Bit-identical
    to the dense stepper: garbage rows (zero page, pad/CoW tails) land
    exactly on masked key positions, where the softmax weight underflows
    to exactly 0.0 and contributes +0.0 to the same-order contraction."""
    from deeplearning4j_tpu.nn.layers.attention import (
        _cached_decode_attention,
    )

    B = q.shape[0]
    NP = page_table.shape[1]
    _, page, H, D = k_pages.shape
    kc = k_pages[page_table].reshape(B, NP * page, H, D)
    vc = v_pages[page_table].reshape(B, NP * page, H, D)
    return _cached_decode_attention(q, kc, vc, pos, causal)


def _paged_flash_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                        acc_ref, m_ref, l_ref, *, page, n_pages, causal,
                        scale):
    """One (batch, head, logical-page) step: the page table is scalar-
    prefetched, so the k/v BlockSpec index maps DMA exactly the physical
    page this slot's logical page j resolves to — no dense gather ever
    materializes. VMEM scratch (acc, m, l) carries the online softmax
    across the NP sequential grid steps."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    T = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale
    k = k_ref[0, :, 0, :].astype(jnp.float32)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [T, page]
    kpos = j * page + jax.lax.broadcasted_iota(jnp.int32, (T, page), 1)
    if causal:
        limit = (pos_ref[b] + 1
                 + jax.lax.broadcasted_iota(jnp.int32, (T, page), 0))
    else:
        limit = pos_ref[b] + T
    s = jnp.where(kpos < limit, s, _NEG)
    blk_max = jnp.max(s, axis=1, keepdims=True)
    new_m = jnp.maximum(m_ref[...], blk_max)
    p = jnp.exp(s - new_m)
    corr = jnp.exp(m_ref[...] - new_m)
    l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_ref[...] = new_m

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0, :, 0, :] = (
            acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal",))
def _paged_flash(q, k_pages, v_pages, page_table, pos, causal):
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    page = k_pages.shape[1]
    NP = page_table.shape[1]
    scale = D ** -0.5
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, NP),
        in_specs=[
            pl.BlockSpec((1, T, 1, D),
                         lambda b, h, j, pt, pos: (b, 0, h, 0)),
            pl.BlockSpec((1, page, 1, D),
                         lambda b, h, j, pt, pos: (pt[b, j], 0, h, 0)),
            pl.BlockSpec((1, page, 1, D),
                         lambda b, h, j, pt, pos: (pt[b, j], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, T, 1, D),
                               lambda b, h, j, pt, pos: (b, 0, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((T, D), jnp.float32),
            pltpu.VMEM((T, 1), jnp.float32),
            pltpu.VMEM((T, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_flash_kernel, page=page, n_pages=NP,
                          causal=causal, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=not _on_tpu(),
    )(page_table, jnp.reshape(pos, (-1,)).astype(jnp.int32),
      q, k_pages, v_pages)


def paged_decode_attention(q, k_pages, v_pages, page_table, pos, causal):
    """Decode attention through the paged KV pool. q: [B, T, H, D] (the
    new positions, globally at [pos, pos+T) per row); k_pages/v_pages:
    [P, page, H, D]; page_table: [B, NP] int32 (0 = the zero page);
    pos: [B] int32 cursors.

    Resolves `flash_attention_paged` through the kernel registry: the
    Pallas paged-gather kernel on TPU (or when forced — interpret mode,
    float-close), else the XLA dense-gather composite, which is
    bit-identical to the dense stepper's `_cached_decode_attention`.
    Inference-only: no VJP is defined (see module note above)."""
    res = _registry.resolve(
        "flash_attention_paged",
        shapes=(tuple(q.shape), tuple(k_pages.shape),
                tuple(page_table.shape)),
        dtypes=(str(q.dtype),), meta=(bool(causal),))
    if res.impl == "pallas":
        return _paged_flash(q, k_pages, v_pages, page_table, pos, causal)
    return _paged_gather_dense(q, k_pages, v_pages, page_table, pos, causal)


def _paged_pallas_available(backend, shapes, dtypes, meta=(), forced=False):
    if backend == "tpu":
        return True, ("TPU paged-gather flash kernel (scalar-prefetched "
                      "page table)")
    if forced:
        return True, ("interpret mode off-TPU (float-close parity tests "
                      "only)")
    return False, ("auto off-TPU keeps the XLA dense-gather composite — "
                   "bit-identical to the dense stepper")


def _paged_xla_available(backend, shapes, dtypes, meta=(), forced=False):
    return True, ("XLA dense-gather + _cached_decode_attention "
                  "(bit-identical fallback)")


_registry.register("flash_attention_paged", [
    _registry.KernelImpl("pallas", _paged_pallas_available),
    _registry.KernelImpl("xla", _paged_xla_available),
])
