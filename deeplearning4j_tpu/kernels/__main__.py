"""``python -m deeplearning4j_tpu.kernels`` — kernel resolution report.

Prints, for every registered kernel, the active mode (and which env knob
set it), the implementation that resolves on this process's backend at a
generic signature, and the availability reason. ``--json`` emits the
same rows as a JSON list.

``--probe KERNEL SHAPES DTYPES`` dry-runs a hypothetical signature
instead: every candidate's availability and refusal reason is printed
(no jit required), for debugging forced-kernel rollouts — e.g.::

    python -m deeplearning4j_tpu.kernels --probe bottleneck_block \\
        256,56,56,64,64,256,1,1 float32 --meta train=true --meta act=relu

SHAPES is a comma-separated int tuple (the kernel's registry signature
order), DTYPES a comma-separated dtype list, and repeatable
``--meta key=value`` pairs fill the meta tuple (``true``/``false``
parse to booleans).
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_meta(pairs):
    meta = []
    for p in pairs or ():
        k, _, v = p.partition("=")
        if v.lower() in ("true", "false"):
            v = v.lower() == "true"
        meta.append((k, v))
    return tuple(meta)


def _probe(args) -> int:
    from deeplearning4j_tpu.kernels import registry

    kernel, shapes_s, dtypes_s = args.probe
    shapes = tuple(int(d) for d in shapes_s.split(",")) if shapes_s else ()
    dtypes = tuple(d for d in dtypes_s.split(",") if d)
    meta = _parse_meta(args.meta)
    selected, rows = registry.probe(kernel, backend=args.backend,
                                    shapes=shapes, dtypes=dtypes, meta=meta)
    mode, source = registry.mode_for(kernel)
    if args.json:
        print(json.dumps({"kernel": kernel, "mode": mode,
                          "mode_source": source, "selected": selected,
                          "candidates": rows}, indent=2))
        return 0
    import jax

    backend = args.backend or jax.default_backend()
    msrc = mode if source == "default" else f"{mode} [{source}]"
    print(f"{kernel} on backend={backend} mode={msrc} "
          f"shapes={shapes} dtypes={dtypes} meta={dict(meta)}:")
    for r in rows:
        mark = "-> " if r["impl"] == selected else "   "
        avail = "available" if r["available"] else "unavailable"
        forced = " (probed as forced)" if r["forced"] else ""
        print(f"  {mark}{r['impl']:<6} {avail:<11} {r['reason']}{forced}")
    print(f"resolves: {selected}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.kernels",
        description="List kernel-registry resolutions (and why).")
    ap.add_argument("--json", action="store_true", help="emit JSON rows")
    ap.add_argument("--backend", default=None,
                    help="probe as this backend (default: the process's "
                         "jax.default_backend())")
    ap.add_argument("--probe", nargs=3, default=None,
                    metavar=("KERNEL", "SHAPES", "DTYPES"),
                    help="dry-run one kernel at a hypothetical signature: "
                         "comma-separated SHAPES ints and DTYPES names; "
                         "prints per-candidate availability + reason")
    ap.add_argument("--meta", action="append", default=None,
                    metavar="KEY=VALUE",
                    help="meta entries for --probe (repeatable; "
                         "true/false parse to booleans)")
    args = ap.parse_args(argv)

    if args.probe:
        return _probe(args)

    from deeplearning4j_tpu.kernels import registry

    rows = registry.describe(backend=args.backend)
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    import jax

    backend = args.backend or jax.default_backend()
    print(f"kernel registry on backend={backend} "
          f"(DL4J_TPU_KERNELS + per-kernel DL4J_TPU_KERNEL_<NAME>):")
    w = max(len(r["kernel"]) for r in rows)
    for r in rows:
        mode = r["mode"] if r["mode_source"] == "default" else (
            f"{r['mode']} [{r['mode_source']}]")
        print(f"  {r['kernel']:<{w}}  mode={mode:<10} -> {r['impl']:<6} "
              f"{r['reason']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
