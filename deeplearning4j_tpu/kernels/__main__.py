"""``python -m deeplearning4j_tpu.kernels`` — kernel resolution report.

Prints, for every registered kernel, the active mode (and which env knob
set it), the implementation that resolves on this process's backend at a
generic signature, and the availability reason. ``--json`` emits the
same rows as a JSON list.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.kernels",
        description="List kernel-registry resolutions (and why).")
    ap.add_argument("--json", action="store_true", help="emit JSON rows")
    ap.add_argument("--backend", default=None,
                    help="probe as this backend (default: the process's "
                         "jax.default_backend())")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.kernels import registry

    rows = registry.describe(backend=args.backend)
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    import jax

    backend = args.backend or jax.default_backend()
    print(f"kernel registry on backend={backend} "
          f"(DL4J_TPU_KERNELS + per-kernel DL4J_TPU_KERNEL_<NAME>):")
    w = max(len(r["kernel"]) for r in rows)
    for r in rows:
        mode = r["mode"] if r["mode_source"] == "default" else (
            f"{r['mode']} [{r['mode_source']}]")
        print(f"  {r['kernel']:<{w}}  mode={mode:<10} -> {r['impl']:<6} "
              f"{r['reason']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
