"""Fused LSTM cell kernel: recurrent matmul + gates + state update.

One `pl.pallas_call` per scan step replaces the XLA op soup of
`nn/layers/recurrent.py::_lstm_scan`'s body: the `[b, n] x [n, 4n]`
recurrent matmul runs on the MXU and every elementwise gate/state op
consumes its operands straight from VMEM — no HBM round-trips between
the split/σ/tanh/mul chain that makes char-RNN the worst-MFU workload in
every bench round (PERF.md §4).

The XLA fallback below is the LITERAL pre-registry scan body moved here
verbatim: same ops, same order, so the traced jaxpr — and therefore the
trained bits — are identical to the pre-PR engines whenever the fallback
is active (`DL4J_TPU_KERNELS=xla` or auto off-TPU).

Availability (auto mode): TPU backend, float32 or bfloat16 compute (the
recurrent matmul always accumulates in f32 via `preferred_element_type`;
outputs are cast back to the operand dtype), sigmoid gate activation,
cell activation in the supported elementwise set, `n_out` a lane (128)
multiple and batch a sublane (8) multiple, and the weights + activations
of one step fitting VMEM. Forced `pallas` drops the backend/tiling
requirements (interpret mode needs neither) but keeps the structural
ones — that is how the CPU parity tests drive the same kernel code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.kernels import registry

# Elementwise activations the Pallas kernel can express in-kernel. Names
# follow `nn/activations.py`.
_GATE_ACTS = ("sigmoid",)
_CELL_ACTS = {
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "relu": lambda x: jnp.maximum(x, 0.0),
    "identity": lambda x: x,
}

_VMEM_BUDGET = 12 * 1024 * 1024


def _pallas_available(backend, shapes, dtypes, meta=(), forced=False):
    m = dict(meta)
    gate, act = m.get("gate"), m.get("act")
    if gate is not None and gate not in _GATE_ACTS:
        return False, f"gate activation {gate!r} not expressible in-kernel"
    if act is not None and act not in _CELL_ACTS:
        return False, f"cell activation {act!r} not expressible in-kernel"
    if dtypes and not set(dtypes) <= {"float32", "bfloat16"}:
        return False, f"dtype {sorted(set(dtypes))} not in (float32, bfloat16)"
    if forced and backend != "tpu":
        return True, "forced (interpret mode off-TPU)"
    if backend != "tpu":
        return False, (f"Pallas LSTM cell needs the TPU backend, have "
                       f"{backend} (DL4J_TPU_KERNEL_LSTM_CELL=pallas forces "
                       "interpret mode)")
    if not shapes:
        return True, "TPU backend (shapes unknown: assumed tile-aligned)"
    b, n = shapes
    if n % 128 or b % 8:
        return False, (f"b={b}, n_out={n} not tile-aligned "
                       "(need n_out % 128 == 0 and b % 8 == 0)")
    if forced:
        return True, "forced (TPU, tile-aligned)"
    step_bytes = 4 * (n * 4 * n + b * 4 * n + 4 * b * n)  # RW + xw + states
    if step_bytes > _VMEM_BUDGET:
        return False, f"one step needs ~{step_bytes} B VMEM > {_VMEM_BUDGET}"
    return True, "TPU fused cell (MXU recurrent matmul + in-VMEM gates)"


def _xla_available(backend, shapes, dtypes, meta=(), forced=False):
    return True, "XLA scan body (bit-identical to the pre-registry engines)"


registry.register("lstm_cell", [
    registry.KernelImpl("pallas", _pallas_available),
    registry.KernelImpl("xla", _xla_available),
])


def xla_cell(gate_act, cell_act, peephole: bool):
    """The pre-registry `_lstm_scan` step body, verbatim (bit-exactness
    contract — do not 'improve' the op order). `pw` is the
    `(p_i, p_f, p_o)` peephole triple or None; `m_t` the `[b]` step mask
    or None. Returns `(h, c, out)`."""

    def cell(xw_t, h_prev, c_prev, RW, pw, m_t):
        z = xw_t + h_prev @ RW
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        if peephole:
            p_i, p_f, p_o = pw
            zi = zi + c_prev * p_i
            zf = zf + c_prev * p_f
        i = gate_act(zi)
        f = gate_act(zf)
        g = cell_act(zg)
        c = f * c_prev + i * g
        if peephole:
            zo = zo + c * p_o
        o = gate_act(zo)
        h = o * cell_act(c)
        if m_t is not None:
            m = m_t[:, None]
            h = m * h + (1.0 - m) * h_prev
            c = m * c + (1.0 - m) * c_prev
            out = m * h
        else:
            out = h
        return h, c, out

    return cell


def _cell_kernel(n_out: int, peephole: bool, masked: bool, act_name: str,
                 refs):
    """Kernel body shared by the peephole/mask variants: `refs` is the
    positional ref tuple in pallas_call order."""
    if peephole and masked:
        xw_ref, h_ref, c_ref, rw_ref, pw_ref, m_ref, ho, co, oo = refs
    elif peephole:
        xw_ref, h_ref, c_ref, rw_ref, pw_ref, ho, co, oo = refs
        m_ref = None
    elif masked:
        xw_ref, h_ref, c_ref, rw_ref, m_ref, ho, co, oo = refs
        pw_ref = None
    else:
        xw_ref, h_ref, c_ref, rw_ref, ho, co, oo = refs
        pw_ref = m_ref = None
    act = _CELL_ACTS[act_name]
    n = n_out
    h_prev = h_ref[...]
    c_prev = c_ref[...]
    z = xw_ref[...] + jnp.dot(h_prev, rw_ref[...],
                              preferred_element_type=jnp.float32)
    zi = z[:, :n]
    zf = z[:, n:2 * n]
    zo = z[:, 2 * n:3 * n]
    zg = z[:, 3 * n:]
    if peephole:
        zi = zi + c_prev * pw_ref[0, :]
        zf = zf + c_prev * pw_ref[1, :]
    i = jax.nn.sigmoid(zi)
    f = jax.nn.sigmoid(zf)
    g = act(zg)
    c = f * c_prev + i * g
    if peephole:
        zo = zo + c * pw_ref[2, :]
    o = jax.nn.sigmoid(zo)
    h = o * act(c)
    if masked:
        m = m_ref[...]  # [b, 1]
        h = m * h + (1.0 - m) * h_prev
        c = m * c + (1.0 - m) * c_prev
        out = m * h
    else:
        out = h
    # Gate math runs in f32 (matmul `preferred_element_type`); the output
    # refs carry the operand dtype (bf16 under mixed policies).
    ho[...] = h.astype(ho.dtype)
    co[...] = c.astype(co.dtype)
    oo[...] = out.astype(oo.dtype)


@functools.lru_cache(maxsize=64)
def _pallas_call(batch: int, n_out: int, peephole: bool, masked: bool,
                 act_name: str, dtype: str, interpret: bool):
    from jax.experimental import pallas as pl

    out = jax.ShapeDtypeStruct((batch, n_out), jnp.dtype(dtype))
    return pl.pallas_call(
        lambda *refs: _cell_kernel(n_out, peephole, masked, act_name, refs),
        out_shape=(out, out, out),
        interpret=interpret,
    )


def pallas_cell(batch: int, n_out: int, peephole: bool, masked: bool,
                act_name: str, dtype: str, interpret: bool):
    """Fused-cell callable with the `xla_cell` signature."""
    call = _pallas_call(batch, n_out, peephole, masked, act_name, dtype,
                        interpret)

    def cell(xw_t, h_prev, c_prev, RW, pw, m_t):
        args = [xw_t, h_prev, c_prev, RW]
        if peephole:
            args.append(jnp.stack(pw))  # [3, n]: rows p_i, p_f, p_o
        if masked:
            args.append(m_t[:, None].astype(xw_t.dtype))
        return call(*args)

    return cell


def resolve_cell(*, batch, n_out, dtype, peephole, masked, gate_activation,
                 activation, gate_act, cell_act):
    """The `_lstm_scan` dispatch seam: resolve once per signature (BEFORE
    the scan body is defined — resolution never runs per timestep) and
    return a `(xw_t, h_prev, c_prev, RW, pw, m_t) -> (h, c, out)` cell."""
    res = registry.resolve(
        "lstm_cell", shapes=(int(batch), int(n_out)),
        dtypes=(str(dtype),),
        meta=(("gate", str(gate_activation)), ("act", str(activation)),
              ("peephole", bool(peephole)), ("masked", bool(masked))))
    if res.impl == "pallas":
        from deeplearning4j_tpu.kernels import _diff

        fused = pallas_cell(int(batch), int(n_out), bool(peephole),
                            bool(masked), str(activation), str(dtype),
                            interpret=jax.default_backend() != "tpu")
        # The cell runs inside the engines' value_and_grad: Pallas forward,
        # XLA-reference backward (kernels/_diff.py).
        return _diff.pallas_fwd_ref_bwd(
            fused, xla_cell(gate_act, cell_act, peephole))
    return xla_cell(gate_act, cell_act, peephole)
