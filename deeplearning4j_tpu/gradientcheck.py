"""Finite-difference gradient checking.

Equivalent of the reference's `gradientcheck/GradientCheckUtil.java:76,211` —
the correctness backbone of the whole test suite (SURVEY.md §4): central
differences `(C(w+eps) - C(w-eps)) / 2eps` per parameter vs the analytic
gradient, for both MultiLayerNetwork and ComputationGraph.

Networks should be built with `.dtype("float64")` (and tests enable
jax_enable_x64) — the reference likewise runs gradient checks in double
precision.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet


def _score_fn_multilayer(net, ds: DataSet):
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    fmask = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
    state = net.state

    def score(params):
        preout, _, _, aux = net._forward_fn(params, state, x, None, False, fmask)
        loss, _ = net._loss_from_preout(params, preout, y, lmask, aux)
        return loss

    return score


def _score_fn_graph(net, mds: MultiDataSet):
    inputs = [jnp.asarray(f) for f in mds.features]
    labels = [jnp.asarray(l) for l in mds.labels]
    fmasks = None
    if mds.features_masks is not None and any(m is not None for m in mds.features_masks):
        fmasks = [None if m is None else jnp.asarray(m) for m in mds.features_masks]
    lmasks = None
    if mds.labels_masks is not None and any(m is not None for m in mds.labels_masks):
        lmasks = [None if m is None else jnp.asarray(m) for m in mds.labels_masks]
    state = net.state

    def score(params):
        outs, _, aux, omasks = net._forward_fn(params, state, inputs, None, False, fmasks)
        loss, _ = net._loss_from_outputs(params, outs, labels, lmasks, aux, omasks)
        return loss

    return score


def check_gradients(
    net,
    data,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    print_results: bool = False,
    subset: Optional[int] = None,
    seed: int = 12345,
) -> bool:
    """Run the central-difference check. Returns True if every checked
    parameter's relative error is under `max_rel_error` (params whose absolute
    error is under `min_abs_error` pass regardless — reference semantics).

    `subset`: check only N randomly-chosen parameters (for big nets).
    """
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(net, MultiLayerNetwork):
        ds = data if isinstance(data, DataSet) else DataSet(*data)
        score = _score_fn_multilayer(net, ds)
    else:
        mds = data if isinstance(data, MultiDataSet) else MultiDataSet.from_dataset(data)
        score = _score_fn_graph(net, mds)

    params = net.params_tree
    score_jit = jax.jit(score)
    grads = jax.jit(jax.grad(score))(params)

    flat_grads, _ = jax.tree_util.tree_flatten(grads)
    flat_params, treedef = jax.tree_util.tree_flatten(params)
    analytic = np.concatenate([np.asarray(g).reshape(-1) for g in flat_grads])
    flat = np.concatenate([np.asarray(p).reshape(-1) for p in flat_params])
    n = flat.size

    idxs = np.arange(n)
    if subset is not None and subset < n:
        idxs = np.random.RandomState(seed).choice(n, subset, replace=False)

    shapes = [np.asarray(p).shape for p in flat_params]
    dtypes = [np.asarray(p).dtype for p in flat_params]

    def rebuild(vec):
        leaves, pos = [], 0
        for s, dt in zip(shapes, dtypes):
            cnt = int(np.prod(s)) if s else 1
            leaves.append(jnp.asarray(vec[pos : pos + cnt].reshape(s), dt))
            pos += cnt
        return jax.tree_util.tree_unflatten(treedef, leaves)

    n_pass = n_fail = 0
    max_err_seen = 0.0
    for i in idxs:
        orig = flat[i]
        flat[i] = orig + epsilon
        plus = float(score_jit(rebuild(flat)))
        flat[i] = orig - epsilon
        minus = float(score_jit(rebuild(flat)))
        flat[i] = orig
        numeric = (plus - minus) / (2 * epsilon)
        a = analytic[i]
        abs_err = abs(a - numeric)
        denom = abs(a) + abs(numeric)
        rel_err = abs_err / denom if denom > 0 else 0.0
        ok = rel_err < max_rel_error or abs_err < min_abs_error
        max_err_seen = max(max_err_seen, rel_err if abs_err >= min_abs_error else 0.0)
        if ok:
            n_pass += 1
        else:
            n_fail += 1
            if print_results:
                print(f"param[{i}] FAIL analytic={a:.8g} numeric={numeric:.8g} relErr={rel_err:.4g}")
    if print_results:
        print(f"GradientCheck: {n_pass} passed, {n_fail} failed, maxRelErr={max_err_seen:.4g}")
    return n_fail == 0
