"""Second-order / line-search solvers.

TPU-native equivalents of the reference's `optimize/solvers/` family —
`LBFGS.java`, `ConjugateGradient.java`, `LineGradientDescent.java`,
`BackTrackLineSearch.java`. The reference runs these as host loops mutating
the flat parameter view; here each is a pure, jit-traceable function over
the flat parameter vector: the WHOLE multi-iteration optimize loop
(`BaseOptimizer.optimize()` analog) compiles to one XLA computation —
`lax.scan` over iterations, `lax.while_loop` for the backtracking line
search, fixed-size circular buffers for the L-BFGS history.

Engines call these through `minimize()` when the config's
`optimization_algo` is not SGD (reference: `Solver.java:41-110` dispatch).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm

Array = jax.Array


def backtrack_line_search(loss_fn: Callable[[Array], Array], w: Array,
                          loss0: Array, grad: Array, direction: Array,
                          max_iters: int = 5, step0: float = 1.0,
                          rho: float = 0.5, c1: float = 1e-4
                          ) -> Tuple[Array, Array, Array]:
    """Armijo backtracking (reference: `BackTrackLineSearch.java` — same
    sufficient-decrease test, geometric step shrink). Returns
    (w_new, loss_new, step_taken); if no step satisfies the condition within
    `max_iters` shrinks, returns the unchanged point with step 0 (the
    reference's `step = 0` failure path, letting CG/L-BFGS restart).
    """
    slope = jnp.vdot(direction, grad)

    def cond(carry):
        alpha, it, _, loss_new = carry
        return jnp.logical_and(it < max_iters,
                               loss_new > loss0 + c1 * alpha * slope)

    def body(carry):
        alpha, it, _, _ = carry
        alpha = alpha * rho
        return alpha, it + 1, w + alpha * direction, loss_fn(w + alpha * direction)

    alpha0 = jnp.asarray(step0, w.dtype)
    init = (alpha0, jnp.asarray(0, jnp.int32), w + alpha0 * direction,
            loss_fn(w + alpha0 * direction))
    alpha, _, w_new, loss_new = jax.lax.while_loop(cond, body, init)
    ok = loss_new <= loss0 + c1 * alpha * slope
    w_out = jnp.where(ok, w_new, w)
    loss_out = jnp.where(ok, loss_new, loss0)
    step_out = jnp.where(ok, alpha, 0.0)
    return w_out, loss_out, step_out


def _line_gradient_descent(loss_fn, w0, iterations, max_line_search):
    """Steepest descent + line search (reference: `LineGradientDescent.java`)."""
    vg = jax.value_and_grad(loss_fn)

    def step(carry, _):
        w, _ = carry
        loss, g = vg(w)
        # Normalized direction keeps step0=1 meaningful across scales
        # (reference normalizes via setupSearchState/GradientStepFunction).
        d = -g / (jnp.linalg.norm(g) + 1e-12)
        w_new, loss_new, _ = backtrack_line_search(
            loss_fn, w, loss, g, d, max_iters=max_line_search)
        return (w_new, loss_new), loss_new

    (w, loss), _ = jax.lax.scan(step, (w0, loss_fn(w0)), None,
                                length=iterations)
    return w, loss


def _conjugate_gradient(loss_fn, w0, iterations, max_line_search):
    """Nonlinear CG, Polak-Ribière+ with automatic restart (reference:
    `ConjugateGradient.java` — PR beta, restart when beta <= 0 or the line
    search fails)."""
    vg = jax.value_and_grad(loss_fn)
    loss0, g0 = vg(w0)

    def step(carry, _):
        w, loss, g, d = carry
        w_new, loss_new, alpha = backtrack_line_search(
            loss_fn, w, loss, g, d, max_iters=max_line_search)
        loss_new, g_new = vg(w_new)
        beta = jnp.vdot(g_new, g_new - g) / (jnp.vdot(g, g) + 1e-30)
        beta = jnp.maximum(beta, 0.0)           # PR+ restart
        beta = jnp.where(alpha > 0.0, beta, 0.0)  # failed search -> steepest
        d_new = -g_new + beta * d
        # Ensure descent; otherwise reset to steepest descent.
        d_new = jnp.where(jnp.vdot(d_new, g_new) < 0.0, d_new, -g_new)
        return (w_new, loss_new, g_new, d_new), loss_new

    init = (w0, loss0, g0, -g0)
    (w, loss, _, _), _ = jax.lax.scan(step, init, None, length=iterations)
    return w, loss


def _lbfgs(loss_fn, w0, iterations, max_line_search, history=10):
    """L-BFGS two-loop recursion over a fixed-size circular (s, y) history
    (reference: `LBFGS.java` — the reference uses a LinkedList of the last m
    (s, y) pairs; a ring buffer is the static-shape equivalent XLA needs)."""
    vg = jax.value_and_grad(loss_fn)
    n = w0.shape[0]
    m = history

    def direction(g, S, Y, rho, k):
        """Two-loop recursion with masking for unfilled slots."""
        q = g
        alphas = jnp.zeros((m,), w0.dtype)
        valid_count = jnp.minimum(k, m)

        def loop1(i, qa):
            q, alphas = qa
            idx = jnp.mod(k - 1 - i, m)
            valid = i < valid_count
            a = rho[idx] * jnp.vdot(S[idx], q)
            a = jnp.where(valid, a, 0.0)
            q = q - a * Y[idx]
            alphas = alphas.at[idx].set(a)
            return q, alphas

        q, alphas = jax.lax.fori_loop(0, m, loop1, (q, alphas))
        # Initial Hessian scaling gamma = s.y / y.y of the newest pair.
        newest = jnp.mod(k - 1, m)
        sy = jnp.vdot(S[newest], Y[newest])
        yy = jnp.vdot(Y[newest], Y[newest])
        gamma = jnp.where(k > 0, sy / (yy + 1e-30), 1.0)
        r = gamma * q

        def loop2(i, r):
            idx = jnp.mod(k - valid_count + i, m)
            valid = i < valid_count
            b = rho[idx] * jnp.vdot(Y[idx], r)
            upd = S[idx] * (alphas[idx] - b)
            return r + jnp.where(valid, upd, 0.0)

        r = jax.lax.fori_loop(0, m, loop2, r)
        return -r

    def step(carry, _):
        w, loss, g, S, Y, rho, k = carry
        d = direction(g, S, Y, rho, k)
        # Fall back to steepest descent if d is not a descent direction.
        d = jnp.where(jnp.vdot(d, g) < 0.0, d, -g / (jnp.linalg.norm(g) + 1e-12))
        w_new, _, alpha = backtrack_line_search(
            loss_fn, w, loss, g, d, max_iters=max_line_search)
        loss_new, g_new = vg(w_new)
        s = w_new - w
        y = g_new - g
        sy = jnp.vdot(s, y)
        # Only store curvature pairs with s.y > 0 (positive definiteness).
        store = jnp.logical_and(alpha > 0.0, sy > 1e-12)
        slot = jnp.mod(k, m)
        S = jnp.where(store, S.at[slot].set(s), S)
        Y = jnp.where(store, Y.at[slot].set(y), Y)
        rho = jnp.where(store, rho.at[slot].set(1.0 / (sy + 1e-30)), rho)
        # On a rejected pair, RESTART (drop the history) instead of freezing
        # it: a stale history keeps proposing the same rejected quasi-Newton
        # direction with a stale gamma, whose tiny accepted steps never yield
        # s.y > 0, so the solver stalls permanently (the reference avoids the
        # stall by restarting/widening in `BackTrackLineSearch.java`). With
        # k reset to 0 the next direction is steepest descent and fresh
        # curvature pairs are captured again.
        k = jnp.where(store, k + 1, 0)
        return (w_new, loss_new, g_new, S, Y, rho, k), loss_new

    loss0, g0 = vg(w0)
    init = (w0, loss0, g0,
            jnp.zeros((m, n), w0.dtype), jnp.zeros((m, n), w0.dtype),
            jnp.zeros((m,), w0.dtype), jnp.asarray(0, jnp.int32))
    (w, loss, *_), _ = jax.lax.scan(step, init, None, length=iterations)
    return w, loss


def minimize(algo, loss_fn: Callable[[Array], Array], w0: Array,
             iterations: int = 10, max_line_search: int = 5,
             history: int = 10) -> Tuple[Array, Array]:
    """Run `iterations` solver iterations of `algo` from `w0`; returns
    (w_final, final_loss). Pure and jit-traceable (reference dispatch:
    `Solver.java:41-110`)."""
    algo = OptimizationAlgorithm.of(algo)
    if algo == OptimizationAlgorithm.LINE_GRADIENT_DESCENT:
        return _line_gradient_descent(loss_fn, w0, iterations, max_line_search)
    if algo == OptimizationAlgorithm.CONJUGATE_GRADIENT:
        return _conjugate_gradient(loss_fn, w0, iterations, max_line_search)
    if algo == OptimizationAlgorithm.LBFGS:
        return partial(_lbfgs, history=history)(
            loss_fn, w0, iterations, max_line_search)
    raise ValueError(f"minimize() does not handle {algo!r} (SGD uses the "
                     "fused jitted train step)")
