"""Training listeners.

Equivalent of the reference's `optimize/api/IterationListener`/`TrainingListener`
SPI and `optimize/listeners/` impls (ScoreIterationListener, PerformanceListener,
CollectScoresIterationListener, ComposableIterationListener). The listener hook
is the single observability point (SURVEY.md §5); networks call
`iteration_done(model, iteration)` after each fit step and the epoch hooks from
`fit()`.

Note: reading `model.score_value` forces a device sync — listeners that log
every iteration should use a `frequency` > 1 on high-latency transports.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")


class IterationListener:
    """Base listener (reference: `optimize/api/IterationListener.java`)."""

    def iteration_done(self, model, iteration: int) -> None:  # pragma: no cover
        pass

    # TrainingListener extras (reference: `optimize/api/TrainingListener.java`)
    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass


class ScoreIterationListener(IterationListener):
    """Log the score every N iterations (reference: `ScoreIterationListener.java`)."""

    def __init__(self, print_iterations: int = 10, out: Optional[Callable[[str], None]] = None):
        self.print_iterations = max(1, int(print_iterations))
        self.out = out or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.print_iterations == 0:
            self.out(f"Score at iteration {iteration} is {model.score_value}")


class PerformanceListener(IterationListener):
    """Samples/sec + batches/sec over the report interval (reference:
    `PerformanceListener.java:86-102` — the BASELINE.md metric semantics).

    JAX dispatch is asynchronous: by default the wall clock here measures
    DISPATCH rate, which can flatter the numbers while the device still has
    queued steps. Pass `sync=True` to settle the in-flight step (fetch the
    loss scalar — the sync that works over every transport, PERF.md §1.4)
    before sampling the clock; this is honest but serializes the pipeline,
    so use it for measurement runs, not production training. Rates for an
    interval with no `record_batch` calls are reported as NaN, never carried
    over stale from a previous interval."""

    def __init__(self, frequency: int = 1, report_score: bool = False,
                 out: Optional[Callable[[str], None]] = None,
                 sync: bool = False):
        self.frequency = max(1, int(frequency))
        self.report_score = report_score
        self.sync = bool(sync)
        self.out = out or (lambda s: logger.info(s))
        self._last_time = None
        self._last_iter = 0
        self._samples_since = 0
        self.last_samples_per_sec = float("nan")
        self.last_batches_per_sec = float("nan")

    def record_batch(self, num_samples: int) -> None:
        self._samples_since += int(num_samples)

    def _settle(self, model) -> None:
        score = getattr(model, "_score", None)
        if score is None:
            return
        try:
            float(score)
        except Exception:
            pass

    def iteration_done(self, model, iteration: int) -> None:
        if self.sync:
            self._settle(model)
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            return
        if iteration - self._last_iter < self.frequency:
            return
        dt = now - self._last_time
        batches = iteration - self._last_iter
        self.last_batches_per_sec = batches / dt if dt > 0 else float("nan")
        self.last_samples_per_sec = (
            self._samples_since / dt if self._samples_since and dt > 0
            else float("nan"))
        msg = (f"iteration {iteration}: {self.last_batches_per_sec:.2f} batches/sec"
               + (f", {self.last_samples_per_sec:.2f} samples/sec" if self._samples_since else ""))
        if self.report_score:
            msg += f", score {model.score_value:.6f}"
        self.out(msg)
        self._last_time = now
        self._last_iter = iteration
        self._samples_since = 0


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs (reference: `CollectScoresIterationListener.java`)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_value))


class ComposableIterationListener(IterationListener):
    """Fan-out to several listeners (reference: `ComposableIterationListener.java`)."""

    def __init__(self, *listeners: IterationListener):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration: int) -> None:
        for l in self.listeners:
            l.iteration_done(model, iteration)

    def on_epoch_start(self, model) -> None:
        for l in self.listeners:
            l.on_epoch_start(model)

    def on_epoch_end(self, model) -> None:
        for l in self.listeners:
            l.on_epoch_end(model)
