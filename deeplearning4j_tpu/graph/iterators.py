"""Random-walk generation.

Equivalent of the reference's `graph/iterator/RandomWalkIterator.java` and
`WeightedRandomWalkIterator.java` (one walk per start vertex, fixed length,
`NoEdgeHandling` for disconnected vertices) plus the parallel providers
(`graph/iterator/parallel/`). The reference steps one walker at a time from
Java; here ALL walkers advance together — each step is one vectorized
numpy gather/sample over the padded neighbor table, which is also the shape
a device-resident walk kernel would take.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling, NoEdgesException


def random_walks(graph: Graph, walk_length: int,
                 starts: Optional[np.ndarray] = None,
                 rng: Optional[np.random.RandomState] = None,
                 no_edge_handling: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                 weighted: bool = False) -> np.ndarray:
    """Generate `[num_starts, walk_length + 1]` vertex-index walks, one per
    start vertex (reference semantics: `RandomWalkIterator.next()` produces
    walkLength+1 vertices including the start). `weighted=True` samples
    neighbors proportional to edge weight (`WeightedRandomWalkIterator`)."""
    rng = rng or np.random.RandomState(0)
    nbrs, cumw, degs = graph.neighbor_table()
    if starts is None:
        starts = np.arange(graph.num_vertices(), dtype=np.int32)
    starts = np.asarray(starts, np.int32)

    B = len(starts)
    walks = np.empty((B, walk_length + 1), np.int32)
    walks[:, 0] = starts
    cur = starts.copy()
    for step in range(walk_length):
        d = degs[cur]
        connected = d > 0
        # Reference semantics: only a walk that actually LANDS on an
        # edgeless vertex throws (`RandomWalkIterator.next` —
        # GENERATE_STRICT); unreachable isolated vertices are fine.
        if (no_edge_handling == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED
                and not np.all(connected)):
            bad = int(cur[np.argmin(connected)])
            raise NoEdgesException(
                f"walk reached vertex {bad} which has no edges "
                "(EXCEPTION_ON_DISCONNECTED)")
        if weighted:
            total = cumw[cur, np.maximum(d - 1, 0)]
            u = rng.rand(B) * total
            # Per-row binary search over the padded cumulative weights.
            choice = np.sum(cumw[cur] < u[:, None], axis=1).astype(np.int64)
            choice = np.minimum(choice, np.maximum(d - 1, 0))
        else:
            choice = (rng.rand(B) * np.maximum(d, 1)).astype(np.int64)
        nxt = nbrs[cur, choice]
        # SELF_LOOP_ON_DISCONNECTED: a degree-0 walker stays put.
        cur = np.where(connected, nxt, cur).astype(np.int32)
        walks[:, step + 1] = cur
    return walks


class RandomWalkIterator:
    """Iterator facade over `random_walks` yielding one walk at a time
    (reference: `GraphWalkIterator` contract — `has_next`/`next`/`reset`/
    `walk_length`)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 no_edge_handling: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                 weighted: bool = False):
        self.graph = graph
        self._walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.weighted = weighted
        self.reset()

    def walk_length(self) -> int:
        return self._walk_length

    def reset(self) -> None:
        self._walks = random_walks(
            self.graph, self._walk_length,
            rng=np.random.RandomState(self.seed),
            no_edge_handling=self.no_edge_handling, weighted=self.weighted)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._walks)

    def next(self) -> np.ndarray:
        walk = self._walks[self._pos]
        self._pos += 1
        return walk

    def __iter__(self) -> Iterator[np.ndarray]:
        while self.has_next():
            yield self.next()
