"""Graph API.

Equivalent of the reference's `deeplearning4j-graph` core abstractions —
`graph/api/IGraph.java:17`, `graph/api/Vertex.java`, `graph/api/Edge.java`,
`graph/api/NoEdgeHandling.java`, and the adjacency-list implementation
`graph/graph/Graph.java:26`. The reference stores per-vertex Java edge lists;
here the graph additionally compiles itself to padded numpy neighbor/weight
tables (`neighbor_table()`) so random walks run vectorized over a whole batch
of walkers at once (see `graph/iterators.py`) instead of one
vertex-at-a-time.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np


class NoEdgeHandling(Enum):
    """What a walk does at a vertex with no outgoing edges (reference:
    `graph/api/NoEdgeHandling.java`)."""

    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class NoEdgesException(RuntimeError):
    """Reference: `graph/exception/NoEdgesException.java`."""


@dataclass
class Vertex:
    """A vertex: integer id + arbitrary value (reference `Vertex.java`)."""

    idx: int
    value: Any = None

    def vertex_id(self) -> int:
        return self.idx


@dataclass
class Edge:
    """An edge (reference `Edge.java`); `value` doubles as the weight for
    weighted walks when numeric."""

    frm: int
    to: int
    value: Any = None
    directed: bool = False


class Graph:
    """Adjacency-list graph (reference: `graph/graph/Graph.java:26` +
    `BaseGraph.java`). Undirected edges are stored in both directions."""

    def __init__(self, num_vertices: int,
                 vertices: Optional[Sequence[Any]] = None):
        if num_vertices <= 0:
            raise ValueError("num_vertices must be positive")
        self._vertices = [
            Vertex(i, vertices[i] if vertices is not None else None)
            for i in range(num_vertices)
        ]
        self._adj: List[List[Tuple[int, float]]] = [[] for _ in range(num_vertices)]
        self._edges: List[Edge] = []
        self._tables: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None

    # ------------------------------------------------------------ mutation

    def add_edge(self, frm: int, to: int, value: Any = None,
                 directed: bool = False) -> None:
        if not (0 <= frm < self.num_vertices() and 0 <= to < self.num_vertices()):
            raise ValueError(f"edge ({frm},{to}) out of range")
        weight = float(value) if isinstance(value, (int, float)) else 1.0
        self._edges.append(Edge(frm, to, value, directed))
        self._adj[frm].append((to, weight))
        if not directed:
            self._adj[to].append((frm, weight))
        self._tables = None

    # ------------------------------------------------------------- queries

    def num_vertices(self) -> int:
        return len(self._vertices)

    def num_edges(self) -> int:
        return len(self._edges)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def get_vertices(self, indexes: Sequence[int]) -> List[Vertex]:
        return [self._vertices[i] for i in indexes]

    def get_vertex_degree(self, vertex: int) -> int:
        return len(self._adj[vertex])

    def get_edges_out(self, vertex: int) -> List[Edge]:
        return [e for e in self._edges
                if e.frm == vertex or (not e.directed and e.to == vertex)]

    def get_connected_vertex_indices(self, vertex: int) -> np.ndarray:
        return np.asarray([t for t, _ in self._adj[vertex]], np.int32)

    def get_connected_vertices(self, vertex: int) -> List[Vertex]:
        return [self._vertices[t] for t, _ in self._adj[vertex]]

    def get_random_connected_vertex(self, vertex: int,
                                    rng: np.random.RandomState) -> Vertex:
        if not self._adj[vertex]:
            raise NoEdgesException(f"vertex {vertex} has no outgoing edges")
        t, _ = self._adj[vertex][rng.randint(len(self._adj[vertex]))]
        return self._vertices[t]

    def degrees(self) -> np.ndarray:
        return np.asarray([len(a) for a in self._adj], np.int32)

    # --------------------------------------------------- vectorized tables

    def neighbor_table(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Padded (neighbors [V, max_deg], cum_weights [V, max_deg],
        degrees [V]) for batched walk stepping. Cached until the edge set
        changes."""
        if self._tables is None:
            V = self.num_vertices()
            max_deg = max((len(a) for a in self._adj), default=0)
            max_deg = max(max_deg, 1)
            nbrs = np.zeros((V, max_deg), np.int32)
            cumw = np.zeros((V, max_deg), np.float64)
            degs = self.degrees()
            for v, adj in enumerate(self._adj):
                if adj:
                    nbrs[v, : len(adj)] = [t for t, _ in adj]
                    cumw[v, : len(adj)] = np.cumsum([w for _, w in adj])
                    # Pad the cumulative row with the total so searchsorted
                    # never lands on a padding slot.
                    cumw[v, len(adj):] = cumw[v, len(adj) - 1]
            self._tables = (nbrs, cumw, degs)
        return self._tables
