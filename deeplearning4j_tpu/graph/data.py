"""Graph loaders.

Equivalent of the reference's `graph/data/GraphLoader.java` with
`DelimitedEdgeLineProcessor` / `WeightedEdgeLineProcessor` /
`DelimitedVertexLoader` — parse "from<delim>to[<delim>weight]" edge-list
files into a `Graph`.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.graph.api import Graph


def load_undirected_graph(path: str, num_vertices: int, delim: str = ",",
                          directed: bool = False) -> Graph:
    """Unweighted edge list, one "from<delim>to" per line; lines starting
    with `#` are comments (reference: `GraphLoader.loadUndirectedGraphEdgeListFile`)."""
    g = Graph(num_vertices)
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(delim)
            g.add_edge(int(parts[0]), int(parts[1]), directed=directed)
    return g


def load_weighted_graph(path: str, num_vertices: int, delim: str = ",",
                        directed: bool = False) -> Graph:
    """Weighted edge list "from<delim>to<delim>weight" (reference:
    `WeightedEdgeLineProcessor`)."""
    g = Graph(num_vertices)
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(delim)
            g.add_edge(int(parts[0]), int(parts[1]), float(parts[2]),
                       directed=directed)
    return g
