"""DeepWalk graph embeddings.

Equivalent of the reference's `graph/models/deepwalk/DeepWalk.java:29`
(Perozzi et al. 2014: skip-gram with hierarchical softmax over random
walks), `GraphHuffman.java` (Huffman tree over vertex DEGREES driving the
HS codes), `models/embeddings/InMemoryGraphLookupTable.java` (per-pair HS
sigmoid update) and the query API `GraphVectorsImpl.java` +
`GraphVectorSerializer.java`.

The reference trains pair-at-a-time from N walker threads; here every walk
window is flattened into (center, target-path) pairs and pushed through the
same jitted `ops/skipgram.hs_skipgram_step` segment-sum kernel Word2Vec
uses — the Hogwild→batched redesign of SURVEY.md §7 hard-part (c) applied
to graphs.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling
from deeplearning4j_tpu.graph.iterators import random_walks
from deeplearning4j_tpu.ops.skipgram import hs_skipgram_step
# GraphHuffman parity: same Huffman core as word2vec, keyed on vertex
# degree with 64-bit code capacity (`GraphHuffman.java` packs codes in a
# long).
from deeplearning4j_tpu.util.huffman import huffman_codes


class GraphVectors:
    """Query API over trained vertex vectors (reference:
    `GraphVectorsImpl.java:21` — getVertexVector / similarity /
    verticesNearest)."""

    def __init__(self, syn0: np.ndarray):
        self.syn0 = np.asarray(syn0)
        norms = np.linalg.norm(self.syn0, axis=1, keepdims=True)
        self._unit = self.syn0 / np.maximum(norms, 1e-12)

    def num_vertices(self) -> int:
        return self.syn0.shape[0]

    def get_vector_size(self) -> int:
        return self.syn0.shape[1]

    def get_vertex_vector(self, idx: int) -> np.ndarray:
        return self.syn0[idx]

    def similarity(self, i: int, j: int) -> float:
        return float(self._unit[i] @ self._unit[j])

    def vertices_nearest(self, idx: int, top: int = 10) -> np.ndarray:
        sims = self._unit @ self._unit[idx]
        order = np.argsort(-sims)
        return order[order != idx][:top].astype(np.int32)

    # ----------------------------------------------------------------- io

    def save(self, path: str) -> None:
        """Text format: one "idx<TAB>v0 v1 ..." line per vertex (reference:
        `GraphVectorSerializer.writeGraphVectors`)."""
        with open(path, "w") as f:
            for i, row in enumerate(self.syn0):
                f.write(f"{i}\t" + " ".join(f"{x:.8g}" for x in row) + "\n")

    @classmethod
    def load(cls, path: str) -> "GraphVectors":
        rows = {}
        with open(path) as f:
            for line in f:
                idx, vec = line.rstrip("\n").split("\t")
                rows[int(idx)] = np.asarray([float(x) for x in vec.split()])
        syn0 = np.stack([rows[i] for i in range(len(rows))]).astype(np.float32)
        return cls(syn0)


class DeepWalk(GraphVectors):
    """DeepWalk trainer (builder parity with `DeepWalk.Builder`:
    vector_size, window_size, learning_rate, seed; plus walk/epoch controls
    that the reference passes to `fit(graph, walkLength)`)."""

    def __init__(self, *, vector_size: int = 100, window_size: int = 2,
                 learning_rate: float = 0.01, seed: int = 12345,
                 epochs: int = 1, batch_size: int = 4096,
                 weighted_walks: bool = False,
                 no_edge_handling: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.epochs = epochs
        self.batch_size = batch_size
        self.weighted_walks = weighted_walks
        self.no_edge_handling = no_edge_handling
        self.syn0 = None
        self._init_called = False

    # -------------------------------------------------------------- setup

    def initialize(self, graph_or_degrees) -> "DeepWalk":
        """Build the degree-keyed Huffman tree + tables (reference:
        `DeepWalk.initialize` — "vertex degrees are used to construct a
        binary (Huffman) tree")."""
        if isinstance(graph_or_degrees, Graph):
            degrees = graph_or_degrees.degrees()
        else:
            degrees = np.asarray(graph_or_degrees, np.int64)
        V, D = len(degrees), self.vector_size
        codes, points, n_inner = huffman_codes(np.maximum(degrees, 1))
        max_code = max((len(c) for c in codes), default=1) or 1
        self._codes_tbl = np.zeros((V, max_code), np.int32)
        self._points_tbl = np.zeros((V, max_code), np.int32)
        self._cmask_tbl = np.zeros((V, max_code), np.float32)
        for i, (c, p) in enumerate(zip(codes, points)):
            self._codes_tbl[i, : len(c)] = c
            self._points_tbl[i, : len(c)] = p
            self._cmask_tbl[i, : len(c)] = 1.0
        rng = np.random.RandomState(self.seed)
        # Reference init (InMemoryGraphLookupTable): small uniform vectors,
        # zero inner-node weights.
        self._syn0 = jnp.asarray(
            ((rng.rand(V, D) - 0.5) / D).astype(np.float32))
        self._syn1 = jnp.zeros((n_inner, D), jnp.float32)
        self._walk_rng = rng
        self._init_called = True
        return self

    # ---------------------------------------------------------------- fit

    def fit(self, graph: Graph, walk_length: int = 40) -> "DeepWalk":
        if not self._init_called:
            self.initialize(graph)
        w = self.window_size
        B = self.batch_size
        lr = jnp.float32(self.learning_rate)

        for _ in range(self.epochs):
            walks = random_walks(
                graph, walk_length, rng=self._walk_rng,
                no_edge_handling=self.no_edge_handling,
                weighted=self.weighted_walks)
            centers, targets = self._skipgram_pairs(walks, w)
            n = len(centers)
            for start in range(0, n, B):
                c = centers[start:start + B]
                t = targets[start:start + B]
                fill = len(c)
                bc = np.zeros(B, np.int32)
                bt = np.zeros(B, np.int32)
                pm = np.zeros(B, np.float32)
                bc[:fill] = c
                bt[:fill] = t
                pm[:fill] = 1.0
                self._syn0, self._syn1 = hs_skipgram_step(
                    self._syn0, self._syn1, jnp.asarray(bc),
                    jnp.asarray(self._codes_tbl[bt]),
                    jnp.asarray(self._points_tbl[bt]),
                    jnp.asarray(self._cmask_tbl[bt]), jnp.asarray(pm), lr)
        GraphVectors.__init__(self, np.asarray(self._syn0))
        return self

    @staticmethod
    def _skipgram_pairs(walks: np.ndarray, window: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """Flatten walks into (center, target) pairs with the reference's
        exact window rule (`DeepWalk.skipGram`: mid ranges over
        [window, len-window), pairing walk[mid] with walk[mid±1..window])."""
        B, L = walks.shape
        mids = np.arange(window, L - window)
        if len(mids) == 0:
            return np.zeros(0, np.int32), np.zeros(0, np.int32)
        offsets = np.concatenate([np.arange(-window, 0), np.arange(1, window + 1)])
        centers = np.repeat(walks[:, mids], len(offsets), axis=1).reshape(-1)
        targets = walks[:, (mids[:, None] + offsets[None, :]).reshape(-1)].reshape(-1)
        return centers.astype(np.int32), targets.astype(np.int32)
