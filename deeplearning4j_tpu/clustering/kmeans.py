"""K-means clustering.

Equivalent of the reference's `clustering/kmeans/KMeansClustering.java` +
`clustering/algorithm/BaseClusteringAlgorithm.java` (iterative
assign/recompute-center strategy with a max-iteration / distance-variation
termination). The reference loops point-at-a-time over Java cluster
objects; here one Lloyd iteration is a single jitted program — an [N, K]
distance matrix on the MXU, argmin assignment, and segment-sum centroid
recomputation — scanned for `max_iterations` steps on device.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class ClusterSet(NamedTuple):
    """Result container (reference: `clustering/cluster/ClusterSet.java`)."""

    centers: np.ndarray        # [K, D]
    assignments: np.ndarray    # [N] cluster index per point
    distances: np.ndarray      # [N] distance of each point to its center
    iterations_done: int


@partial(jax.jit, static_argnums=(2, 3))
def _lloyd(points, centers0, max_iterations, cosine):
    """Scan of Lloyd iterations. Empty clusters keep their previous center
    (the reference re-uses the most-spread cluster's point; keeping the
    center is the static-shape equivalent that cannot lose clusters)."""
    N, D = points.shape
    K = centers0.shape[0]
    pp = jnp.sum(points * points, axis=1)

    def dist2(centers):
        if cosine:
            pn = points / jnp.maximum(
                jnp.linalg.norm(points, axis=1, keepdims=True), 1e-12)
            cn = centers / jnp.maximum(
                jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12)
            return 1.0 - pn @ cn.T
        cc = jnp.sum(centers * centers, axis=1)
        return pp[:, None] - 2.0 * points @ centers.T + cc[None, :]

    def step(centers, _):
        d = dist2(centers)
        assign = jnp.argmin(d, axis=1)
        sums = jax.ops.segment_sum(points, assign, num_segments=K)
        counts = jax.ops.segment_sum(jnp.ones((N,), points.dtype), assign,
                                     num_segments=K)
        new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0),
                        centers)
        return new, None

    centers, _ = jax.lax.scan(step, centers0, None, length=max_iterations)
    d = dist2(centers)
    assign = jnp.argmin(d, axis=1)
    best = jnp.take_along_axis(d, assign[:, None], axis=1)[:, 0]
    return centers, assign, jnp.sqrt(jnp.maximum(best, 0.0)) if not cosine else best


class KMeansClustering:
    """`KMeansClustering.setup(k, maxIterations, distanceFunction)` parity.

    distance_function: "euclidean" (default) or "cosine" (the reference
    passes an ND4J distance-function name through `ClusteringStrategy`).
    """

    def __init__(self, k: int, max_iterations: int = 100,
                 distance_function: str = "euclidean", seed: int = 12345,
                 n_init: int = 3):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self.max_iterations = max_iterations
        self.distance_function = distance_function
        self.seed = seed
        self.n_init = n_init

    @classmethod
    def setup(cls, k: int, max_iterations: int = 100,
              distance_function: str = "euclidean",
              seed: int = 12345, n_init: int = 3) -> "KMeansClustering":
        return cls(k, max_iterations, distance_function, seed, n_init)

    def apply_to(self, points: np.ndarray) -> ClusterSet:
        points = np.asarray(points, np.float32)
        N = len(points)
        if N < self.k:
            raise ValueError(f"need >= k={self.k} points, got {N}")
        rng = np.random.RandomState(self.seed)
        cosine = self.distance_function == "cosine"
        pts = jnp.asarray(points)
        best: Optional[ClusterSet] = None
        best_inertia = np.inf
        # Restart `n_init` times from distinct k-means++ seedings and keep
        # the lowest-inertia run (Lloyd only finds local optima; the
        # reference samples random initial centers once — ++ with restarts
        # strictly improves on that and stays deterministic).
        def seed_dist(c):
            # ++ seeding uses the RUN's OWN metric: squared Euclidean for
            # euclidean runs, (1 - cosine similarity) for cosine runs —
            # a Euclidean D^2 would mis-seed cosine clusterings by vector
            # magnitude.
            if cosine:
                num = points @ c
                den = (np.linalg.norm(points, axis=1)
                       * max(np.linalg.norm(c), 1e-12)) + 1e-12
                return np.maximum(1.0 - num / den, 0.0)
            return np.sum((points - c) ** 2, axis=1)

        for _ in range(max(self.n_init, 1)):
            centers = [points[rng.randint(N)]]
            # Running elementwise minimum: one distance pass per new center
            # (O(K*N)) instead of re-scanning every chosen center (O(K^2*N)).
            d2 = seed_dist(centers[0])
            for _ in range(1, self.k):
                total = d2.sum()
                if total > 0:
                    c = points[rng.choice(N, p=d2 / total)]
                else:  # all remaining points coincide with a chosen center
                    c = points[rng.randint(N)]
                centers.append(c)
                d2 = np.minimum(d2, seed_dist(c))
            c, a, d = _lloyd(pts, jnp.asarray(np.stack(centers)),
                             self.max_iterations, cosine)
            inertia = float(jnp.sum(d * d))
            if inertia < best_inertia:
                best_inertia = inertia
                best = ClusterSet(np.asarray(c), np.asarray(a), np.asarray(d),
                                  self.max_iterations)
        return best
