"""Vantage-point tree.

Equivalent of the reference's `clustering/vptree/VPTree.java` (metric-space
nearest-neighbor structure; the reference uses it to find input-space
neighbors for Barnes-Hut t-SNE). Build: pick a vantage point, split the
remainder at the median distance into inside/outside balls; search prunes
balls by the triangle inequality.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class _VPNode:
    __slots__ = ("idx", "mu", "inside", "outside")

    def __init__(self, idx: int, mu: float):
        self.idx = idx
        self.mu = mu
        self.inside: Optional["_VPNode"] = None
        self.outside: Optional["_VPNode"] = None


class VPTree:
    """VP-tree over a fixed point set with euclidean or cosine distance
    (reference `VPTree(items, distanceFunction)`)."""

    def __init__(self, points: np.ndarray, distance_function: str = "euclidean",
                 seed: int = 12345):
        self.points = np.asarray(points, np.float64)
        self.distance_function = distance_function
        if distance_function == "cosine":
            norms = np.linalg.norm(self.points, axis=1, keepdims=True)
            self._unit = self.points / np.maximum(norms, 1e-12)
        rng = np.random.RandomState(seed)
        self._root = self._build(np.arange(len(self.points)), rng)

    def _dist(self, i: int, idx: np.ndarray) -> np.ndarray:
        if self.distance_function == "cosine":
            return 1.0 - self._unit[idx] @ self._unit[i]
        return np.linalg.norm(self.points[idx] - self.points[i], axis=1)

    def _dist_to_query(self, q: np.ndarray, idx: int) -> float:
        if self.distance_function == "cosine":
            qn = q / max(np.linalg.norm(q), 1e-12)
            return float(1.0 - self._unit[idx] @ qn)
        return float(np.linalg.norm(self.points[idx] - q))

    def _build(self, idx: np.ndarray, rng) -> Optional[_VPNode]:
        if len(idx) == 0:
            return None
        vp_pos = rng.randint(len(idx))
        vp = int(idx[vp_pos])
        rest = np.delete(idx, vp_pos)
        if len(rest) == 0:
            return _VPNode(vp, 0.0)
        d = self._dist(vp, rest)
        mu = float(np.median(d))
        node = _VPNode(vp, mu)
        inside = d < mu
        if not inside.any():
            # mu == min(d): ties at the median. Move the tied points inside
            # (d <= mu keeps the pruning inequalities valid on both sides).
            inside = d <= mu
            if inside.all():
                # ALL distances equal mu: every point sits exactly on the
                # boundary, so any partition satisfies both pruning bounds —
                # split by index to guarantee O(log N) depth on
                # duplicate-heavy data instead of recursing once per point.
                half = len(rest) // 2
                node.inside = self._build(rest[:half], rng)
                node.outside = self._build(rest[half:], rng)
                return node
        node.inside = self._build(rest[inside], rng)
        node.outside = self._build(rest[~inside], rng)
        return node

    def knn(self, query: np.ndarray, k: int) -> List[Tuple[float, int]]:
        """k nearest (distance, index) pairs, ascending."""
        query = np.asarray(query, np.float64)
        best: List[Tuple[float, int]] = []

        def visit(node):
            if node is None:
                return
            d = self._dist_to_query(query, node.idx)
            if len(best) < k or d < best[-1][0]:
                best.append((d, node.idx))
                best.sort(key=lambda t: t[0])
                del best[k:]
            tau = best[-1][0] if len(best) == k else np.inf
            if d < node.mu:
                visit(node.inside)
                if d + tau >= node.mu:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau <= node.mu:
                    visit(node.inside)

        visit(self._root)
        return best
