"""KD-tree for nearest-neighbor search.

Equivalent of the reference's `clustering/kdtree/KDTree.java` (insert,
nearest-neighbor, k-NN, range search over axis-aligned splits). A KD-tree
is a host-side search structure in the reference too (pure Java over
INDArray rows); the TPU framework keeps it host-side as a batch-build
median-split tree over numpy arrays — device work is only worthwhile for
the brute-force path, which `knn_brute` provides via one [Q, N] distance
matrix for large batches of queries.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("point_idx", "axis", "left", "right")

    def __init__(self, point_idx: int, axis: int):
        self.point_idx = point_idx
        self.axis = axis
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    """Median-split KD-tree. `insert` parity with the reference plus a bulk
    constructor (`KDTree(points)`) that builds a balanced tree."""

    def __init__(self, points: Optional[np.ndarray] = None, dims: Optional[int] = None):
        if isinstance(points, int) and dims is None:
            points, dims = None, points  # KDTree(3) == KDTree(dims=3)
        if points is not None:
            points = np.asarray(points, np.float64)
            self.dims = points.shape[1]
            self._points: List[np.ndarray] = list(points)
            # Balanced bulk build via recursive median split.
            self._root = self._build(points, np.arange(len(points)), 0)
        else:
            if dims is None:
                raise ValueError("provide points or dims")
            self.dims = dims
            self._points = []
            self._root = None

    def _build(self, points: np.ndarray, idx: np.ndarray, depth: int):
        if len(idx) == 0:
            return None
        axis = depth % self.dims
        order = idx[np.argsort(points[idx, axis], kind="stable")]
        mid = len(order) // 2
        node = _Node(int(order[mid]), axis)
        node.left = self._build(points, order[:mid], depth + 1)
        node.right = self._build(points, order[mid + 1:], depth + 1)
        return node

    # ------------------------------------------------------------- insert

    def insert(self, point: np.ndarray) -> None:
        point = np.asarray(point, np.float64)
        idx = len(self._points)
        self._points.append(point)
        if self._root is None:
            self._root = _Node(idx, 0)
            return
        node = self._root
        depth = 0
        while True:
            axis = depth % self.dims
            if point[axis] < self._points[node.point_idx][axis]:
                if node.left is None:
                    node.left = _Node(idx, (depth + 1) % self.dims)
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = _Node(idx, (depth + 1) % self.dims)
                    return
                node = node.right
            depth += 1

    def size(self) -> int:
        return len(self._points)

    # ------------------------------------------------------------ queries

    def nn(self, query: np.ndarray) -> Tuple[float, np.ndarray]:
        """Nearest neighbor: (distance, point) — reference `KDTree.nn`."""
        d, i = self.knn_indices(query, 1)[0]
        return d, self._points[i]

    def knn(self, query: np.ndarray, k: int) -> List[Tuple[float, np.ndarray]]:
        return [(d, self._points[i]) for d, i in self.knn_indices(query, k)]

    def knn_indices(self, query: np.ndarray, k: int) -> List[Tuple[float, int]]:
        if not self._points:
            raise ValueError("query on an empty KDTree (add points first)")
        query = np.asarray(query, np.float64)
        best: List[Tuple[float, int]] = []  # kept sorted, max size k
        # Explicit stack instead of recursion: an insert-built tree can be a
        # depth-N spine (no rebalancing), which would blow the recursion
        # limit. Entries are (node, plane_distance); the plane check is
        # re-evaluated at pop time against the now-tighter k-th best.
        stack = [(self._root, 0.0)]
        while stack:
            node, plane = stack.pop()
            if node is None:
                continue
            if len(best) == k and plane >= best[-1][0]:
                continue  # pruned: splitting plane farther than k-th best
            p = self._points[node.point_idx]
            d = float(np.linalg.norm(query - p))
            if len(best) < k or d < best[-1][0]:
                best.append((d, node.point_idx))
                best.sort(key=lambda t: t[0])
                del best[k:]
            diff = query[node.axis] - p[node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            # Push far first so the near side is explored first (LIFO).
            stack.append((far, abs(diff)))
            stack.append((near, 0.0))
        return best


def knn_brute(points: np.ndarray, queries: np.ndarray, k: int
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Brute-force batched k-NN: one [Q, N] distance matrix (the MXU-shaped
    path for large query batches). Returns (distances [Q,k], indices [Q,k])."""
    points = np.asarray(points, np.float64)
    queries = np.asarray(queries, np.float64)
    d2 = (np.sum(queries ** 2, axis=1)[:, None]
          - 2.0 * queries @ points.T + np.sum(points ** 2, axis=1)[None, :])
    idx = np.argsort(d2, axis=1)[:, :k]
    d = np.sqrt(np.maximum(np.take_along_axis(d2, idx, axis=1), 0.0))
    return d, idx
