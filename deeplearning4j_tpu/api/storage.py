"""Training-stats storage and routing.

TPU-native equivalent of the reference's `api/storage/` abstraction
(`StatsStorage.java`, `StatsStorageRouter.java`, `Persistable`) that carries
`StatsListener` reports to the UI/analysis layer. The reference SBE-encodes
records and routes them to in-memory/file/remote-HTTP sinks; here records
are plain JSON-able dicts and the sinks are in-memory and JSONL-file — the
formats a human (or the bundled UI server) can read directly.

A record is a dict with at least: `session_id`, `worker_id`, `timestamp`
(ms), `iteration`, and either `static: true` (model metadata, once per run)
or sampled stats fields (score, norms, timings, memory).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


class StatsStorageRouter:
    """Write-side interface (reference: `StatsStorageRouter.java`)."""

    def put_static_info(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def put_update(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read-side additions (reference: `StatsStorage.java`)."""

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def get_updates(self, session_id: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[Dict[str, Any]]:
        updates = self.get_updates(session_id)
        return updates[-1] if updates else None


def _stamp(record: Dict[str, Any]) -> Dict[str, Any]:
    record.setdefault("timestamp", int(time.time() * 1000))
    return record


class InMemoryStatsStorage(StatsStorage):
    """Reference: `InMemoryStatsStorage.java`. Thread-safe append/query."""

    def __init__(self):
        self._lock = threading.Lock()
        self._static: Dict[str, Dict[str, Any]] = {}
        self._updates: Dict[str, List[Dict[str, Any]]] = {}

    def put_static_info(self, record):
        with self._lock:
            self._static[record["session_id"]] = _stamp(dict(record))

    def put_update(self, record):
        with self._lock:
            self._updates.setdefault(record["session_id"], []).append(
                _stamp(dict(record)))

    def list_session_ids(self):
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_updates(self, session_id):
        with self._lock:
            return list(self._updates.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """JSONL file sink+source (reference: `FileStatsStorage.java` — the
    reference uses MapDB binary; JSONL keeps records human-plottable)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        if not os.path.exists(path):
            with open(path, "w"):
                pass

    def _append(self, record: Dict[str, Any]) -> None:
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")

    def put_static_info(self, record):
        rec = _stamp(dict(record))
        rec["static"] = True
        self._append(rec)

    def put_update(self, record):
        self._append(_stamp(dict(record)))

    def _iter_records(self) -> Iterator[Dict[str, Any]]:
        with self._lock, open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue

    def list_session_ids(self):
        return sorted({r.get("session_id") for r in self._iter_records()
                       if r.get("session_id")})

    def get_static_info(self, session_id):
        out = None
        for r in self._iter_records():
            if r.get("session_id") == session_id and r.get("static"):
                out = r
        return out

    def get_updates(self, session_id):
        return [r for r in self._iter_records()
                if r.get("session_id") == session_id and not r.get("static")]


class RemoteStatsStorageRouter(StatsStorageRouter):
    """HTTP-POST routing to a remote `UIServer` (reference:
    `api/storage/impl/RemoteUIStatsStorageRouter.java` — async posting with
    bounded retries so a dead UI never stalls training). Records are queued
    and shipped by a daemon thread to `<url>/remote`; after `retry_count`
    consecutive failures a record is dropped (the reference's
    `maxRetryCount` shutdown analog, minus killing the router).

    The whole point on a pod: training runs in one process/host, the UI
    watches from another — `UIServer(enable_remote=True)` is the receiver.
    """

    def __init__(self, url: str, retry_count: int = 5,
                 retry_delay_seconds: float = 1.0, queue_size: int = 1000):
        import queue

        self.url = url.rstrip("/")
        self.retry_count = int(retry_count)
        self.retry_delay_seconds = float(retry_delay_seconds)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._closed = False
        self._drop_lock = threading.Lock()  # `dropped` is bumped from both
        self.dropped = 0                    # the worker and caller threads
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _post(self, payload: Dict[str, Any]) -> None:
        import urllib.request

        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.url + "/remote", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()

    def _worker(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                for attempt in range(self.retry_count):
                    try:
                        self._post(item)
                        break
                    except Exception:
                        time.sleep(self.retry_delay_seconds * (attempt + 1))
                else:
                    with self._drop_lock:
                        self.dropped += 1
            finally:
                self._queue.task_done()  # incl. the close sentinel

    def _enqueue(self, payload: Dict[str, Any]) -> None:
        if self._closed:
            raise RuntimeError("router is closed")
        try:
            self._queue.put_nowait(payload)
        except Exception:
            with self._drop_lock:
                self.dropped += 1  # bounded queue full: drop, never block

    def put_static_info(self, record):
        self._enqueue({"type": "static", "record": _stamp(dict(record))})

    def put_update(self, record):
        self._enqueue({"type": "update", "record": _stamp(dict(record))})

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until everything queued so far has been shipped OR dropped
        (tests / orderly shutdown). Honors `timeout` even while the worker
        is mid-retry: join() runs on a side thread we wait on."""
        done = threading.Event()

        def join_then_set():
            self._queue.join()
            done.set()

        t = threading.Thread(target=join_then_set, daemon=True)
        t.start()
        if not done.wait(timeout):
            raise TimeoutError("remote stats queue did not drain")

    def close(self) -> None:
        """Never blocks on a full queue: queued-but-unsent records are
        dropped in favor of a prompt shutdown (the class's contract is to
        never stall training)."""
        self._closed = True
        while True:
            try:
                self._queue.put_nowait(None)
                return
            except Exception:
                try:  # make room by dropping the oldest queued record
                    self._queue.get_nowait()
                    self._queue.task_done()
                    with self._drop_lock:
                        self.dropped += 1
                except Exception:
                    time.sleep(0.01)
