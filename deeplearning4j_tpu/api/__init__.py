"""Cross-cutting service APIs (reference: `deeplearning4j-core/.../api/`)."""

from deeplearning4j_tpu.api.storage import (  # noqa: F401
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsStorage,
    StatsStorageRouter,
)
