"""Failure detection and in-place recovery.

The reference has essentially no failure-detection story (SURVEY.md §5
names this a gap the TPU build should EXCEED: its Spark layer retries
partitions, nothing watches training health). This module adds the
TPU-native version: a listener that watches the training score for
divergence (NaN/inf) and, when it fires, rolls the LIVE network back to
the newest HEALTHY checkpoint written by `CheckpointListener` — params,
updater state, iteration/epoch counters, and the RNG continuation — so the
training loop keeps running without re-construction or host restart.

Composes with `util/checkpoint.py`'s async checkpointing: the
CheckpointListener provides the rollback targets; this listener validates
a candidate's params AND updater state are finite before restoring (with
momentum-family updaters the optimizer state typically goes non-finite a
step before the params do, so a params-only check would pick a checkpoint
that re-diverges immediately).
"""

from __future__ import annotations

import zipfile
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.util import checkpoint as ckpt_mod
from deeplearning4j_tpu.util import model_serializer


class TrainingDivergedError(RuntimeError):
    """Raised when divergence persists past `max_recoveries` rollbacks."""


def restore_in_place(net, path: str) -> None:
    """Load a checkpoint INTO an existing network object (same config):
    params, updater state, iteration/epoch, RNG continuation. Keeps every
    external reference to `net` (listeners, wrappers, user code) valid —
    the recovery path must not swap object identity."""
    fresh = ckpt_mod.load_checkpoint(path)
    net.params_tree = fresh.params_tree
    net.state = fresh.state
    net.opt_state = fresh.opt_state
    net.iteration = fresh.iteration
    net.epoch = fresh.epoch
    net._train_rng = fresh._train_rng
    net._clock = None
    net._score = None  # score_value reads nan until the next step reports


def _checkpoint_healthy(path: str) -> bool:
    """True if every parameter AND updater-state value in the checkpoint
    is finite. Handles both formats: a sharded checkpoint directory
    (chunks are scanned leaf-by-leaf — never assembling more than one
    leaf on host) and the `model_serializer` ZIP (float64 raw bytes)."""
    import os

    if os.path.isdir(path):
        from deeplearning4j_tpu.checkpoint import store as sharded_store

        try:
            sharded_store.verify_checkpoint(path)
            index = sharded_store.read_index(path)
            for key, entry in index["leaves"].items():
                if not (key.startswith("params/")
                        or key.startswith("updater/")):
                    continue
                if not np.all(np.isfinite(
                        sharded_store.read_full(path, entry))):
                    return False
            return True
        except Exception:
            return False
    try:
        with zipfile.ZipFile(path) as z:
            names = set(z.namelist())
            params = np.frombuffer(
                z.read(model_serializer.COEFFICIENTS), np.float64)
            if not np.all(np.isfinite(params)):
                return False
            if model_serializer.UPDATER_STATE in names:
                upd = np.frombuffer(
                    z.read(model_serializer.UPDATER_STATE), np.float64)
                if not np.all(np.isfinite(upd)):
                    return False
        return True
    except Exception:
        return False


class FailureDetectionListener(IterationListener):
    """Watchdog: every `check_frequency` iterations inspect the score; on
    NaN/inf, roll back to the newest healthy checkpoint and keep training.

    The score inspected is the one from the PREVIOUS check interval — by
    the time the next check fires it has long since materialized, so the
    watchdog never blocks the dispatch pipeline the way an immediate
    `float(score)` would (the train loop deliberately defers all syncs;
    `nn/multilayer.py::score_value`). Detection therefore lags one
    interval; the healthy-checkpoint walk absorbs any checkpoint written
    inside that lag.

    `checkpoints`: the CheckpointListener supplying rollback targets
    (attach it BEFORE this listener so snapshots precede checks).
    """

    def __init__(self, checkpoints: ckpt_mod.CheckpointListener, *,
                 check_frequency: int = 10, max_recoveries: int = 3):
        self.checkpoints = checkpoints
        self.check_frequency = max(1, int(check_frequency))
        self.max_recoveries = int(max_recoveries)
        self.recoveries = 0
        self.recovery_log: List[dict] = []
        self._pending = None  # (iteration, device score) from last check

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.check_frequency:
            return
        previous, self._pending = self._pending, (iteration, model._score)
        if previous is None:
            return
        prev_iter, prev_score = previous
        score = float("nan") if prev_score is None else float(prev_score)
        if np.isfinite(score):
            return
        self._recover(model, prev_iter, score)

    # ------------------------------------------------------------- recovery

    def _recover(self, model, iteration: int, score: float) -> None:
        if self.recoveries >= self.max_recoveries:
            raise TrainingDivergedError(
                f"score {score} at iteration {iteration} after "
                f"{self.recoveries} recoveries — giving up")
        self.checkpoints.flush()  # drain any in-flight write first
        target = self._newest_healthy()
        if target is None:
            raise TrainingDivergedError(
                f"score {score} at iteration {iteration} and no healthy "
                "checkpoint to roll back to")
        restore_in_place(model, target)
        self._pending = None
        # Checkpoints newer than the restore point capture diverged (or
        # soon-to-diverge) state; drop them so a second recovery doesn't
        # land on one, and so the replayed iterations re-checkpoint.
        keep, drop = [], []
        for p in self.checkpoints.saved_paths:
            (keep if p == target or not self._newer_than(p, model.iteration)
             else drop).append(p)
        self.checkpoints.saved_paths[:] = keep
        self.recoveries += 1
        self.recovery_log.append({
            "detected_at_iteration": iteration,
            "restored_from": target,
            "restored_iteration": model.iteration,
            "bad_score": score,
            "dropped_checkpoints": drop,
        })

    @staticmethod
    def _newer_than(path: str, iteration: int) -> bool:
        try:
            import json
            import os

            if os.path.isdir(path):
                from deeplearning4j_tpu.checkpoint import store as sstore

                manifest = sstore.read_meta(path)
            else:
                with zipfile.ZipFile(path) as z:
                    manifest = json.loads(z.read(model_serializer.MANIFEST))
            return int(manifest.get("iteration", -1)) > iteration
        except Exception:
            return True  # unreadable: treat as stale and drop

    def _newest_healthy(self) -> Optional[str]:
        for path in reversed(self.checkpoints.saved_paths):
            if _checkpoint_healthy(path):
                return path
        return None
