"""Shared Huffman-coding core.

One heap-based builder serving both hierarchical-softmax users: word2vec's
frequency-keyed tree (reference: `models/word2vec/wordstore/Huffman.java`,
MAX_CODE_LENGTH 40) and DeepWalk's vertex-degree-keyed tree (reference:
`graph/models/deepwalk/GraphHuffman.java`, codes packed in a 64-bit long).
"""

from __future__ import annotations

import heapq
import itertools
from typing import List, Sequence, Tuple


def huffman_codes(freqs: Sequence[float], max_code_length: int = 64
                  ) -> Tuple[List[List[int]], List[List[int]], int]:
    """Build Huffman codes/points over arbitrary frequencies.

    Returns (codes, points, n_inner): codes[i] is leaf i's bit path from
    the root, points[i] the inner-node indices along it (0-based into the
    syn1 table), n_inner the number of inner nodes (>= 1).
    """
    n = len(freqs)
    if n == 0:
        return [], [], 0
    if n == 1:
        return [[0]], [[0]], 1
    counter = itertools.count()
    heap = [(float(f), next(counter), i) for i, f in enumerate(freqs)]
    heapq.heapify(heap)
    parent = {}
    next_inner = n
    while len(heap) > 1:
        f1, _, n1 = heapq.heappop(heap)
        f2, _, n2 = heapq.heappop(heap)
        inner = next_inner
        next_inner += 1
        parent[n1] = (inner, 0)
        parent[n2] = (inner, 1)
        heapq.heappush(heap, (f1 + f2, next(counter), inner))
    root = heap[0][2]
    codes, points = [], []
    for i in range(n):
        c, p = [], []
        node = i
        while node != root:
            par, bit = parent[node]
            c.append(bit)
            p.append(par - n)
            node = par
        c.reverse()
        p.reverse()
        codes.append(c[:max_code_length])
        points.append(p[:max_code_length])
    return codes, points, max(next_inner - n, 1)
