"""Model checkpointing.

Equivalent of the reference's `util/ModelSerializer.java:43,84-148`: a ZIP
container with `configuration.json` (full model config — the JSON round-trip
is load-bearing), `coefficients.bin` (the flattened contiguous param view),
and `updaterState.bin` (flat optimizer state). This build adds `state.npz`
(batchnorm running stats / center-loss centers — state the reference keeps
inside params) and a `manifest.json` with format/version/engine type.

The flat binary views keep the reference's two-buffer-dump property: a
checkpoint is two contiguous arrays plus JSON, trivially shardable and
portable. Arrays are little-endian float32/float64 raw bytes.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Optional, Union

import numpy as np

MANIFEST = "manifest.json"
CONFIGURATION = "configuration.json"
COEFFICIENTS = "coefficients.bin"
UPDATER_STATE = "updaterState.bin"
EXTRA_STATE = "state.npz"


def save_model(net, path: Union[str, os.PathLike], save_updater: bool = True) -> None:
    """Write a model ZIP (reference: `ModelSerializer.writeModel`)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    kind = "ComputationGraph" if isinstance(net, ComputationGraph) else "MultiLayerNetwork"
    params = net.params().astype(np.float64)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(MANIFEST, json.dumps({
            "format": "deeplearning4j_tpu/model-zip",
            "version": 1,
            "engine": kind,
            "param_dtype": "float64",
            "num_params": int(params.size),
            "iteration": int(net.iteration),
            "epoch": int(net.epoch),
        }))
        z.writestr(CONFIGURATION, net.conf.to_json())
        z.writestr(COEFFICIENTS, params.tobytes())
        if save_updater and net.opt_state is not None:
            z.writestr(UPDATER_STATE, net.updater_state_flat().astype(np.float64).tobytes())
        if net.state:
            buf = io.BytesIO()
            flat = {}
            for lk, sub in net.state.items():
                for k, v in sub.items():
                    flat[f"{lk}/{k}"] = np.asarray(v)
            np.savez(buf, **flat)
            z.writestr(EXTRA_STATE, buf.getvalue())


def load_model(path: Union[str, os.PathLike], load_updater: bool = True):
    """Restore a model ZIP (reference: `ModelSerializer.restoreMultiLayerNetwork` /
    `restoreComputationGraph` — the engine kind is detected from the manifest)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.neural_net import (
        ComputationGraphConfiguration,
        MultiLayerConfiguration,
    )

    with zipfile.ZipFile(path, "r") as z:
        manifest = json.loads(z.read(MANIFEST))
        conf_json = z.read(CONFIGURATION).decode()
        if manifest["engine"] == "ComputationGraph":
            conf = ComputationGraphConfiguration.from_json(conf_json)
            net = ComputationGraph(conf).init()
        else:
            conf = MultiLayerConfiguration.from_json(conf_json)
            net = MultiLayerNetwork(conf).init()
        flat = np.frombuffer(z.read(COEFFICIENTS), dtype=np.float64)
        net.set_params(flat)
        if load_updater and UPDATER_STATE in z.namelist():
            net.set_updater_state_flat(
                np.frombuffer(z.read(UPDATER_STATE), dtype=np.float64))
        if EXTRA_STATE in z.namelist():
            loaded = np.load(io.BytesIO(z.read(EXTRA_STATE)))
            for key in loaded.files:
                lk, k = key.split("/", 1)
                if lk in net.state and k in net.state[lk]:
                    net.state[lk][k] = jnp.asarray(loaded[key], net.state[lk][k].dtype)
        net.iteration = int(manifest.get("iteration", 0))
        net.epoch = int(manifest.get("epoch", 0))
    return net
