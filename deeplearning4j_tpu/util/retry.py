"""Exponential-backoff retry primitive (reference analog: the Spark
training master's fault-tolerant RPC layer — `TrainingMaster` retries
worker RPCs and Aeron re-offers publications until the media driver
accepts; PAPER.md scale-out layer).

One policy object, three consumers with very different failure textures:

- elastic cluster join / coordinator RPCs (`parallel/coordinator.py`):
  the coordinator may not be up yet, or mid-reform — retry for tens of
  seconds with jitter so a restarted 256-host pod doesn't synchronize
  its reconnect stampede;
- checkpoint writes (`checkpoint/manager.py`): NFS/GCS blips are
  transient, a failed write must not kill the training loop;
- serving model reload (`serving/host.py`): a reload racing an
  atomic-rename publish sees a half-moment of ENOENT.

Deliberately dependency-free and jax-free: this must be importable from
signal handlers and worker subprocesses before jax initializes.

Knobs (the backoff envelope, PERF.md §18):

- ``DL4J_TPU_RETRY_BASE_S``  — first sleep (default 0.1s)
- ``DL4J_TPU_RETRY_MAX_S``   — per-sleep cap (default 5s)
- ``DL4J_TPU_RETRY_TRIES``   — default attempt budget (default 5)
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type, TypeVar

T = TypeVar("T")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class RetryError(Exception):
    """All attempts exhausted. ``last`` carries the final cause;
    ``elapsed`` / ``attempts`` carry the spent budget so a caller holding
    a request deadline can report exactly what the envelope cost."""

    def __init__(self, message: str, last: Optional[BaseException] = None,
                 elapsed: float = 0.0, attempts: int = 0):
        super().__init__(message)
        self.last = last
        self.elapsed = float(elapsed)
        self.attempts = int(attempts)


@dataclass
class Backoff:
    """Exponential backoff with full jitter (AWS-style: sleep is uniform
    in [0, min(cap, base * 2^attempt)] — full jitter decorrelates retry
    stampedes better than equal-jitter for thundering-herd joins).

    ``tries`` counts ATTEMPTS, not sleeps: tries=5 means 5 calls with 4
    sleeps between them. ``max_elapsed_s`` (optional) is the total
    elapsed-time budget: no sleep is taken that would push the envelope
    past it, so a caller holding a request deadline never overshoots by
    a backoff step — the router's failover path and the elastic join both
    hand their caller's deadline straight in. ``deadline_s`` is the older
    spelling of the same budget (kept for callers that already pass it);
    when both are set the tighter one wins.
    """

    base_s: float = field(
        default_factory=lambda: _env_float("DL4J_TPU_RETRY_BASE_S", 0.1))
    max_s: float = field(
        default_factory=lambda: _env_float("DL4J_TPU_RETRY_MAX_S", 5.0))
    tries: int = field(
        default_factory=lambda: _env_int("DL4J_TPU_RETRY_TRIES", 5))
    deadline_s: Optional[float] = None
    max_elapsed_s: Optional[float] = None
    jitter: bool = True
    # Injectable for deterministic tests (fault harness pins these).
    _sleep: Callable[[float], None] = field(default=time.sleep, repr=False)
    _rand: Callable[[], float] = field(default=random.random, repr=False)

    def sleep_for(self, attempt: int) -> float:
        """Sleep duration after failed attempt `attempt` (0-based)."""
        cap = min(self.max_s, self.base_s * (2.0 ** attempt))
        return cap * self._rand() if self.jitter else cap

    def _budget(self) -> Optional[float]:
        if self.deadline_s is None:
            return self.max_elapsed_s
        if self.max_elapsed_s is None:
            return self.deadline_s
        return min(self.deadline_s, self.max_elapsed_s)

    def run(self, fn: Callable[[], T], *,
            retry_on: Tuple[Type[BaseException], ...] = (Exception,),
            on_retry: Optional[Callable[[int, BaseException], None]] = None,
            describe: str = "operation") -> T:
        """Call ``fn`` until it returns, a non-retryable exception escapes,
        or the budget (tries and/or elapsed-time) runs out -> `RetryError`
        carrying ``elapsed`` and ``attempts``.

        ``on_retry(attempt, exc)`` fires before each sleep — the elastic
        client uses it to bump `dl4j_elastic_events_total` and log.
        """
        start = time.monotonic()
        budget = self._budget()
        last: Optional[BaseException] = None
        attempts = 0
        for attempt in range(max(1, self.tries)):
            attempts = attempt + 1
            try:
                return fn()
            except retry_on as exc:  # noqa: PERF203 - retry loop
                last = exc
                if attempt + 1 >= max(1, self.tries):
                    break
                pause = self.sleep_for(attempt)
                if (budget is not None
                        and time.monotonic() - start + pause > budget):
                    break
                if on_retry is not None:
                    on_retry(attempt, exc)
                self._sleep(pause)
        elapsed = time.monotonic() - start
        raise RetryError(
            f"{describe} failed after {attempts} attempts "
            f"({elapsed:.1f}s): {last!r}", last,
            elapsed=elapsed, attempts=attempts)


def with_retries(fn: Callable[[], T], *,
                 tries: Optional[int] = None,
                 base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 max_elapsed_s: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 on_retry: Optional[Callable[[int, BaseException], None]] = None,
                 describe: str = "operation") -> T:
    """Functional shorthand: ``with_retries(lambda: client.join(...))``.

    Defaults come from the env knobs via `Backoff`; explicit kwargs win.
    """
    bo = Backoff()
    if tries is not None:
        bo.tries = tries
    if base_s is not None:
        bo.base_s = base_s
    if max_s is not None:
        bo.max_s = max_s
    bo.deadline_s = deadline_s
    bo.max_elapsed_s = max_elapsed_s
    return bo.run(fn, retry_on=retry_on, on_retry=on_retry, describe=describe)
