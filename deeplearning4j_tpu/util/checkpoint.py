"""Periodic async checkpointing + exact training resume.

The SURVEY §5 exceed-goal: the reference has essentially no mid-job fault
tolerance (Spark retries tasks; nothing checkpoints a running fit —
`ParameterAveragingTrainingMaster` never persists mid-job), so this module
goes beyond parity: a `CheckpointListener` snapshots FULL training state
(params, updater state, persistent layer state, iteration/epoch, and the
train-time RNG key) every N iterations, with the file write off the
training thread; `load_checkpoint` restores a network whose continued
`fit()` reproduces the uninterrupted run bit-for-bit (same params, same
dropout/sampling randomness — the RNG continuation is part of the state).

File formats: the legacy `model_serializer` ZIP (so `load_model` can also
open a checkpoint) plus a `training/rng.npy` entry carrying the PRNG key —
or, with `format="sharded"`, the `deeplearning4j_tpu/checkpoint/` store:
per-shard chunk files + atomic COMMIT, which parallelizes save I/O and
restores elastically onto any mesh shape. `load_checkpoint` opens both
(a directory path is a sharded checkpoint, a file path a ZIP).
"""

from __future__ import annotations

import io
import json
import os
import re
import threading
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.util import model_serializer

RNG_ENTRY = "training/rng.npy"


def _current_rng_key(net) -> np.ndarray:
    """The live RNG continuation: inside the device clock once training has
    stepped, else the host-side attribute."""
    if getattr(net, "_clock", None) is not None:
        return np.asarray(net._clock[1])
    return np.asarray(net._train_rng)


def save_checkpoint(net, path, format: str = "zip") -> str:
    """Synchronous full-state checkpoint. `format="zip"`: model ZIP +
    training RNG (the listener does the same thing with the write
    off-thread). `format="sharded"`: a committed sharded checkpoint
    directory at `path` (per-shard chunks + COMMIT; see
    `deeplearning4j_tpu/checkpoint/`)."""
    if format == "sharded":
        from deeplearning4j_tpu.checkpoint import store as sharded_store

        return sharded_store.save_checkpoint(net, path)
    model_serializer.save_model(net, path, save_updater=True)
    with zipfile.ZipFile(path, "a") as z:
        buf = io.BytesIO()
        np.save(buf, _current_rng_key(net))
        z.writestr(RNG_ENTRY, buf.getvalue())
    return str(path)


def load_checkpoint(path, mesh=None, context=None):
    """Restore engine + params + updater state + iteration/epoch AND the
    RNG continuation, so the next `fit()` step is identical to what the
    checkpointed run would have executed.

    Opens both formats: a directory is a sharded checkpoint (a committed
    step, or a `CheckpointManager` root — latest committed step wins), a
    file the legacy `model_serializer` ZIP. `mesh`/`context` name a target
    placement for the sharded path (elastic restore)."""
    import jax.numpy as jnp

    if os.path.isdir(str(path)):
        from deeplearning4j_tpu.checkpoint import legacy

        return legacy.load_any(path, mesh=mesh, context=context)
    net = model_serializer.load_model(path, load_updater=True)
    with zipfile.ZipFile(path) as z:
        if RNG_ENTRY in z.namelist():
            key = np.load(io.BytesIO(z.read(RNG_ENTRY)))
            net._train_rng = jnp.asarray(key, jnp.uint32)
            net._clock = None
    return net


class CheckpointListener(IterationListener):
    """Checkpoint every `frequency` iterations, keeping the most recent
    `keep_last` files, writing off the training thread.

    The device->host snapshot happens at the iteration boundary (it must —
    the train step donates its buffers, so the arrays the checkpoint needs
    are gone one step later); the encode + disk write, which dominate
    wall time, run on a single background worker. If a write is still in
    flight when the next snapshot fires, the listener waits (bounding
    checkpoint memory to one in-flight snapshot) — with the default
    frequencies that stall is never hit.

    `format="zip"` writes the legacy single-file ZIPs; `format="sharded"`
    writes committed sharded step directories (`step_{iteration:08d}/`,
    per-shard chunk I/O + atomic COMMIT — `deeplearning4j_tpu/checkpoint/`).
    Either way `saved_paths` lists committed checkpoints oldest-first and
    `load_checkpoint` opens any entry.
    """

    def __init__(self, directory: str, frequency: int = 100,
                 keep_last: int = 3,
                 filename_pattern: str = "checkpoint_iter{iteration}.zip",
                 format: str = "zip"):
        if format not in ("zip", "sharded"):
            raise ValueError(f"format must be 'zip' or 'sharded', got {format!r}")
        self.directory = directory
        self.frequency = max(1, int(frequency))
        self.keep_last = int(keep_last)
        self.filename_pattern = filename_pattern
        self.format = format
        os.makedirs(directory, exist_ok=True)
        self._inflight: Optional[threading.Thread] = None
        self.saved_paths: List[str] = []

    # ------------------------------------------------------------ snapshot

    @staticmethod
    def _host_snapshot(net) -> Dict[str, Any]:
        import jax

        # Start all device->host copies asynchronously, then materialize.
        for leaf in jax.tree_util.tree_leaves((net.params_tree, net.opt_state,
                                               net.state)):
            try:
                leaf.copy_to_host_async()
            except AttributeError:
                pass
        return {
            "engine": type(net).__name__,
            "conf_json": net.conf.to_json(),
            "params": net.params().astype(np.float64),
            "updater": (None if net.opt_state is None
                        else net.updater_state_flat().astype(np.float64)),
            "state": {f"{lk}/{k}": np.asarray(v)
                      for lk, sub in net.state.items()
                      for k, v in sub.items()} if net.state else {},
            "iteration": int(net.iteration),
            "epoch": int(net.epoch),
            "rng": _current_rng_key(net),
        }

    @staticmethod
    def _write(snap: Dict[str, Any], path: str) -> None:
        tmp = path + ".tmp"
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr(model_serializer.MANIFEST, json.dumps({
                "format": "deeplearning4j_tpu/model-zip",
                "version": 1,
                "engine": snap["engine"],
                "param_dtype": "float64",
                "num_params": int(snap["params"].size),
                "iteration": snap["iteration"],
                "epoch": snap["epoch"],
            }))
            z.writestr(model_serializer.CONFIGURATION, snap["conf_json"])
            z.writestr(model_serializer.COEFFICIENTS, snap["params"].tobytes())
            if snap["updater"] is not None:
                z.writestr(model_serializer.UPDATER_STATE,
                           snap["updater"].tobytes())
            if snap["state"]:
                buf = io.BytesIO()
                np.savez(buf, **snap["state"])
                z.writestr(model_serializer.EXTRA_STATE, buf.getvalue())
            buf = io.BytesIO()
            np.save(buf, snap["rng"])
            z.writestr(RNG_ENTRY, buf.getvalue())
        os.replace(tmp, path)  # atomic: a crash never leaves a torn file

    def _prune(self) -> None:
        import shutil

        while self.keep_last > 0 and len(self.saved_paths) > self.keep_last:
            old = self.saved_paths.pop(0)
            try:
                if os.path.isdir(old):
                    shutil.rmtree(old)
                else:
                    os.remove(old)
            except OSError:
                pass

    # ---------------------------------------------------------------- hook

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        if self._inflight is not None:
            self._inflight.join()  # bound to one in-flight snapshot
        if self.format == "sharded":
            from deeplearning4j_tpu.checkpoint import store as sharded_store

            snap = sharded_store.snapshot_net(model)
            path = os.path.join(self.directory, f"step_{iteration:08d}")
            write = sharded_store.write_snapshot
        else:
            snap = self._host_snapshot(model)
            path = os.path.join(
                self.directory,
                self.filename_pattern.format(iteration=iteration))
            write = self._write

        def work():
            write(snap, path)
            # Record + prune only AFTER the new file is durably in place: a
            # crash mid-write must never have already deleted the previous
            # good checkpoint (keep_last=1 would otherwise leave nothing).
            # Re-checkpointing an iteration (e.g. after a failure-recovery
            # rollback replays it) must MOVE the entry, not duplicate it —
            # a duplicate would later make _prune delete a file a newer
            # entry still references.
            if path in self.saved_paths:
                self.saved_paths.remove(path)
            self.saved_paths.append(path)
            self._prune()

        self._inflight = threading.Thread(target=work, daemon=True)
        self._inflight.start()

    def on_epoch_end(self, model) -> None:
        self.flush()

    def flush(self) -> None:
        if self._inflight is not None:
            self._inflight.join()
            self._inflight = None

    def last_checkpoint(self) -> Optional[str]:
        self.flush()
        return self.saved_paths[-1] if self.saved_paths else None
