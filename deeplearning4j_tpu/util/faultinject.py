"""Deterministic fault injection for elastic-training chaos tests.

Recovery code that only runs when real hardware dies is recovery code
that doesn't work. This harness makes every failure mode in the elastic
design a *scheduled, reproducible* event: a **fault plan** is a list of
faults keyed by (training step, worker rank), evaluated at fixed
injection points inside `ElasticTrainer.run` — so "worker 1 dies at step
7" happens at exactly step 7 on exactly worker 1, every CI run.

Plan format (JSON — inline in ``DL4J_TPU_FAULT_PLAN`` or ``@/path`` to a
file; `FaultPlan.from_env()` reads it in every worker process):

    [
      {"kind": "kill",             "step": 7, "worker": 1},
      {"kind": "preempt",          "step": 4},
      {"kind": "hang_coordinator", "step": 1, "worker": 0, "seconds": 2.0},
      {"kind": "truncate_chunk",   "step": 5, "worker": 0},
      {"kind": "delay_h2d",        "step": 3, "ms": 200}
    ]

Kinds (each fires at the TOP of its step, before the local fit):

- ``kill``             — ``os._exit(137)``: hard host loss, no checkpoint,
                         no cleanup; survivors must detect via heartbeat.
- ``preempt``          — SIGTERM to self: exercises the graceful
                         preemption path (checkpoint + flight bundle +
                         coordinated exit).
- ``hang_coordinator`` — the worker hosting the coordinator stops it
                         responding for ``seconds``; peers must survive
                         via backoff-retry, and the membership reaper
                         must NOT evict anyone for a hang the
                         coordinator itself caused.
- ``truncate_chunk``   — truncates the newest committed checkpoint's
                         largest chunk file: the next restore must detect
                         corruption and fall back to the previous step.
- ``delay_h2d``        — sleeps ``ms`` before the step's dispatch
                         (models a slow host->device link; exercises
                         step-barrier timeout margins).

Fleet kinds (serving chaos; the injection point is the replica's request
admission — ``step`` is the replica-local REQUEST NUMBER and ``worker``
is the replica index, so "replica 1 dies on its 50th request" is exact
and reproducible):

- ``kill_replica``     — ``os._exit(137)`` mid-request: hard replica
                         loss; the router must detect via lease expiry
                         and fail the in-flight request over.
- ``hang_replica``     — the replica stops answering for ``seconds``
                         (in-flight requests stall, heartbeats continue
                         or stop per ``stop_heartbeats``): exercises the
                         router's per-request timeout + failover, not
                         just eviction.
- ``slow_decode``      — every subsequent request on the replica gains
                         ``ms`` of latency (models decode slowdown; the
                         least-loaded policy should shift traffic away).
- ``lock_invert``      — runs `analysis.locktrace.lock_inversion_drill`:
                         two threads forced into AB/BA lock acquisition
                         for up to ``seconds``. Requires
                         ``DL4J_TPU_LOCKTRACE=1``; asserts-by-effect
                         that the tracer flags the order cycle and the
                         stall watchdog dumps exactly one flight bundle
                         (drill results land in ``fault.args["result"]``).

``worker`` omitted means "fires on every worker". Each fault fires at
most once per process (fire-once), so a restarted worker replaying steps
after recovery does not re-inject its fault — recovery runs are clean by
construction.

Faults with side effects outside this module (hang, truncate) are
dispatched through a handler map the trainer registers, keeping the
harness free of checkpoint/coordinator imports.
"""

from __future__ import annotations

import json
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

ENV_KNOB = "DL4J_TPU_FAULT_PLAN"

KINDS = ("kill", "preempt", "hang_coordinator", "truncate_chunk",
         "delay_h2d", "kill_replica", "hang_replica", "slow_decode",
         "lock_invert")


@dataclass
class Fault:
    kind: str
    step: int
    worker: Optional[int] = None  # None -> every worker
    args: Dict[str, Any] = field(default_factory=dict)
    fired: bool = False

    def matches(self, step: int, worker: int) -> bool:
        return (not self.fired and self.step == int(step)
                and (self.worker is None or self.worker == int(worker)))


class FaultPlan:
    """An ordered list of `Fault`s plus the dispatch logic."""

    def __init__(self, faults: Optional[List[Fault]] = None):
        self.faults = list(faults or [])

    def __bool__(self) -> bool:
        return bool(self.faults)

    # ------------------------------------------------------------ parsing

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        data = json.loads(text)
        if not isinstance(data, list):
            raise ValueError("fault plan must be a JSON list of faults")
        faults = []
        for i, item in enumerate(data):
            if not isinstance(item, dict) or "kind" not in item \
                    or "step" not in item:
                raise ValueError(
                    f"fault[{i}]: each fault needs 'kind' and 'step'")
            kind = str(item["kind"])
            if kind not in KINDS:
                raise ValueError(
                    f"fault[{i}]: unknown kind {kind!r} (have {KINDS})")
            worker = item.get("worker")
            args = {k: v for k, v in item.items()
                    if k not in ("kind", "step", "worker")}
            faults.append(Fault(kind=kind, step=int(item["step"]),
                                worker=None if worker is None else int(worker),
                                args=args))
        return cls(faults)

    @classmethod
    def from_env(cls) -> "FaultPlan":
        """Empty plan when the knob is unset — production is a no-op
        (`maybe_fire` on an empty plan is one list check)."""
        raw = os.environ.get(ENV_KNOB, "").strip()
        if not raw:
            return cls()
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        return cls.from_json(raw)

    # ----------------------------------------------------------- dispatch

    def maybe_fire(self, step: int, worker: int,
                   handlers: Optional[Dict[str, Callable[[Fault], None]]]
                   = None) -> List[Fault]:
        """Fire every not-yet-fired fault matching (step, worker).

        Built-in actions for ``kill`` / ``preempt`` / ``delay_h2d``;
        ``hang_coordinator`` and ``truncate_chunk`` require a handler
        (missing handler -> the fault is skipped, marked fired, and
        reported in the return value so tests can assert on it).
        """
        fired: List[Fault] = []
        for fault in self.faults:
            if not fault.matches(step, worker):
                continue
            fault.fired = True
            fired.append(fault)
            handler = (handlers or {}).get(fault.kind)
            if handler is not None:
                handler(fault)
            elif fault.kind in ("kill", "kill_replica"):
                # Hard loss: no atexit, no flushes — mirrors a yanked host.
                os._exit(137)
            elif fault.kind == "preempt":
                os.kill(os.getpid(), signal.SIGTERM)
            elif fault.kind in ("delay_h2d", "slow_decode"):
                time.sleep(float(fault.args.get("ms", 100)) / 1000.0)
            elif fault.kind == "hang_replica":
                time.sleep(float(fault.args.get("seconds", 1.0)))
            elif fault.kind == "lock_invert":
                # Two-thread AB/BA acquisition drill: proves the lock
                # tracer flags the cycle and the stall watchdog dumps
                # its one flight bundle (requires DL4J_TPU_LOCKTRACE=1).
                from deeplearning4j_tpu.analysis import locktrace

                fault.args["result"] = locktrace.lock_inversion_drill(
                    acquire_timeout_s=float(
                        fault.args.get("seconds", 2.0)))
            # hang_coordinator / truncate_chunk without a handler: recorded
            # as fired, no action (the injection point lacks the object).
        return fired


def truncate_newest_chunk(step_dir: str, drop_bytes: int = 64) -> Optional[str]:
    """Corrupt a committed checkpoint the way interrupted storage does:
    shave ``drop_bytes`` off the END of the largest chunk file, leaving
    the manifest + COMMIT marker intact (so only the size/integrity check
    can catch it). Returns the damaged path, or None if nothing to damage.

    Used by the ``truncate_chunk`` handler and directly by tests.
    """
    best, best_size = None, -1
    for name in os.listdir(step_dir):
        if name.startswith(("manifest", "COMMIT")):
            continue
        p = os.path.join(step_dir, name)
        if os.path.isfile(p):
            size = os.path.getsize(p)
            if size > best_size:
                best, best_size = p, size
    if best is None or best_size <= 0:
        return None
    with open(best, "r+b") as f:
        f.truncate(max(0, best_size - int(drop_bytes)))
    return best
