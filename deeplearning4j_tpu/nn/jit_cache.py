"""Shared jit-program cache for the two engines.

`MultiLayerNetwork._get_jit` and `ComputationGraph._get_jit` used to carry
near-identical copies of the cache-key construction + lookup; both now
delegate here, and the compile-cache store (`compilation/`) hooks in once
instead of twice.

The cache key is ``(kind, sorted static args, context_cache_key(),
kernels.config_key())``: the active `ParallelContext` selects which
program a layer traces (ring vs flash attention, expert-sharded vs local
MoE), and the kernel-registry env config selects which implementation
each dispatch seam resolves (Pallas vs XLA fallback, `kernels/
registry.py`), so both are part of the program identity — the same net
can train sharded and unsharded, or fused and fallback, in one process
without stale programs. Folding the kernel config in HERE is also the
"hoist to signature level" fix: a restacked superstep block with an
already-seen signature is a cache hit, so kernel resolution (and its
`is_available` probes) never re-runs per block. Superstep `k`/`scan` arrive through
`static`, so each distinct block length is its own cached program (the
StepProfiler's jit-cache-growth heuristic relies on that to classify a
tail block's first call as compile).

When the compile cache is enabled (`DL4J_TPU_COMPILE_CACHE`, on by
default) each freshly built program is wrapped in a
`compilation.CachedProgram`, which consults the fingerprinted AOT
executable store before the first trace and writes back on miss; when
disabled, the raw jitted callable is cached — byte-for-byte the old
behavior.
"""

from __future__ import annotations

from deeplearning4j_tpu import compilation as _compilation
from deeplearning4j_tpu.kernels import registry as _kernels_registry
from deeplearning4j_tpu.parallel.context import context_cache_key


def get_jit(net, hit_metric, miss_metric, kind: str, **static):
    """Cached program lookup for one engine instance (see module
    docstring). `hit_metric`/`miss_metric` are the engine's labeled
    jit-cache counters."""
    key = (kind, tuple(sorted(static.items())), context_cache_key(),
           _kernels_registry.config_key())
    fn = net._jit_cache.get(key)
    if fn is not None:
        hit_metric.inc()
        return fn
    miss_metric.inc()
    fn = _compilation.wrap_program(net._build_jit(kind, **static),
                                   net, kind, static)
    net._jit_cache[key] = fn
    return fn
