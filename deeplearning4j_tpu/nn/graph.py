"""ComputationGraph: the DAG network engine.

Equivalent of the reference's `nn/graph/ComputationGraph.java` (2276 LoC) +
`nn/graph/vertex/` — arbitrary-DAG, multi-input/multi-output networks. The
topological order is computed once from the config (reference `:283,851`) and
the whole graph traverses at trace time into a single jitted program; vertex
objects never exist at runtime.
"""

from __future__ import annotations

import copy
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import activations as activations_mod
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn import params as params_mod
from deeplearning4j_tpu.nn.conf.enums import (
    BackpropType,
    OptimizationAlgorithm,
)
from deeplearning4j_tpu.nn.conf.graph import (
    DuplicateToTimeSeriesVertex,
    LastTimeStepVertex,
    LayerVertex,
)
from deeplearning4j_tpu.nn.conf.dtype_policy import resolve_policy
from deeplearning4j_tpu.nn.conf.layers import is_bias_param
from deeplearning4j_tpu.nn.conf.neural_net import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf import preprocessors as preprocessors_mod
from deeplearning4j_tpu.nn.layers import OUTPUT_LAYER_TYPES, get_impl
from deeplearning4j_tpu.ops import grad_norm as grad_norm_mod
from deeplearning4j_tpu.ops import schedules as schedules_mod
from deeplearning4j_tpu.ops import updaters as updaters_mod
from deeplearning4j_tpu.nn import jit_cache as jit_cache_mod
from deeplearning4j_tpu.nn import superstep as _superstep
from deeplearning4j_tpu.nn import transfer as transfer_mod
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets import staging as _staging
from deeplearning4j_tpu.datasets.iterators import (
    MultiSuperbatch,
    Superbatch,
    SuperbatchIterator,
    maybe_reset,
    transfer_cast,
)
from deeplearning4j_tpu import observability as _obs

# Hot-loop series resolved once at import (observability/metrics.py rule 2).
_M_ITERS = _obs.metrics.counter(
    "dl4j_train_iterations_total", "Completed training iterations",
    label_names=("engine",)).labels(engine="graph")
_M_EPOCHS = _obs.metrics.counter(
    "dl4j_train_epochs_total", "Completed fit() epochs",
    label_names=("engine",)).labels(engine="graph")
_M_DISPATCH_FAMILY = _obs.metrics.histogram(
    "dl4j_step_dispatch_seconds",
    "Host time to dispatch one staged batch (async — completion is NOT "
    "awaited; see dl4j_step_latency_seconds from StepProfiler for settled "
    "latency); `k` = train iterations fused into the dispatch (superstep)",
    label_names=("engine", "k"))
_M_DISPATCH_K = {1: _M_DISPATCH_FAMILY.labels(engine="graph", k="1")}


def _dispatch_observe(k: int, seconds: float) -> None:
    child = _M_DISPATCH_K.get(k)
    if child is None:  # few distinct k values per process; cache children
        child = _M_DISPATCH_FAMILY.labels(engine="graph", k=str(k))
        _M_DISPATCH_K[k] = child
    child.observe(seconds)
_M_H2D = _obs.metrics.counter(
    "dl4j_host_to_device_bytes_total",
    "Host-resident bytes staged to device with training batches",
    label_names=("engine",)).labels(engine="graph")
_M_JIT_HIT = _obs.metrics.counter(
    "dl4j_jit_cache_hits_total", "Engine jit-program cache hits",
    label_names=("engine",)).labels(engine="graph")
_M_JIT_MISS = _obs.metrics.counter(
    "dl4j_jit_cache_misses_total",
    "Engine jit-program cache misses (a new program will trace+compile)",
    label_names=("engine",)).labels(engine="graph")
_M_INPUT_WAIT = _obs.metrics.histogram(
    "dl4j_input_wait_seconds",
    "Host seconds blocked in iterator-next waiting for the next batch "
    "(input starvation; the device is idle while this accrues)",
    label_names=("source",)).labels(source="graph")


def _as_mds(data, labels=None) -> MultiDataSet:
    if isinstance(data, MultiDataSet):
        return data
    if isinstance(data, DataSet):
        return MultiDataSet.from_dataset(data)
    return MultiDataSet(features=[np.asarray(data)], labels=[np.asarray(labels)])


def _as_mask_list(masks):
    """Normalize a MultiDataSet mask list for the jitted fns: None when no
    entry is present, else per-entry jnp arrays (None entries preserved)."""
    if masks is None or not any(m is not None for m in masks):
        return None
    return [None if m is None else jnp.asarray(m) for m in masks]


class ComputationGraph:
    """DAG network engine (see module docstring)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo_order = conf.topological_order()
        self.layer_vertices = {
            name: v for name, v in conf.vertices.items() if isinstance(v, LayerVertex)
        }
        self.params_tree: Optional[Dict[str, Any]] = None
        self.state: Dict[str, Any] = {}
        self.opt_state: Optional[Dict[str, Any]] = None
        self.iteration = 0
        self.epoch = 0
        self._score = float("nan")
        self.listeners: List[Any] = []
        self._collect_stats = False
        self.last_training_stats: Dict[str, Any] = {}
        self._initialized = False
        # Precision policy (nn/conf/dtype_policy.py): explicit `dtype_policy`
        # wins, else the legacy `dtype` string maps onto the matching preset.
        self.dtype_policy = resolve_policy(conf.global_conf)
        self._compute_dtype = self.dtype_policy.jnp_compute
        self._loss_dtype = (
            jnp.float64
            if self.dtype_policy.resolved_param_dtype == "float64"
            else jnp.float32
        )
        self._output_dtype = self.dtype_policy.jnp_output
        self._jit_cache: Dict[Any, Any] = {}
        self._rnn_state: Dict[str, Any] = {}
        self._clock = None  # on-device (step, rng) carry; see _device_clock


    @property
    def score_value(self) -> float:
        """Loss of the most recent iteration. Reading this syncs with the
        device (the train loop itself never blocks — important over
        high-latency device transports)."""
        v = self._score
        return float(v) if v is not None else float("nan")

    @score_value.setter
    def score_value(self, v):
        self._score = v

    # ------------------------------------------------------------------ init

    def init(self, params=None) -> "ComputationGraph":
        g = self.conf.global_conf
        pol = self.dtype_policy
        root = jax.random.PRNGKey(g.seed)
        # Low-precision param policies INITIALIZE at f32 (the master copy);
        # stored params are its cast. See MultiLayerNetwork.init.
        pdt = jnp.float32 if pol.low_precision_params else pol.jnp_param
        names = sorted(self.layer_vertices)
        keys = jax.random.split(root, max(len(names), 1))
        master = None
        if params is None:
            params = {
                name: params_mod.init_layer_params(self.layer_vertices[name].layer, keys[i], dtype=pdt)
                for i, name in enumerate(names)
            }
            if pol.low_precision_params:
                master = params
                params = params_mod.cast_floating(params, pol.jnp_param)
        elif pol.low_precision_params:
            master = params_mod.cast_floating(params, jnp.float32)
        self.params_tree = params
        self.state = {
            name: params_mod.init_layer_state(v.layer, dtype=pdt)
            for name, v in self.layer_vertices.items()
            if v.layer.state_shapes()
        }
        self._updaters = {}
        self._schedules = {}
        for name, v in self.layer_vertices.items():
            layer = v.layer
            self._updaters[name] = updaters_mod.create(
                layer.updater,
                momentum=layer.momentum if layer.momentum is not None else g.momentum,
                adam_mean_decay=layer.adam_mean_decay if layer.adam_mean_decay is not None else g.adam_mean_decay,
                adam_var_decay=layer.adam_var_decay if layer.adam_var_decay is not None else g.adam_var_decay,
                rho=layer.rho if layer.rho is not None else g.rho,
                rms_decay=layer.rms_decay if layer.rms_decay is not None else g.rms_decay,
                epsilon=layer.epsilon if layer.epsilon is not None else g.epsilon,
            )
            self._schedules[name] = schedules_mod.make_schedule(
                float(layer.learning_rate if layer.learning_rate is not None else g.learning_rate),
                g.lr_policy, g.lr_policy_decay_rate, g.lr_policy_power,
                g.lr_policy_steps, g.max_num_iterations, g.lr_schedule,
            )
        # Transfer learning / LoRA (nn/transfer.py): frozen leaves get NO
        # updater state — opt_state is built over the trainable subtree
        # (a fully-frozen vertex's entry is ()). Empty spec (the common
        # case) keeps the structures byte-identical to before.
        self._frozen_spec = transfer_mod.frozen_spec(
            ((n, v.layer) for n, v in self.layer_vertices.items()),
            self.params_tree)
        opt_base = master if master is not None else self.params_tree
        opt_src = (transfer_mod.split_tree(opt_base, self._frozen_spec)[0]
                   if self._frozen_spec else opt_base)
        self.opt_state = {
            name: (() if name in self._frozen_spec and not opt_src[name]
                   else self._updaters[name].init(opt_src[name]))
            for name in self.layer_vertices
        }
        # Reserved opt_state keys (never vertex names): f32 master params
        # and the on-device (scale, good_count) loss-scale carry — see
        # MultiLayerNetwork.init.
        if master is not None:
            self.opt_state["_master"] = master
        if pol.uses_loss_scaling:
            self.opt_state["_ls"] = (
                jnp.float32(pol.initial_loss_scale), jnp.float32(0.0))
        self._train_rng = jax.random.PRNGKey(g.seed ^ 0x5EED)
        self._clock = None
        self._initialized = True
        return self

    def _device_clock(self):
        """On-device (step, rng) carry, advanced inside the jitted train step
        — the hot loop makes zero host->device transfers (a host scalar
        conversion costs milliseconds over a tunneled device transport)."""
        if self._clock is None:
            self._clock = (
                jax.device_put(np.float32(self.iteration)),
                self._train_rng,
            )
        return self._clock

    @property
    def _uint8_policies(self) -> Dict[str, str]:
        """Per-network-input uint8 staging policy (see
        `nn/conf/preprocessors.py`): every vertex fed directly by the input
        votes, and a mixed ids/value vote is 'ambiguous' (raises if uint8
        actually arrives)."""
        out: Dict[str, str] = {}
        for name in self.conf.network_inputs:
            consumers = []
            for vname, ins in self.conf.vertex_inputs.items():
                if name in ins:
                    vertex = self.conf.vertices.get(vname)
                    consumers.append(getattr(vertex, "layer", None))
            out[name] = preprocessors_mod.resolve_uint8_policy(consumers)
        return out

    # --------------------------------------------------------------- forward

    def _forward_fn(self, params, state, inputs: Sequence, rng, train: bool,
                    fmasks: Optional[Sequence] = None, keep_rnn_state: bool = False,
                    collect: bool = False):
        """Traverse the DAG in topo order (reference: forward `:1044-1090`)."""
        cdt = self._compute_dtype
        values: Dict[str, jnp.ndarray] = {}
        masks: Dict[str, Optional[jnp.ndarray]] = {}
        policies = self._uint8_policies
        for i, name in enumerate(self.conf.network_inputs):
            # Device-side ImagePreProcessingScaler (see
            # MultiLayerNetwork._forward_fn): bytes over the link, scale
            # 0-255 -> 0-1 on device — but only for value consumers; an
            # input feeding an ids-format EmbeddingLayer is cast, and a
            # uint8 input feeding both kinds raises instead of guessing.
            values[name] = preprocessors_mod.apply_uint8_policy(
                jnp.asarray(inputs[i]), policies[name], cdt)
            masks[name] = None if fmasks is None else fmasks[i]
        new_state: Dict[str, Any] = {}
        aux: Dict[str, Any] = {}
        for vi, name in enumerate(self.topo_order):
            vertex = self.conf.vertices[name]
            in_names = self.conf.vertex_inputs[name]
            in_vals = [values[n] for n in in_names]
            in_masks = [masks[n] for n in in_names]
            if isinstance(vertex, LayerVertex):
                x, mask = in_vals[0], in_masks[0]
                if vertex.preprocessor is not None:
                    x, mask = vertex.preprocessor(x, mask)
                layer = vertex.layer
                if type(layer).__name__ == "CenterLossOutputLayer":
                    aux[f"center_loss_input:{name}"] = x
                    aux[f"centers:{name}"] = state.get(name, {}).get("centers")
                lrng = jax.random.fold_in(rng, vi) if rng is not None else None
                # Params stored at param_dtype, cast (or dequantized) to the
                # policy's compute dtype at use (nn/params.py).
                lparams = params_mod.prep_layer_params(params.get(name, {}),
                                                       cdt, layer=layer)
                out, lstate_new, mask = get_impl(layer)(
                    layer, lparams, state.get(name, {}), x,
                    rng=lrng, train=train, mask=mask,
                )
                if lstate_new and "_aux_loss" in lstate_new:
                    # Reserved key: auxiliary loss terms (MoE load balance)
                    # go into the objective, never persist as state.
                    lstate_new = dict(lstate_new)
                    aux["aux_loss"] = aux.get("aux_loss", 0.0) + \
                        lstate_new.pop("_aux_loss")
                if lstate_new:
                    declared = set(layer.state_shapes())
                    keep = {k: v for k, v in lstate_new.items()
                            if k in declared or keep_rnn_state}
                    if keep:
                        new_state[name] = keep
                values[name] = out
                masks[name] = mask
            elif isinstance(vertex, DuplicateToTimeSeriesVertex):
                ref = values[vertex.input_name]
                values[name] = vertex.apply(in_vals, in_masks, time_steps=ref.shape[1])
                masks[name] = masks.get(vertex.input_name)
            elif isinstance(vertex, LastTimeStepVertex):
                m = masks.get(vertex.mask_array_input) if vertex.mask_array_input else in_masks[0]
                values[name] = vertex.apply(in_vals, [m])
                masks[name] = None
            else:
                values[name] = vertex.apply(in_vals, in_masks)
                masks[name] = in_masks[0] if in_masks else None
        outs = [values[n] for n in self.conf.network_outputs]
        omasks = [masks.get(n) for n in self.conf.network_outputs]
        if collect:
            return outs, new_state, values, aux, omasks
        return outs, new_state, aux, omasks

    def _get_jit(self, kind: str, **static):
        # Key construction/lookup + compile-cache store hook shared with
        # MultiLayerNetwork (see nn/jit_cache.py).
        return jit_cache_mod.get_jit(self, _M_JIT_HIT, _M_JIT_MISS,
                                     kind, **static)

    def warmup(self, data=None, kinds=None, background: bool = False,
               batch_size: int = 32):
        """Pre-compile (or AOT-load) the jit programs for an example
        batch's signature without running them — params/optimizer/RNG are
        untouched. See `compilation.warmup.warmup_net` for the `data` /
        `kinds` / `background` contract."""
        from deeplearning4j_tpu.compilation import warmup as warmup_mod

        return warmup_mod.warmup_net(self, data, kinds=kinds,
                                     background=background,
                                     batch_size=batch_size)

    def _build_jit(self, kind: str, train=False, keep_rnn_state=False,
                   advance=False, collect=False, algo=None, k=None,
                   scan=True, kernels=None):
        # `k`/`scan` select the superstep program shape (`nn/superstep.py`,
        # see MultiLayerNetwork._build_jit): distinct block lengths register
        # as distinct cached programs so StepProfiler attributes a tail
        # block's first call to compile.
        if kind == "solver_step":
            from jax.flatten_util import ravel_pytree

            from deeplearning4j_tpu.optimize import solvers as solvers_mod

            g = self.conf.global_conf
            iterations = max(1, g.iterations)
            mls = max(1, int(g.max_num_line_search_iterations))

            def solver_fn(params, state, inputs, labels, fmasks, lmasks):
                w0, unravel = ravel_pytree(params)

                def loss_flat(w):
                    p = unravel(w)
                    outs, _, aux, omasks = self._forward_fn(
                        p, state, inputs, None, False, fmasks)
                    return self._loss_from_outputs(
                        p, outs, labels, lmasks, aux, omasks)[0]

                w, loss = solvers_mod.minimize(
                    algo, loss_flat, w0, iterations=iterations,
                    max_line_search=mls)
                return unravel(w), loss

            return jax.jit(solver_fn, donate_argnums=(0,))
        if kind == "output":
            def output_fn(params, state, inputs, fmasks, rng):
                outs, new_state, _, _ = self._forward_fn(
                    params, state, inputs, rng, train, fmasks,
                    keep_rnn_state=keep_rnn_state,
                )
                final = []
                for n, o in zip(self.conf.network_outputs, outs):
                    layer = self.layer_vertices.get(n)
                    o = o.astype(self._output_dtype)
                    if layer is not None and type(layer.layer).__name__ in OUTPUT_LAYER_TYPES:
                        o = activations_mod.resolve(layer.layer.activation)(o)
                    final.append(o)
                return final, new_state
            return jax.jit(output_fn)
        if kind == "score":
            def score_fn(params, state, inputs, labels, fmasks, lmasks):
                outs, _, aux, omasks = self._forward_fn(params, state, inputs, None, False, fmasks)
                return self._loss_from_outputs(params, outs, labels, lmasks, aux, omasks)[0]
            return jax.jit(score_fn)
        if kind == "train_step":
            def step_fn(params, state, opt_state, inputs, labels, fmasks, lmasks, clock):
                step, key = clock
                key, sub = jax.random.split(key)
                out = self._train_step(params, state, opt_state, inputs, labels,
                                       fmasks, lmasks, step, sub, carry_rnn=False)
                return out + ((step + 1.0, key),)
            return jax.jit(step_fn, donate_argnums=(0, 2))
        if kind == "train_superstep":
            # K full train iterations as ONE dispatch: a fused loop (`lax.scan`
            # by default, opt-in unrolled — `nn/superstep.py`) over the
            # leading [K] axis of stacked input/label/mask LISTS (lists are
            # pytrees, so the loop slices every entry; None mask entries are
            # empty pytrees and pass through). Clock advance matches the
            # per-batch `train_step` exactly — bit-identical RNG chain.
            # See MultiLayerNetwork's twin + PERF.md §13.
            def step_super(params, state, opt_state, inputs, labels, fmasks,
                           lmasks, clock):
                def body(carry, inp):
                    params, state, opt_state, (step, key) = carry
                    ins, labs, fms, lms = inp
                    key, sub = jax.random.split(key)
                    params, state, opt_state, loss = self._train_step(
                        params, state, opt_state, ins, labs, fms, lms, step,
                        sub, carry_rnn=False)
                    return (params, state, opt_state, (step + 1.0, key)), loss

                (params, state, opt_state,
                 clock), losses = _superstep.superstep_loop(
                    body, (params, state, opt_state, clock),
                    (inputs, labels, fmasks, lmasks), k, scan)
                return params, state, opt_state, losses, clock
            return jax.jit(step_super, donate_argnums=(0, 2))
        if kind == "train_step_stats":
            def step_fn_s(params, state, opt_state, inputs, labels, fmasks, lmasks, clock):
                step, key = clock
                key, sub = jax.random.split(key)
                out = self._train_step(params, state, opt_state, inputs, labels,
                                       fmasks, lmasks, step, sub, carry_rnn=False,
                                       collect_stats=True)
                return out + ((step + 1.0, key),)
            return jax.jit(step_fn_s, donate_argnums=(0, 2))
        if kind == "train_step_tbptt":
            # `advance` static: chunks of one sequence share a step value;
            # only the final chunk ticks the clock. `collect` adds the
            # StatsListener scalars so tBPTT training reports them too.
            def step_fn2(params, state, opt_state, inputs, labels, fmasks, lmasks, clock, ebs):
                step, key = clock
                key, sub = jax.random.split(key)
                out = self._train_step(params, state, opt_state, inputs, labels,
                                       fmasks, lmasks, step, sub, carry_rnn=True,
                                       ebs=ebs, collect_stats=collect)
                new_step = step + 1.0 if advance else step
                return out + ((new_step, key),)
            return jax.jit(step_fn2, donate_argnums=(0, 2))
        if kind == "train_step_tbptt_scan":
            # Whole tBPTT pass as ONE jitted program, mirroring
            # `MultiLayerNetwork`'s `train_step_tbptt_scan` (PERF.md §4):
            # chunk 0 unrolled (creates the rnn carries), middle chunks as a
            # `lax.scan` whose body time-slices the closed-over full
            # sequences with `dynamic_slice` (static 2-D inputs pass
            # through untouched), remainder chunk unrolled at its true
            # length. RNG split chain matches the per-chunk path exactly.
            fwd = int(self.conf.tbptt_fwd_length)

            def step_scan(params, state, opt_state, inputs, labels, fmasks,
                          lmasks, clock, ebs):
                step, key = clock
                t = max(f.shape[1] for f in inputs if f.ndim == 3)
                n_full = t // fwd
                rem = t - n_full * fwd
                subs = []
                for _ in range(n_full + (1 if rem else 0)):
                    key, sub = jax.random.split(key)
                    subs.append(sub)

                def sliced(lst, slicer, is_mask=False):
                    if lst is None:
                        return None
                    out = []
                    for a in lst:
                        seq = a is not None and a.shape[1:2] == (t,) and (
                            a.ndim == 3 or (a.ndim == 2 and (
                                is_mask
                                or jnp.issubdtype(a.dtype, jnp.integer))))
                        out.append(slicer(a) if seq else a)
                    return out

                def static_chunk(args, sl):
                    inputs_c = sliced(args[0], lambda a: a[:, sl])
                    labels_c = sliced(args[1], lambda a: a[:, sl])
                    fm_c = sliced(args[2], lambda a: a[:, sl], True)
                    lm_c = sliced(args[3], lambda a: a[:, sl], True)
                    return inputs_c, labels_c, fm_c, lm_c

                c0 = static_chunk((inputs, labels, fmasks, lmasks),
                                  slice(0, fwd))
                params, state, opt_state, loss = self._train_step(
                    params, state, opt_state, *c0, step, subs[0],
                    carry_rnn=True, ebs=ebs)

                if n_full > 1:
                    def body(carry, inp):
                        params, state, opt_state = carry
                        c, sub = inp
                        off = c * fwd

                        def dyn(a):
                            return jax.lax.dynamic_slice_in_dim(a, off, fwd, 1)

                        inputs_c = sliced(inputs, dyn)
                        labels_c = sliced(labels, dyn)
                        fm_c = sliced(fmasks, dyn, True)
                        lm_c = sliced(lmasks, dyn, True)
                        params, state, opt_state, closs = self._train_step(
                            params, state, opt_state, inputs_c, labels_c,
                            fm_c, lm_c, step, sub, carry_rnn=True, ebs=ebs)
                        return (params, state, opt_state), closs

                    (params, state, opt_state), losses = jax.lax.scan(
                        body, (params, state, opt_state),
                        (jnp.arange(1, n_full), jnp.stack(subs[1:n_full])))
                    loss = losses[-1]
                if rem:
                    cr = static_chunk((inputs, labels, fmasks, lmasks),
                                      slice(n_full * fwd, t))
                    params, state, opt_state, loss = self._train_step(
                        params, state, opt_state, *cr, step, subs[-1],
                        carry_rnn=True, ebs=ebs)
                return (params, state, opt_state, loss, (step + 1.0, key))
            return jax.jit(step_scan, donate_argnums=(0, 2))
        raise ValueError(kind)

    # ----------------------------------------------------------------- loss

    def _l1_l2_penalty(self, params):
        total = 0.0
        for name, v in self.layer_vertices.items():
            layer = v.layer
            l1 = float(layer.l1 or 0.0)
            l2 = float(layer.l2 or 0.0)
            if (l1 == 0.0 and l2 == 0.0) or name not in params:
                continue
            for wk in layer.weight_param_keys():
                if wk not in params[name]:
                    continue
                w = params[name][wk].astype(self._loss_dtype)
                if l2:
                    total = total + 0.5 * l2 * jnp.sum(w * w)
                if l1:
                    total = total + l1 * jnp.sum(jnp.abs(w))
        return total

    def _loss_from_outputs(self, params, outs, labels, lmasks, aux, omasks,
                           ebs=None):
        total = 0.0
        extra_state: Dict[str, Any] = {}
        for i, name in enumerate(self.conf.network_outputs):
            v = self.layer_vertices.get(name)
            if v is None or type(v.layer).__name__ not in OUTPUT_LAYER_TYPES:
                raise ValueError(f"Network output {name!r} is not an output layer")
            layer = v.layer
            preout = outs[i].astype(self._loss_dtype)
            y = labels[i]
            lmask = lmasks[i] if lmasks is not None else None
            if lmask is None and omasks and omasks[i] is not None and preout.ndim == 3:
                lmask = omasks[i]
            # `ebs` overrides the divisors for tBPTT chunks (full-sequence
            # minibatch count, see MultiLayerNetwork._loss_from_preout).
            eb = ebs[i] if ebs is not None else losses_mod.effective_batch_size(y, lmask)
            if i == 0:
                eb0 = eb
            total = total + losses_mod.score(
                layer.loss_function, y, preout, layer.activation, lmask,
                average=False,
            ) / eb
            if type(layer).__name__ == "CenterLossOutputLayer":
                feats = aux[f"center_loss_input:{name}"].astype(self._loss_dtype)
                centers = aux[f"centers:{name}"]
                cls = (jnp.asarray(y, jnp.int32)
                       if jnp.issubdtype(jnp.asarray(y).dtype, jnp.integer)
                       else jnp.argmax(y, axis=-1))
                c = centers[cls]
                # Row weights: labels mask excludes data-parallel padding rows
                # from the center-loss term and the center updates.
                w = jnp.ones(y.shape[0], self._loss_dtype) if lmask is None else (
                    lmask.reshape(y.shape[0], -1)[:, 0].astype(self._loss_dtype))
                total = total + 0.5 * layer.lambda_ * jnp.sum(
                    w * jnp.sum((feats - c) ** 2, axis=-1)) / eb
                diff = (c - feats) * w[:, None]
                num = jax.ops.segment_sum(diff, cls, num_segments=layer.n_out)
                cnt = jax.ops.segment_sum(w.astype(jnp.float32), cls,
                                          num_segments=layer.n_out)
                extra_state[name] = {"centers": centers - layer.alpha * num / (1.0 + cnt)[:, None]}
        if "aux_loss" in aux:
            # Layer-emitted auxiliary objectives (MoE load balance), already
            # scaled per-layer; batch-size-invariant means, not divided by eb.
            total = total + aux["aux_loss"]
        # Penalty divided by minibatch size, matching the reference objective
        # (BaseOutputLayer.java:100-101, LayerUpdater.postApply:104-108).
        return total + self._l1_l2_penalty(params) / eb0, extra_state

    # ----------------------------------------------------------- train step

    def _train_step(self, params, state, opt_state, inputs, labels, fmasks, lmasks,
                    step, rng, carry_rnn=False, ebs=None, collect_stats=False):
        # Transfer learning / LoRA: differentiate the TRAINABLE subtree
        # only — frozen leaves (incl. int8 bases, which jax.grad refuses)
        # close over the loss as constants and re-attach to the outputs
        # as the same arrays. Empty spec: identity, program unchanged.
        spec = getattr(self, "_frozen_spec", None)
        if spec:
            params, frozen_stored = transfer_mod.split_tree(params, spec)
        else:
            frozen_stored = None

        def loss_fn(p):
            if frozen_stored is not None:
                p = transfer_mod.merge_tree(p, frozen_stored)
            outs, new_state, aux, omasks = self._forward_fn(
                p, state, inputs, rng, True, fmasks, keep_rnn_state=carry_rnn
            )
            loss, extra = self._loss_from_outputs(p, outs, labels, lmasks, aux,
                                                  omasks, ebs)
            for n, s in extra.items():
                new_state.setdefault(n, {}).update(s)
            return loss, new_state

        pol = self.dtype_policy
        scaling = pol.uses_loss_scaling
        lowp = pol.low_precision_params

        if scaling:
            # Dynamic loss scaling (f16-class compute): backward on the
            # SCALED loss, f32 unscale after; (scale, good_count) lives in
            # opt_state so a fused superstep scan carries it on device.
            # See MultiLayerNetwork._train_step.
            scale, good = opt_state["_ls"]

            def scaled_loss_fn(p):
                loss, new_state = loss_fn(p)
                return loss * scale.astype(loss.dtype), (loss, new_state)

            (_, (loss, new_state)), grads = jax.value_and_grad(
                scaled_loss_fn, has_aux=True)(params)
            grads = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32) / scale, grads)
            finite = jnp.bool_(True)
            for leaf in jax.tree_util.tree_leaves(grads):
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
        else:
            (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if lowp:
                grads = params_mod.cast_floating(grads, jnp.float32)

        # Low-precision params: updates apply to the f32 MASTER copy; stored
        # params are its cast (no bf16/f16 update underflow).
        base = opt_state["_master"] if lowp else params
        frozen_master = None
        if spec and lowp:
            base, frozen_master = transfer_mod.split_tree(base, spec)
        g = self.conf.global_conf
        sign = 1.0 if g.minimize else -1.0
        new_base, new_opt = {}, {}
        stats: Dict[str, Any] = {}
        for name, v in self.layer_vertices.items():
            layer = v.layer
            lgrads = grads.get(name, {})
            if not lgrads:
                new_base[name] = base.get(name, {})
                new_opt[name] = opt_state.get(name, ())
                continue
            lgrads = grad_norm_mod.normalize_layer_gradients(
                lgrads, layer.gradient_normalization,
                float(layer.gradient_normalization_threshold or 1.0),
            )
            lr = self._schedules[name](step)
            st, deltas = self._updaters[name].update(opt_state[name], lgrads, lr, step)
            base_lr = float(layer.learning_rate if layer.learning_rate is not None else g.learning_rate)
            bias_lr = float(layer.bias_learning_rate if layer.bias_learning_rate is not None else base_lr)
            if bias_lr != base_lr and base_lr != 0.0:
                factor = bias_lr / base_lr
                # Per param TYPE via is_bias_param (b_f/b_b, vb/eb/db, beta),
                # matching reference `LayerUpdater.java:243`.
                deltas = {k: (d * factor if is_bias_param(k) else d)
                          for k, d in deltas.items()}
            new_base[name] = {k: base[name][k] - sign * deltas[k] for k in base[name]}
            new_opt[name] = st
            if collect_stats:
                # In-jit per-param mean magnitudes (only scalars leave the
                # device; reference `BaseStatsListener.java:273` semantics).
                stats[name] = {
                    k: {
                        "grad_mm": jnp.mean(jnp.abs(lgrads[k])),
                        "update_mm": jnp.mean(jnp.abs(deltas[k])),
                        "param_mm": jnp.mean(jnp.abs(new_base[name][k])),
                    }
                    for k in lgrads
                }

        if scaling:
            # Skip-step on non-finite scaled grads: per-leaf select of the
            # OLD values, then scale backoff / growth bookkeeping — all
            # on-device `jnp.where`, superstep-safe.
            def sel(n, o):
                return jnp.where(finite, n, o)

            new_base = jax.tree_util.tree_map(
                sel, new_base, {n: base[n] for n in new_base})
            new_opt = jax.tree_util.tree_map(
                sel, new_opt, {n: opt_state[n] for n in new_opt})
            new_state = {
                n: {k: (sel(v, state[n][k])
                        if n in state and k in state[n] else v)
                    for k, v in s.items()}
                for n, s in new_state.items()
            }
            new_good = jnp.where(finite, good + 1.0, jnp.float32(0.0))
            grow = new_good >= jnp.float32(pol.loss_scale_growth_interval)
            new_scale = jnp.where(
                finite,
                jnp.where(grow,
                          scale * jnp.float32(pol.loss_scale_growth_factor),
                          scale),
                scale * jnp.float32(pol.loss_scale_backoff_factor))
            new_good = jnp.where(grow, jnp.float32(0.0), new_good)

        if lowp:
            new_params = params_mod.cast_floating(new_base, pol.jnp_param)
            if frozen_stored is not None:
                # Frozen STORED leaves pass through untouched (no recast);
                # the master keeps its frozen f32 copies alongside.
                new_params = transfer_mod.merge_tree(new_params, frozen_stored)
                new_opt["_master"] = transfer_mod.merge_tree(
                    new_base, frozen_master)
            else:
                new_opt["_master"] = new_base
        elif frozen_stored is not None:
            new_params = transfer_mod.merge_tree(new_base, frozen_stored)
        else:
            new_params = new_base
        if scaling:
            new_opt["_ls"] = (new_scale, new_good)

        merged_state = dict(state)
        for n, s in new_state.items():
            merged = dict(merged_state.get(n, {}))
            merged.update(s)
            merged_state[n] = merged
        if collect_stats:
            return new_params, merged_state, new_opt, loss, stats
        return new_params, merged_state, new_opt, loss

    # ------------------------------------------------------------------ fit

    def fit(self, data, labels=None):
        """Train (reference: `ComputationGraph.fit` `:671,740`)."""
        if not self._initialized:
            self.init()
        if labels is not None or isinstance(data, (DataSet, MultiDataSet)):
            iterator = [_as_mds(data, labels)]
        else:
            iterator = data
        maybe_reset(iterator)
        for listener in self.listeners:
            listener.on_epoch_start(self)
        with _obs.tracer.span("graph.fit", cat="train", epoch=self.epoch):
            k = self._superstep_k()
            src = self._superstep_wrap(iterator, k) if k > 1 else iterator
            # Overlap host->device transfers with compute: multi-batch
            # epochs stream through a background DeviceStager (single
            # batches and already-staging sources pass through).
            src = _staging.maybe_stage(
                src, net=self, engine="graph",
                transfer_dtype=getattr(self.dtype_policy,
                                       "transfer_dtype", None))
            src_it = iter(src)
            try:
                while True:
                    # iterator-next is timed separately: with async/staged
                    # input tiers this wait is pure device starvation.
                    t_wait = time.perf_counter()
                    try:
                        item = next(src_it)
                    except StopIteration:
                        break
                    self._last_input_wait = time.perf_counter() - t_wait
                    _M_INPUT_WAIT.observe(self._last_input_wait)
                    self._fit_dispatch(
                        item if isinstance(item, MultiSuperbatch)
                        else _as_mds(item))
            finally:
                # An abandoned epoch must not leave staged HBM buffers.
                _staging.close_stager(src_it)
                _staging.close_stager(src)
        self.epoch += 1
        _M_EPOCHS.inc()
        for listener in self.listeners:
            listener.on_epoch_end(self)
        return self

    def _fit_dispatch(self, mds):
        """tBPTT/plain/superstep dispatch + iterations loop for one staged
        batch (or stacked `MultiSuperbatch`) — shared by `fit()` and
        `ParallelWrapper`. Observability choke point (see
        `MultiLayerNetwork._fit_dispatch`); `StepProfiler` patches this
        method on the instance."""
        tdt = getattr(self.dtype_policy, "transfer_dtype", None)
        if tdt is not None:
            mds = transfer_cast(mds, tdt)
        h2d = _obs.host_nbytes(mds.features, mds.labels,
                               mds.features_masks
                               if hasattr(mds, "features_masks")
                               else mds.features_mask,
                               mds.labels_masks
                               if hasattr(mds, "labels_masks")
                               else mds.labels_mask)
        _M_H2D.inc(h2d)
        it0 = self.iteration
        t0 = time.perf_counter()
        with _obs.iteration_span("graph", it0 + 1):
            try:
                return self._fit_dispatch_inner(mds)
            except Exception as e:
                # Forensics for uncaught dispatch failures: the bundle is
                # written before the exception unwinds the fit loop.
                _obs.flight.on_crash("graph.dispatch", e)
                raise
            finally:
                dt = time.perf_counter() - t0
                _dispatch_observe(int(getattr(mds, "k", 1)), dt)
                _M_ITERS.inc(max(0, self.iteration - it0))
                _obs.flight.record_step(
                    "graph", self.iteration, loss=self._score, seconds=dt,
                    k=int(getattr(mds, "k", 1)), h2d_bytes=h2d,
                    input_wait=getattr(self, "_last_input_wait", None),
                    jit_hits=_M_JIT_HIT.get(), jit_misses=_M_JIT_MISS.get())

    def _fit_dispatch_inner(self, mds):
        if isinstance(mds, (MultiSuperbatch, Superbatch)):
            # Stacked K-block: `_superstep_k` gated out solver / tBPTT /
            # stats / multi-iteration paths before blocks formed.
            return self._fit_superstep(mds)
        g = self.conf.global_conf
        algo = OptimizationAlgorithm.of(g.optimization_algo)
        if algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            return self._fit_solver(mds, algo)
        tbptt = BackpropType.of(self.conf.backprop_type) == BackpropType.TRUNCATED_BPTT
        for _ in range(max(1, g.iterations)):
            if tbptt and any(
                f.ndim == 3 and f.shape[1] > self.conf.tbptt_fwd_length
                for f in mds.features
            ):
                self._fit_tbptt(mds)
            else:
                self._fit_one(mds)

    def _fit_solver(self, mds: MultiDataSet, algo):
        """Full-batch LBFGS/CG/line-search optimize of one batch (reference:
        `Solver.java:41-110`); see `MultiLayerNetwork._fit_solver`."""
        self._check_sgd_only_policy("solver optimizers (LBFGS/CG/line search)")
        g = self.conf.global_conf
        fn = self._get_jit("solver_step", algo=str(algo))
        fmasks = _as_mask_list(mds.features_masks)
        lmasks = _as_mask_list(mds.labels_masks)
        self.params_tree, loss = fn(
            self.params_tree, self.state,
            [jnp.asarray(f) for f in mds.features],
            [jnp.asarray(l) for l in mds.labels],
            fmasks, lmasks,
        )
        self._score = loss
        self.iteration += max(1, g.iterations)
        # Stats snapshots are SGD-path only; clear stale ones (see
        # `MultiLayerNetwork._fit_solver`). Listener cadence deviation vs
        # `BaseOptimizer` is documented there too.
        self.last_training_stats = {}
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)

    # -------------------------------------------------------------- superstep

    def _superstep_k(self) -> int:
        """Effective superstep K (see `MultiLayerNetwork._superstep_k`):
        the config/env knob, gated to 0 for stats listeners, tBPTT, solver
        optimizers, and multi-`iterations` batches."""
        env = os.environ.get("DL4J_TPU_SUPERSTEP_K")
        g = self.conf.global_conf
        try:
            k = int(env) if env else int(getattr(g, "superstep_k", 0) or 0)
        except ValueError:
            return 0
        if (k < 2 or self._collect_stats
                or max(1, g.iterations) != 1
                or BackpropType.of(self.conf.backprop_type)
                == BackpropType.TRUNCATED_BPTT
                or OptimizationAlgorithm.of(g.optimization_algo)
                != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
            return 0
        return k

    def _check_sgd_only_policy(self, what: str) -> None:
        pol = self.dtype_policy
        if pol.low_precision_params or pol.uses_loss_scaling:
            raise ValueError(
                f"{what} does not support dtype policy {pol.name!r}: "
                "low-precision param storage (f32 master copies) and "
                "dynamic loss scaling are SGD-train-step features; use a "
                "float32 / float64 / mixed_bfloat16 policy here")

    def _superstep_wrap(self, iterator, k: int):
        """SuperbatchIterator over `iterator`, converting items to
        MultiDataSet BEFORE stacking; the wrapper is cached on the base so
        device-cached epochs restack once (see MultiLayerNetwork twin). The
        policy's `transfer_dtype` rides along so staged superbatches ship
        at the reduced dtype (halved H2D bytes)."""
        tdt = self.dtype_policy.transfer_dtype
        if isinstance(iterator, SuperbatchIterator):
            return iterator
        wrapper = getattr(iterator, "_superbatch_wrapper", None)
        if (isinstance(wrapper, SuperbatchIterator)
                and wrapper.base is iterator and wrapper.k == k
                and getattr(wrapper, "transfer_dtype", None) == tdt):
            wrapper.net = self  # staging budget follows the current net
            return wrapper
        wrapper = SuperbatchIterator(iterator, k, transform=_as_mds,
                                     transfer_dtype=tdt, net=self)
        try:
            iterator._superbatch_wrapper = wrapper
        except (AttributeError, TypeError):
            pass  # lists/tuples/slots: re-wrapped per fit(), still correct
        return wrapper

    def _fit_superstep(self, sb):
        """One dispatch, K train iterations (`train_superstep` scan); the
        `[K]` loss vector fans out to listeners per iteration — same
        (iteration, score) sequence as the per-batch loop."""
        if isinstance(sb, Superbatch):
            # DataSet-shaped block (e.g. from ParallelWrapper): lift to the
            # graph's list-of-parts shape.
            sb = MultiSuperbatch(
                [sb.features], [sb.labels],
                None if sb.features_mask is None else [sb.features_mask],
                None if sb.labels_mask is None else [sb.labels_mask],
                k=sb.k)
        k = int(sb.k)
        if k == 1:  # defensive: SuperbatchIterator yields raw singletons
            return self._fit_one(MultiDataSet(
                features=[f[0] for f in sb.features],
                labels=[l[0] for l in sb.labels],
                features_masks=None if sb.features_masks is None
                else [None if m is None else m[0] for m in sb.features_masks],
                labels_masks=None if sb.labels_masks is None
                else [None if m is None else m[0] for m in sb.labels_masks],
            ))
        step_fn = self._get_jit("train_superstep", k=k,
                                scan=_superstep.use_scan(),
                                kernels=_superstep.kernel_config())
        (self.params_tree, self.state, self.opt_state, losses,
         self._clock) = step_fn(
            self.params_tree, self.state, self.opt_state,
            [jnp.asarray(f) for f in sb.features],
            [jnp.asarray(l) for l in sb.labels],
            _as_mask_list(sb.features_masks),
            _as_mask_list(sb.labels_masks),
            self._device_clock(),
        )
        for i in range(k):
            self._score = losses[i]  # device scalar; sync deferred
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)

    def _fit_tbptt(self, mds: MultiDataSet):
        """Truncated BPTT over a DAG (reference: `ComputationGraph` tBPTT path):
        chunk all sequence arrays along time; rnn state carries across chunks."""
        if any(getattr(v.layer, "decode_cache_length", None)
               for v in self.layer_vertices.values()):
            raise ValueError(
                "truncated BPTT carries undeclared layer state across "
                "chunks, which would thread attention KV caches into "
                "training; unset decode_cache_length (it is an inference "
                "feature) or use standard backprop")
        fwd = self.conf.tbptt_fwd_length
        t = max(f.shape[1] for f in mds.features if f.ndim == 3)
        saved_state = self.state
        # Per-output divisors from the FULL-sequence masks (a row masked out
        # of one chunk still counts — reference divide-by-minibatch).
        full_lmasks = mds.labels_masks
        ebs = tuple(
            jax.device_put(np.float32(
                losses_mod.effective_batch_size(
                    l, full_lmasks[i] if full_lmasks is not None else None
                )
            ))
            for i, l in enumerate(mds.labels)
        )
        for lab in mds.labels:
            sparse = (np.issubdtype(np.asarray(lab).dtype, np.integer)
                      and lab.ndim == 2)
            if lab.ndim != 3 and not sparse:
                raise ValueError(
                    "Truncated BPTT requires per-timestep labels: [b, t, c] "
                    "one-hot or [b, t] integer class ids"
                )

        def time_slice(a, sl, is_mask=False):
            # Only 3-D [b, t, f] arrays (and, explicitly, 2-D [b, t] masks
            # or [b, t] integer class-id labels) are sequences; a static
            # 2-D float input whose feature dim happens to equal t must
            # pass through untouched.
            if a is None:
                return None
            if a.ndim == 3 and a.shape[1] == t:
                return a[:, sl]
            if a.ndim == 2 and a.shape[1] == t and (
                    is_mask or np.issubdtype(np.asarray(a).dtype,
                                             np.integer)):
                return a[:, sl]
            return a

        if not self._collect_stats:
            # Fast path: the whole chunk loop is one jitted scan — ONE
            # dispatch per sequence (PERF.md §4); per-chunk dispatch remains
            # only for StatsListener observability.
            step_fn = self._get_jit("train_step_tbptt_scan")
            fmasks = _as_mask_list(mds.features_masks)
            lmasks = _as_mask_list(mds.labels_masks)
            (self.params_tree, self.state, self.opt_state, loss,
             self._clock) = step_fn(
                self.params_tree, self.state, self.opt_state,
                [jnp.asarray(f) for f in mds.features],
                [jnp.asarray(l) for l in mds.labels],
                fmasks, lmasks, self._device_clock(), ebs,
            )
            self._score = loss
            return self._finish_tbptt(saved_state)
        n_chunks = math.ceil(t / fwd)
        for ci in range(n_chunks):
            sl = slice(ci * fwd, min((ci + 1) * fwd, t))
            chunk = MultiDataSet(
                features=[time_slice(f, sl) for f in mds.features],
                labels=[time_slice(l, sl) for l in mds.labels],
                features_masks=None if mds.features_masks is None
                else [time_slice(m, sl, is_mask=True) for m in mds.features_masks],
                labels_masks=None if mds.labels_masks is None
                else [time_slice(m, sl, is_mask=True) for m in mds.labels_masks],
            )
            self._fit_one(chunk, tbptt=True, count_iteration=False, ebs=ebs,
                          advance=ci == n_chunks - 1)
        self._finish_tbptt(saved_state)

    def _finish_tbptt(self, saved_state):
        # Drop rnn carries, keep declared (BN) state.
        declared = {n: set(v.layer.state_shapes()) for n, v in self.layer_vertices.items()}
        self.state = {
            n: {k: v for k, v in s.items() if k in declared.get(n, set())}
            for n, s in self.state.items()
        }
        self.state = {n: s for n, s in self.state.items() if s}
        for n, s in saved_state.items():
            self.state.setdefault(n, s)
        self.iteration += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)

    def _next_rng(self):
        if self._clock is not None:
            # The rng stream's continuation lives in the device clock; pull it
            # back to the host-side attribute before splitting.
            self._train_rng = self._clock[1]
            self._clock = None
        self._train_rng, sub = jax.random.split(self._train_rng)
        return sub

    def _fit_one(self, mds: MultiDataSet, tbptt: bool = False,
                 count_iteration: bool = True, ebs=None, advance=True):
        if tbptt:
            step_fn = self._get_jit("train_step_tbptt", advance=advance,
                                    collect=self._collect_stats)
        else:
            kind = "train_step_stats" if self._collect_stats else "train_step"
            step_fn = self._get_jit(kind)
        fmasks = _as_mask_list(mds.features_masks)
        lmasks = _as_mask_list(mds.labels_masks)
        args = [
            self.params_tree, self.state, self.opt_state,
            [jnp.asarray(f) for f in mds.features],
            [jnp.asarray(l) for l in mds.labels],
            fmasks, lmasks, self._device_clock(),
        ]
        if tbptt:
            args.append(ebs)
        out = step_fn(*args)
        if len(out) == 6:
            self.params_tree, self.state, self.opt_state, loss, stats, self._clock = out
            self.last_training_stats = stats
        else:
            self.params_tree, self.state, self.opt_state, loss, self._clock = out
        self._score = loss  # device scalar; sync deferred to score_value
        if count_iteration:
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)

    # -------------------------------------------------------------- predict

    def output(self, *inputs, train: bool = False, features_masks=None,
               params=None) -> List[np.ndarray]:
        """`params` substitutes another params tree of the same structure
        (e.g. an adapter-merged serving tree — `nn/lora.py`) for this
        net's own; params are jit arguments, so the swap re-uses the
        compiled program."""
        fn = self._get_jit("output", train=train)
        outs, _ = fn(self.params_tree if params is None else params,
                     self.state,
                     [jnp.asarray(x) for x in inputs],
                     features_masks,
                     self._next_rng() if train else jax.random.PRNGKey(0))
        return [np.asarray(o) for o in outs]

    def output_single(self, *inputs, **kw) -> np.ndarray:
        return self.output(*inputs, **kw)[0]

    # ----------------------------------------------------------------- rnn

    def _declared_state(self):
        return {
            name: tuple(v.layer.state_shapes())
            for name, v in self.layer_vertices.items()
        }

    def rnn_time_step(self, *inputs) -> List[np.ndarray]:
        """Stateful single/multi-step inference (reference:
        `ComputationGraph.rnnTimeStep:1386` — same contract as
        `MultiLayerNetwork.rnn_time_step`): hidden state (LSTM carries,
        attention KV caches, positional cursors) persists across calls.
        Accepts [b, f] (one step) or [b, t, f] per input."""
        from deeplearning4j_tpu.nn import rnn_state as rnn_mod

        arrs = []
        squeeze = False
        for x in inputs:
            x = np.asarray(x)
            if x.ndim == 2:
                x = x[:, None, :]
                squeeze = True
            arrs.append(x)
        self._rnn_pos = rnn_mod.check_decode_budget(
            getattr(self, "_rnn_pos", 0), arrs[0].shape[1],
            rnn_mod.decode_capacity(
                v.layer for v in self.layer_vertices.values()))
        fn = self._get_jit("output", train=False, keep_rnn_state=True)
        state = rnn_mod.merge_rnn_state(self.state, self._rnn_state)
        outs, new_state = fn(self.params_tree, state,
                             [jnp.asarray(x) for x in arrs], None,
                             jax.random.PRNGKey(0))
        self._rnn_state = rnn_mod.split_rnn_state(new_state,
                                                  self._declared_state())
        result = []
        for o in outs:
            o = np.asarray(o)
            result.append(o[:, 0] if squeeze and o.ndim == 3 else o)
        return result

    def rnn_clear_previous_state(self):
        self._rnn_state = {}
        self._rnn_pos = 0

    def score(self, data, labels=None) -> float:
        mds = _as_mds(data, labels)
        fn = self._get_jit("score")
        fmasks = _as_mask_list(mds.features_masks)
        lmasks = _as_mask_list(mds.labels_masks)
        return float(fn(
            self.params_tree, self.state,
            [jnp.asarray(f) for f in mds.features],
            [jnp.asarray(l) for l in mds.labels],
            fmasks, lmasks,
        ))

    def evaluate(self, iterator, top_n: int = 1):
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation(top_n=top_n)
        maybe_reset(iterator)
        if isinstance(iterator, (DataSet, MultiDataSet)):
            iterator = [iterator]
        for item in iterator:
            mds = _as_mds(item)
            fmasks = _as_mask_list(mds.features_masks)
            out = self.output(*mds.features, features_masks=fmasks)[0]
            lmask = mds.labels_masks[0] if mds.labels_masks else None
            ev.eval(mds.labels[0], out, mask=lmask)
        return ev

    # ------------------------------------------------------------- params io

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        self._collect_stats = any(
            getattr(l, "requires_training_stats", False) for l in listeners)
        return self

    def num_params(self) -> int:
        return int(sum(params_mod.num_params(v.layer) for v in self.layer_vertices.values()))

    def _param_orders(self):
        return {n: list(v.layer.param_shapes()) for n, v in self.layer_vertices.items()}

    def _param_vertex_order(self):
        return [n for n in self.topo_order if n in self.layer_vertices]

    def params(self) -> np.ndarray:
        return params_mod.flatten_params(
            self.params_tree, self._param_vertex_order(), self._param_orders()
        )

    def set_params(self, flat: np.ndarray):
        self.params_tree = params_mod.unflatten_params(
            np.asarray(flat), self.params_tree, self._param_vertex_order(), self._param_orders()
        )

    def updater_state_flat(self) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(self.opt_state)
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_updater_state_flat(self, flat: np.ndarray):
        leaves, treedef = jax.tree_util.tree_flatten(self.opt_state)
        out, pos = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(np.asarray(flat[pos:pos + n]).reshape(l.shape), l.dtype))
            pos += n
        self.opt_state = jax.tree_util.tree_unflatten(treedef, out)

    def clone(self) -> "ComputationGraph":
        """Deep copy with COPIED device buffers (the train step donates the
        source's buffers; aliased arrays would be deleted under the clone)."""
        net = ComputationGraph(copy.deepcopy(self.conf))
        if self._initialized:
            net.init(params=jax.tree_util.tree_map(jnp.copy, self.params_tree))
            net.state = jax.tree_util.tree_map(jnp.copy, self.state)
            net.opt_state = jax.tree_util.tree_map(jnp.copy, self.opt_state)
            net.iteration = self.iteration
            net.epoch = self.epoch
        return net

    def summary(self) -> str:
        lines = ["=" * 78]
        lines.append(f"{'Vertex':<28}{'Type':<28}{'Params':>10}")
        lines.append("-" * 78)
        for name in self.topo_order:
            v = self.conf.vertices[name]
            if isinstance(v, LayerVertex):
                lines.append(
                    f"{name:<28}{type(v.layer).__name__:<28}{params_mod.num_params(v.layer):>10}"
                )
            else:
                lines.append(f"{name:<28}{type(v).__name__:<28}{'-':>10}")
        lines.append("-" * 78)
        lines.append(f"Total params: {self.num_params()}")
        lines.append("=" * 78)
        return "\n".join(lines)
