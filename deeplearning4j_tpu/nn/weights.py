"""Weight initialization schemes.

TPU-native equivalent of the reference's `nn/weights/WeightInit.java` +
`nn/weights/WeightInitUtil.java`: given a scheme, fan-in/fan-out, and a JAX PRNG
key, produce an initial weight array. Fan values follow the reference's
convention (dense: fanIn = nIn, fanOut = nOut; conv: fanIn = inDepth*kH*kW,
fanOut = outDepth*kH*kW).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.distributions import Distribution
from deeplearning4j_tpu.nn.conf.enums import WeightInit


def init_weights(
    rng: jax.Array,
    shape: tuple,
    fan_in: float,
    fan_out: float,
    scheme: WeightInit = WeightInit.XAVIER,
    distribution: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> jnp.ndarray:
    scheme = WeightInit.of(scheme) or WeightInit.XAVIER
    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if scheme == WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("WeightInit.IDENTITY requires a square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == WeightInit.DISTRIBUTION:
        if distribution is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a distribution")
        return distribution.sample(rng, shape, dtype)
    if scheme == WeightInit.UNIFORM:
        a = 1.0 / math.sqrt(max(fan_in, 1.0))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.XAVIER:
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / (fan_in + fan_out))
    if scheme == WeightInit.XAVIER_UNIFORM:
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.XAVIER_FAN_IN:
        return jax.random.normal(rng, shape, dtype) / math.sqrt(fan_in)
    if scheme == WeightInit.XAVIER_LEGACY:
        # Reference legacy variant: randn / sqrt(shape[0] + shape[1])
        denom = math.sqrt(sum(shape[:2]) if len(shape) >= 2 else shape[0])
        return jax.random.normal(rng, shape, dtype) / denom
    if scheme == WeightInit.RELU:
        return jax.random.normal(rng, shape, dtype) * math.sqrt(2.0 / fan_in)
    if scheme == WeightInit.RELU_UNIFORM:
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.SIGMOID_UNIFORM:
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.LECUN_NORMAL:
        return jax.random.normal(rng, shape, dtype) * math.sqrt(1.0 / fan_in)
    if scheme == WeightInit.LECUN_UNIFORM:
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.NORMALIZED:
        # Reference legacy: (U[0,1) - 0.5) / shape[0]
        return (jax.random.uniform(rng, shape, dtype) - 0.5) / shape[0]
    if scheme == WeightInit.SIZE:
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    if scheme == WeightInit.VI:
        # Reference legacy variance-normalized init: zero-centered uniform [-a, a]
        a = math.sqrt(6.0 / (sum(shape[:2]) if len(shape) >= 2 else shape[0] + 1))
        return jax.random.uniform(rng, shape, dtype, minval=-a, maxval=a)
    raise ValueError(f"Unknown weight init scheme: {scheme!r}")
