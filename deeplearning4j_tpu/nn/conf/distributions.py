"""Weight distributions for WeightInit.DISTRIBUTION.

JSON-serializable equivalents of the reference's `nn/conf/distribution/`
(NormalDistribution, UniformDistribution, BinomialDistribution, GaussianDistribution).
"""

from __future__ import annotations

from dataclasses import dataclass, asdict

import jax
import jax.numpy as jnp


@dataclass
class Distribution:
    def sample(self, rng, shape, dtype=jnp.float32):
        raise NotImplementedError

    def to_dict(self):
        d = asdict(self)
        d["@dist"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        if d is None:
            return None
        d = dict(d)
        kind = d.pop("@dist")
        cls = _DISTRIBUTIONS[kind]
        return cls(**d)


@dataclass
class NormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, rng, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(rng, shape, dtype)


# Reference treats Gaussian/Normal as synonyms (`nn/conf/distribution/GaussianDistribution`).
@dataclass
class GaussianDistribution(NormalDistribution):
    pass


@dataclass
class UniformDistribution(Distribution):
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, rng, shape, dtype=jnp.float32):
        return jax.random.uniform(rng, shape, dtype, minval=self.lower, maxval=self.upper)


@dataclass
class BinomialDistribution(Distribution):
    number_of_trials: int = 1
    probability_of_success: float = 0.5

    def sample(self, rng, shape, dtype=jnp.float32):
        draws = jax.random.bernoulli(
            rng, self.probability_of_success, (self.number_of_trials,) + tuple(shape)
        )
        return jnp.sum(draws, axis=0).astype(dtype)


_DISTRIBUTIONS = {
    "NormalDistribution": NormalDistribution,
    "GaussianDistribution": GaussianDistribution,
    "UniformDistribution": UniformDistribution,
    "BinomialDistribution": BinomialDistribution,
}
