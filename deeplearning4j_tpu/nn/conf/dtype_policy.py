"""DtypePolicy: first-class precision policy for the config DSL.

One object answers three questions the engines used to hardcode:

- ``param_dtype``   — what the stored parameter leaves are (HBM residency);
- ``compute_dtype`` — what layer math runs in (params are cast at use, the
  dominant matmul/conv traffic — PERF.md §2's HBM-bound lever);
- ``output_dtype``  — what ``output()`` returns to callers.

Two training-side mechanisms hang off the policy:

- **master copies**: when ``param_dtype`` is lower precision than f32, the
  optimizer keeps an f32 master copy of every param (and f32 updater
  state); each step updates the master and re-casts, so repeated tiny
  updates never underflow the low-precision representation. The master
  tree rides inside ``opt_state`` under the reserved ``"_master"`` key —
  jit signatures, checkpoint trees and the superstep scan carry are
  unchanged in shape, they just grow leaves.
- **dynamic loss scaling** (f16-class compute): the loss is multiplied by
  a scale before backward, gradients are unscaled after; a step whose
  scaled grads are non-finite is SKIPPED (params/updater/state keep their
  old values via a ``jnp.where`` select) and the scale halves; after
  ``growth_interval`` consecutive finite steps it doubles. The
  ``(scale, good_count)`` pair lives at ``opt_state["_ls"]`` — carried
  ON-DEVICE so a fused superstep ``lax.scan`` stays one program with no
  host round-trip per iteration.

The default policy is ``"float32"`` and is bit-identical to the engines'
historical behavior (it serializes to *nothing*: ``GlobalConf.to_dict``
omits an unset policy so conf JSON — and therefore AOT compile-cache
fingerprints — are byte-identical to pre-policy builds).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

_CANONICAL = {
    "float32": "float32", "f32": "float32", "fp32": "float32",
    "float64": "float64", "f64": "float64", "double": "float64",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "float16": "float16", "f16": "float16", "fp16": "float16",
    "mixed_bfloat16": "mixed_bfloat16",
    "mixed_float16": "mixed_float16",
}

# f16-class dtypes default to dynamic loss scaling; bf16 keeps f32's
# exponent range so it trains unscaled.
_PRESETS = {
    # name: (param, compute, output, dynamic_loss_scale)
    "float32": ("float32", "float32", "float32", False),
    "float64": ("float64", "float64", "float64", False),
    "mixed_bfloat16": ("float32", "bfloat16", "float32", False),
    "mixed_float16": ("float32", "float16", "float32", True),
    "bfloat16": ("bfloat16", "bfloat16", "bfloat16", False),
    "float16": ("float16", "float16", "float16", True),
}

_LOW_PRECISION = ("bfloat16", "float16")


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """Immutable precision policy. Build via a preset name
    (``DtypePolicy.of("mixed_bfloat16")``) or field-by-field; unspecified
    fields fall back to the preset the ``name`` selects."""

    name: str = "float32"
    param_dtype: Optional[str] = None
    compute_dtype: Optional[str] = None
    output_dtype: Optional[str] = None
    # Host->device staging cast for superbatch/device-cache tiers
    # (datasets/iterators.py): features/labels ship at this dtype, halving
    # H2D bytes for f32 pipelines (the BENCH_r05 1.91x, now a config knob).
    transfer_dtype: Optional[str] = None
    # Dynamic loss scaling (None = preset default for the name).
    dynamic_loss_scale: Optional[bool] = None
    initial_loss_scale: float = 2.0 ** 15
    loss_scale_growth_interval: int = 2000
    loss_scale_growth_factor: float = 2.0
    loss_scale_backoff_factor: float = 0.5

    def __post_init__(self):
        name = _CANONICAL.get(str(self.name))
        if name is None:
            raise ValueError(
                f"unknown dtype policy {self.name!r}; presets: "
                f"{sorted(_PRESETS)}")
        object.__setattr__(self, "name", name)

    # ------------------------------------------------------------ resolved
    def _preset(self):
        return _PRESETS[self.name]

    @property
    def resolved_param_dtype(self) -> str:
        return self.param_dtype or self._preset()[0]

    @property
    def resolved_compute_dtype(self) -> str:
        return self.compute_dtype or self._preset()[1]

    @property
    def resolved_output_dtype(self) -> str:
        return self.output_dtype or self._preset()[2]

    @property
    def jnp_param(self):
        import jax.numpy as jnp
        return jnp.dtype(self.resolved_param_dtype)

    @property
    def jnp_compute(self):
        import jax.numpy as jnp
        return jnp.dtype(self.resolved_compute_dtype)

    @property
    def jnp_output(self):
        import jax.numpy as jnp
        return jnp.dtype(self.resolved_output_dtype)

    @property
    def low_precision_params(self) -> bool:
        """True when params are stored below f32 — the optimizer then keeps
        f32 master copies at ``opt_state["_master"]``."""
        return self.resolved_param_dtype in _LOW_PRECISION

    @property
    def uses_loss_scaling(self) -> bool:
        if self.dynamic_loss_scale is not None:
            return bool(self.dynamic_loss_scale)
        return self._preset()[3]

    @property
    def is_default(self) -> bool:
        """Full-f32 with no knobs set — serializes to nothing and must be
        bit-identical to the pre-policy engines."""
        return self == DtypePolicy()

    # ---------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        d: dict = {"name": self.name}
        for f in dataclasses.fields(self):
            if f.name == "name":
                continue
            v = getattr(self, f.name)
            if v is not None and v != f.default:
                d[f.name] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DtypePolicy":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    @classmethod
    def of(cls, v: Any) -> "DtypePolicy":
        """Coerce str | dict | DtypePolicy | None to a policy."""
        if v is None:
            return cls()
        if isinstance(v, DtypePolicy):
            return v
        if isinstance(v, str):
            return cls(name=v)
        if isinstance(v, dict):
            return cls.from_dict(v)
        raise TypeError(f"cannot build a DtypePolicy from {type(v).__name__}")


def resolve_policy(global_conf) -> DtypePolicy:
    """The one resolution point both engines use. An explicit
    ``dtype_policy`` wins; otherwise the legacy ``GlobalConf.dtype`` string
    maps onto the preset with identical semantics ("bfloat16" historically
    meant bf16 COMPUTE over f32 params — i.e. ``mixed_bfloat16``)."""
    explicit = getattr(global_conf, "dtype_policy", None)
    if explicit is not None:
        return DtypePolicy.of(explicit)
    legacy = getattr(global_conf, "dtype", "float32")
    if legacy == "bfloat16":
        return DtypePolicy(name="mixed_bfloat16")
    if legacy == "float64":
        return DtypePolicy(name="float64")
    return DtypePolicy()
