"""NeuralNetConfiguration builder DSL and network configurations.

Equivalent of the reference's `nn/conf/NeuralNetConfiguration.java` (builder +
ListBuilder, `:200,478`), `MultiLayerConfiguration.java`, and
`ComputationGraphConfiguration.java` (GraphBuilder) — fluent builders producing
JSON-round-trippable configurations, with global hyperparameter defaults merged
into per-layer configs at build time and `InputType`-driven shape inference and
automatic preprocessor insertion (reference `ConvolutionLayerSetup.java:42`).

JSON round-trip is load-bearing in the reference (Spark broadcast, UI,
ModelSerializer) and is preserved here for checkpointing and serving.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from deeplearning4j_tpu.nn.conf.distributions import Distribution
from deeplearning4j_tpu.nn.conf.enums import (
    Activation,
    BackpropType,
    GradientNormalization,
    LearningRatePolicy,
    OptimizationAlgorithm,
    Updater,
    WeightInit,
    ConvolutionMode,
)
from deeplearning4j_tpu.nn.conf.graph import (
    GraphVertexConf,
    LayerVertex,
    vertex_from_dict,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, layer_from_dict
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor,
    preprocessor_from_dict,
)

# Per-layer fields that inherit from the builder's globals when unset
# (reference: NeuralNetConfiguration.Builder global defaults applied per layer).
_INHERITED_FIELDS = (
    "activation", "weight_init", "dist", "learning_rate", "bias_learning_rate",
    "l1", "l2", "dropout", "use_drop_connect", "bias_init", "updater", "momentum",
    "adam_mean_decay", "adam_var_decay", "rho", "rms_decay", "epsilon",
    "gradient_normalization", "gradient_normalization_threshold",
)


@dataclass
class GlobalConf:
    """Resolved global hyperparameters (reference: `NeuralNetConfiguration` fields)."""

    seed: int = 12345
    iterations: int = 1
    optimization_algo: Any = OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    learning_rate: float = 1e-1
    bias_learning_rate: Optional[float] = None
    lr_policy: Any = LearningRatePolicy.NONE
    lr_policy_decay_rate: float = 0.0
    lr_policy_power: float = 0.0
    lr_policy_steps: float = 1.0
    lr_schedule: Optional[Dict[int, float]] = None
    max_num_iterations: int = 1
    updater: Any = Updater.SGD
    momentum: float = 0.9
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    rho: float = 0.95
    rms_decay: float = 0.95
    epsilon: Optional[float] = None
    weight_init: Any = WeightInit.XAVIER
    dist: Optional[Distribution] = None
    activation: Any = Activation.SIGMOID
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    dropout: float = 0.0
    use_drop_connect: bool = False
    minimize: bool = True
    gradient_normalization: Any = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    mini_batch: bool = True
    convolution_mode: Any = ConvolutionMode.TRUNCATE
    max_num_line_search_iterations: int = 5
    dtype: str = "float32"  # legacy dtype knob ("float32" | "bfloat16" | "float64")
    # First-class precision policy (nn/conf/dtype_policy.py): a DtypePolicy,
    # preset name, or dict. None = derive from the legacy `dtype` string.
    # Serialized ONLY when set, so default conf JSON — and the AOT
    # compile-cache fingerprints built from it — stay byte-identical.
    dtype_policy: Any = None
    # Superstep training: fuse up to K train iterations into ONE device
    # dispatch (lax.scan over stacked batches; PERF.md §13). 0/1 = per-batch
    # dispatch. Overridable at runtime via DL4J_TPU_SUPERSTEP_K.
    superstep_k: int = 0

    def to_dict(self):
        d = {}
        for k, v in self.__dict__.items():
            if k == "dtype_policy":
                if v is None:
                    continue  # unset policy serializes to nothing (bit-compat)
                from deeplearning4j_tpu.nn.conf.dtype_policy import DtypePolicy
                v = DtypePolicy.of(v).to_dict()
            elif isinstance(v, Distribution):
                v = v.to_dict()
            elif hasattr(v, "value") and not isinstance(v, (int, float, bool)):
                v = v.value
            d[k] = v
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d or {})
        if isinstance(d.get("dist"), dict):
            d["dist"] = Distribution.from_dict(d["dist"])
        if d.get("dtype_policy") is not None:
            from deeplearning4j_tpu.nn.conf.dtype_policy import DtypePolicy
            d["dtype_policy"] = DtypePolicy.of(d["dtype_policy"])
        if d.get("lr_schedule"):
            d["lr_schedule"] = {int(k): float(v) for k, v in d["lr_schedule"].items()}
        g = GlobalConf()
        for k, v in d.items():
            if hasattr(g, k):
                setattr(g, k, v)
        return g


class NeuralNetConfiguration:
    """Entry point: `NeuralNetConfiguration.builder()` (reference `:478`)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    """Fluent global-hyperparameter builder (reference: `NeuralNetConfiguration.Builder`)."""

    def __init__(self):
        self._g = GlobalConf()

    # Each setter mirrors a reference builder method (camelCase -> snake_case).
    def seed(self, v): self._g.seed = int(v); return self
    def iterations(self, v): self._g.iterations = int(v); return self
    def optimization_algo(self, v): self._g.optimization_algo = OptimizationAlgorithm.of(v); return self
    def learning_rate(self, v): self._g.learning_rate = float(v); return self
    def bias_learning_rate(self, v): self._g.bias_learning_rate = float(v); return self
    def learning_rate_decay_policy(self, v): self._g.lr_policy = LearningRatePolicy.of(v); return self
    def lr_policy_decay_rate(self, v): self._g.lr_policy_decay_rate = float(v); return self
    def lr_policy_power(self, v): self._g.lr_policy_power = float(v); return self
    def lr_policy_steps(self, v): self._g.lr_policy_steps = float(v); return self
    def learning_rate_schedule(self, schedule):
        self._g.lr_policy = LearningRatePolicy.SCHEDULE
        self._g.lr_schedule = {int(k): float(v) for k, v in schedule.items()}
        return self
    def updater(self, v): self._g.updater = Updater.of(v); return self
    def momentum(self, v): self._g.momentum = float(v); return self
    def adam_mean_decay(self, v): self._g.adam_mean_decay = float(v); return self
    def adam_var_decay(self, v): self._g.adam_var_decay = float(v); return self
    def rho(self, v): self._g.rho = float(v); return self
    def rms_decay(self, v): self._g.rms_decay = float(v); return self
    def epsilon(self, v): self._g.epsilon = float(v); return self
    def weight_init(self, v): self._g.weight_init = WeightInit.of(v); return self
    def dist(self, v): self._g.dist = v; self._g.weight_init = WeightInit.DISTRIBUTION; return self
    def activation(self, v): self._g.activation = v; return self
    def bias_init(self, v): self._g.bias_init = float(v); return self
    def l1(self, v): self._g.l1 = float(v); return self
    def l2(self, v): self._g.l2 = float(v); return self
    def drop_out(self, v): self._g.dropout = float(v); return self
    def use_drop_connect(self, v=True): self._g.use_drop_connect = bool(v); return self
    def superstep_k(self, v): self._g.superstep_k = int(v); return self
    def minimize(self, v=True): self._g.minimize = bool(v); return self
    def gradient_normalization(self, v): self._g.gradient_normalization = GradientNormalization.of(v); return self
    def gradient_normalization_threshold(self, v): self._g.gradient_normalization_threshold = float(v); return self
    def mini_batch(self, v=True): self._g.mini_batch = bool(v); return self
    def convolution_mode(self, v): self._g.convolution_mode = ConvolutionMode.of(v); return self
    def max_num_line_search_iterations(self, v): self._g.max_num_line_search_iterations = int(v); return self
    def regularization(self, v=True): return self  # reference compat no-op: l1/l2 always honored
    def dtype(self, v): self._g.dtype = str(v); return self
    def dtype_policy(self, v):
        from deeplearning4j_tpu.nn.conf.dtype_policy import DtypePolicy
        self._g.dtype_policy = DtypePolicy.of(v); return self

    def list(self) -> "ListBuilder":
        """Start a sequential-network config (reference `:200`)."""
        return ListBuilder(copy.deepcopy(self._g))

    def graph_builder(self) -> "GraphBuilder":
        """Start a DAG config (reference: `ComputationGraphConfiguration.GraphBuilder`)."""
        return GraphBuilder(copy.deepcopy(self._g))


def _merge_globals(layer: Layer, g: GlobalConf) -> Layer:
    """Fill a layer's unset (None) hyperparams from the globals."""
    layer = copy.deepcopy(layer)
    for f in _INHERITED_FIELDS:
        if getattr(layer, f, None) is None:
            setattr(layer, f, getattr(g, f.replace("bias_learning_rate", "bias_learning_rate")))
    if layer.bias_learning_rate is None:
        layer.bias_learning_rate = layer.learning_rate
    if getattr(layer, "convolution_mode", "absent") is None:
        layer.convolution_mode = g.convolution_mode
    return layer


class ListBuilder:
    """Sequential-network builder (reference: `NeuralNetConfiguration.ListBuilder`)."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._layers: Dict[int, Layer] = {}
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop = True
        self._pretrain = False
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, index_or_layer, maybe_layer=None) -> "ListBuilder":
        if maybe_layer is None:
            self._layers[len(self._layers)] = index_or_layer
        else:
            self._layers[int(index_or_layer)] = maybe_layer
        return self

    def input_preprocessor(self, index: int, p: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[int(index)] = p
        return self

    def set_input_type(self, t: InputType) -> "ListBuilder":
        self._input_type = t
        return self

    def backprop(self, v: bool) -> "ListBuilder":
        self._backprop = bool(v)
        return self

    def pretrain(self, v: bool) -> "ListBuilder":
        self._pretrain = bool(v)
        return self

    def backprop_type(self, v) -> "ListBuilder":
        self._backprop_type = BackpropType.of(v)
        return self

    def t_bptt_forward_length(self, v: int) -> "ListBuilder":
        self._tbptt_fwd = int(v)
        return self

    def t_bptt_backward_length(self, v: int) -> "ListBuilder":
        self._tbptt_back = int(v)
        return self

    def build(self) -> "MultiLayerConfiguration":
        n = len(self._layers)
        if sorted(self._layers) != list(range(n)):
            raise ValueError(f"Layer indices must be contiguous from 0; got {sorted(self._layers)}")
        layers = [_merge_globals(self._layers[i], self._g) for i in range(n)]
        preprocessors = dict(self._preprocessors)

        if self._input_type is not None:
            current = self._input_type
            for i, layer in enumerate(layers):
                if i not in preprocessors:
                    auto = layer.default_preprocessor(current)
                    if auto is not None:
                        preprocessors[i] = auto
                if i in preprocessors:
                    current = preprocessors[i].get_output_type(current)
                layer.set_n_in(current, override=True)
                current = layer.get_output_type(current)
        else:
            # Without an input type, still propagate n_in from explicit n_out chain.
            current = None
            for layer in layers:
                if current is not None:
                    layer.set_n_in(current, override=False)
                try:
                    current = layer.get_output_type(
                        current if current is not None
                        else InputType.feed_forward(getattr(layer, "n_in", 0))
                    )
                except Exception:
                    current = None

        return MultiLayerConfiguration(
            global_conf=self._g,
            layers=layers,
            input_preprocessors=preprocessors,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            input_type=self._input_type,
        )


@dataclass
class MultiLayerConfiguration:
    """Sequential network configuration (reference: `MultiLayerConfiguration.java`)."""

    global_conf: GlobalConf = field(default_factory=GlobalConf)
    layers: List[Layer] = field(default_factory=list)
    input_preprocessors: Dict[int, InputPreProcessor] = field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: Any = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    input_type: Optional[InputType] = None

    def to_dict(self):
        return {
            "format": "deeplearning4j_tpu/MultiLayerConfiguration",
            "version": 1,
            "global_conf": self.global_conf.to_dict(),
            "layers": [l.to_dict() for l in self.layers],
            "input_preprocessors": {str(k): v.to_dict() for k, v in self.input_preprocessors.items()},
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": BackpropType.of(self.backprop_type).value,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "input_type": self.input_type.to_dict() if self.input_type else None,
        }

    def to_json(self, indent=2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            global_conf=GlobalConf.from_dict(d.get("global_conf")),
            layers=[layer_from_dict(l) for l in d["layers"]],
            input_preprocessors={
                int(k): preprocessor_from_dict(v)
                for k, v in (d.get("input_preprocessors") or {}).items()
            },
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=BackpropType.of(d.get("backprop_type", "standard")),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            input_type=InputType.from_dict(d.get("input_type")),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """YAML form (reference: `MultiLayerConfiguration.toYaml()`
        `NeuralNetConfiguration.java:295-340` — same payload as JSON)."""
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml

        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))


class GraphBuilder:
    """DAG builder (reference: `ComputationGraphConfiguration.GraphBuilder`)."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, GraphVertexConf] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._input_types: Dict[str, InputType] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None) -> "GraphBuilder":
        self._vertices[name] = LayerVertex(layer=layer, preprocessor=preprocessor)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertexConf, *inputs: str) -> "GraphBuilder":
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        for name, t in zip(self._inputs, types):
            self._input_types[name] = t
        return self

    def backprop(self, v: bool) -> "GraphBuilder":
        self._backprop = bool(v)
        return self

    def pretrain(self, v: bool) -> "GraphBuilder":
        self._pretrain = bool(v)
        return self

    def backprop_type(self, v) -> "GraphBuilder":
        self._backprop_type = BackpropType.of(v)
        return self

    def t_bptt_forward_length(self, v: int) -> "GraphBuilder":
        self._tbptt_fwd = int(v)
        return self

    def t_bptt_backward_length(self, v: int) -> "GraphBuilder":
        self._tbptt_back = int(v)
        return self

    def build(self) -> "ComputationGraphConfiguration":
        conf = ComputationGraphConfiguration(
            global_conf=self._g,
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            vertices={
                n: (LayerVertex(layer=_merge_globals(v.layer, self._g), preprocessor=v.preprocessor)
                    if isinstance(v, LayerVertex) else copy.deepcopy(v))
                for n, v in self._vertices.items()
            },
            vertex_inputs={n: list(v) for n, v in self._vertex_inputs.items()},
            input_types=dict(self._input_types),
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )
        conf.validate()
        if self._input_types:
            conf.infer_shapes()
        return conf


@dataclass
class ComputationGraphConfiguration:
    """DAG network configuration (reference: `ComputationGraphConfiguration.java`)."""

    global_conf: GlobalConf = field(default_factory=GlobalConf)
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    vertices: Dict[str, GraphVertexConf] = field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = field(default_factory=dict)
    input_types: Dict[str, InputType] = field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: Any = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def validate(self):
        """Structural validation (reference: `ComputationGraphConfiguration.validate()`)."""
        if not self.network_inputs:
            raise ValueError("ComputationGraph requires at least one network input")
        if not self.network_outputs:
            raise ValueError("ComputationGraph requires at least one network output")
        known = set(self.network_inputs) | set(self.vertices)
        for name, ins in self.vertex_inputs.items():
            for i in ins:
                if i not in known:
                    raise ValueError(f"Vertex {name!r} input {i!r} is not a known vertex/input")
        for o in self.network_outputs:
            if o not in self.vertices:
                raise ValueError(f"Network output {o!r} is not a vertex")
        self.topological_order()  # raises on cycles

    def topological_order(self) -> List[str]:
        """Kahn topological sort of vertex names, inputs first (reference:
        `ComputationGraph.java:851 topologicalSortOrder()`)."""
        indegree = {n: 0 for n in self.vertices}
        dependents: Dict[str, List[str]] = {n: [] for n in list(self.vertices) + self.network_inputs}
        for name, ins in self.vertex_inputs.items():
            for i in ins:
                dependents.setdefault(i, []).append(name)
                if i in self.vertices:
                    indegree[name] += 1
        order: List[str] = []
        ready = sorted(n for n, d in indegree.items() if d == 0)
        while ready:
            n = ready.pop(0)
            order.append(n)
            for dep in dependents.get(n, []):
                indegree[dep] -= 1
                if indegree[dep] == 0:
                    ready.append(dep)
        if len(order) != len(self.vertices):
            raise ValueError("Cycle detected in ComputationGraph configuration")
        return order

    def infer_shapes(self):
        """Infer n_in for all layer vertices from input types, inserting default
        preprocessors (reference: `addPreProcessors`/`getLayerActivationTypes`)."""
        types: Dict[str, InputType] = dict(self.input_types)
        for name in self.topological_order():
            vertex = self.vertices[name]
            in_types = [types[i] for i in self.vertex_inputs[name]]
            if isinstance(vertex, LayerVertex):
                it = in_types[0]
                if vertex.preprocessor is None:
                    auto = vertex.layer.default_preprocessor(it)
                    if auto is not None:
                        vertex.preprocessor = auto
                if vertex.preprocessor is not None:
                    it = vertex.preprocessor.get_output_type(it)
                vertex.layer.set_n_in(it, override=True)
                types[name] = vertex.layer.get_output_type(it)
            else:
                types[name] = vertex.get_output_type(*in_types)
        self._vertex_output_types = types
        return types

    def to_dict(self):
        return {
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration",
            "version": 1,
            "global_conf": self.global_conf.to_dict(),
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "vertices": {n: v.to_dict() for n, v in self.vertices.items()},
            "vertex_inputs": self.vertex_inputs,
            "input_types": {n: t.to_dict() for n, t in self.input_types.items()},
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": BackpropType.of(self.backprop_type).value,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    def to_json(self, indent=2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(
            global_conf=GlobalConf.from_dict(d.get("global_conf")),
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            vertices={n: vertex_from_dict(v) for n, v in d["vertices"].items()},
            vertex_inputs={n: list(v) for n, v in d["vertex_inputs"].items()},
            input_types={n: InputType.from_dict(t) for n, t in (d.get("input_types") or {}).items()},
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=BackpropType.of(d.get("backprop_type", "standard")),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        """YAML form (reference: `ComputationGraphConfiguration.toYaml()`)."""
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml

        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))
