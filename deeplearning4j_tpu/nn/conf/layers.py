"""Layer configuration classes.

Equivalent of the reference's `nn/conf/layers/*` (one config class per layer
type; inventory in SURVEY.md §2). Configs are JSON-serializable dataclasses
carrying hyperparameters and shape-inference logic; the forward math lives in
`deeplearning4j_tpu.nn.layers.*` and is looked up by config class name — the
TPU analog of the reference's conf/impl split, minus the helper SPI (XLA lowers
conv/BN/LSTM directly; no cuDNN-style plug-in point is needed).

Unset per-layer hyperparameters (None) inherit the builder's global defaults at
build time, matching `NeuralNetConfiguration.Builder` semantics.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nn.conf.distributions import Distribution
from deeplearning4j_tpu.nn.conf.enums import (
    Activation,
    ConvolutionMode,
    GradientNormalization,
    LossFunction,
    PoolingType,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType

_LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_dict(d: dict):
    d = dict(d)
    kind = d.pop("@class")
    cls = _LAYER_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"Unknown layer type in config JSON: {kind}")
    return cls.from_dict(d)


def is_bias_param(name: str) -> bool:
    """Single source of truth for bias-vs-weight param classification
    (shared with `nn/params.py` init and the engines' L1/L2 penalty)."""
    return (
        name in ("b", "vb", "beta")
        or name.startswith(("b_", "eb", "db"))
        # Per-branch BN shift params of the fused BottleneckBlock
        # (beta_a/beta_b/beta_c/beta_proj): bias semantics like "beta".
        or name.startswith("beta_")
        or name.endswith("B")
    )


def _tuple2(v) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    t = tuple(int(x) for x in v)
    if len(t) == 1:
        return (t[0], t[0])
    return t  # type: ignore[return-value]


@dataclass
class Layer:
    """Base layer config: per-layer hyperparameter overrides (None = inherit global).

    Mirrors the reference's `nn/conf/layers/Layer.java` builder fields.
    """

    name: Optional[str] = None
    activation: Optional[Any] = None
    weight_init: Optional[Any] = None
    dist: Optional[Distribution] = None
    learning_rate: Optional[float] = None
    bias_learning_rate: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None  # retain probability; 0/1/None disables
    # DropConnect: when true, `dropout` is applied to the INPUT WEIGHTS
    # instead of the input activations (reference: `conf.isUseDropConnect()`
    # read in `BaseLayer.preOutput:371-373` / `LSTMHelpers.java:98-101`).
    use_drop_connect: Optional[bool] = None
    bias_init: Optional[float] = None
    updater: Optional[Any] = None
    momentum: Optional[float] = None
    adam_mean_decay: Optional[float] = None
    adam_var_decay: Optional[float] = None
    rho: Optional[float] = None
    rms_decay: Optional[float] = None
    epsilon: Optional[float] = None
    gradient_normalization: Optional[Any] = None
    gradient_normalization_threshold: Optional[float] = None
    # Transfer learning / LoRA (nn/transfer.py, nn/lora.py). None keeps the
    # serialized conf byte-identical to pre-transfer checkpoints (to_dict
    # skips None fields). `frozen=True` excludes the layer's base params
    # from grads and updater state; `lora_rank` adds `<name>__lora_a/b`
    # sibling leaves for every 2-D weight (base weights become frozen,
    # adapters train).
    frozen: Optional[bool] = None
    lora_rank: Optional[int] = None
    lora_alpha: Optional[float] = None

    # ---- shape inference ----
    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType, override: bool) -> None:
        """Infer n_in from the previous layer's output type (no-op by default)."""

    def default_preprocessor(self, input_type: InputType):
        return None

    # ---- params ----
    def param_shapes(self) -> Dict[str, Tuple[int, ...]]:
        """Ordered mapping param-name -> shape (defines the flat-view order)."""
        return {}

    def state_shapes(self) -> Dict[str, Tuple[int, ...]]:
        """Non-trainable state (e.g. batchnorm running stats)."""
        return {}

    def weight_param_keys(self) -> Sequence[str]:
        """Params treated as weights for L1/L2 and weight-init purposes.
        Biases are never regularized (reference semantics)."""
        return [k for k in self.param_shapes() if not is_bias_param(k)]

    def has_params(self) -> bool:
        return bool(self.param_shapes())

    def is_pretrainable(self) -> bool:
        return False

    # ---- serde ----
    def to_dict(self) -> dict:
        d: Dict[str, Any] = {"@class": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            if isinstance(v, Distribution):
                v = v.to_dict()
            elif isinstance(v, (Activation, WeightInit, Updater, LossFunction,
                                GradientNormalization, PoolingType, ConvolutionMode)):
                v = v.value
            elif isinstance(v, tuple):
                v = list(v)
            d[f.name] = v
        return d

    @classmethod
    def from_dict(cls, d: dict):
        kwargs = dict(d)
        if "dist" in kwargs and isinstance(kwargs["dist"], dict):
            kwargs["dist"] = Distribution.from_dict(kwargs["dist"])
        names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in kwargs.items() if k in names}
        for key in ("kernel_size", "stride", "padding", "pooling_dimensions",
                    "encoder_layer_sizes", "decoder_layer_sizes"):
            if key in kwargs and isinstance(kwargs[key], list):
                kwargs[key] = tuple(kwargs[key])
        return cls(**kwargs)


@dataclass
class FeedForwardLayer(Layer):
    """Base for layers with explicit n_in/n_out (reference: `FeedForwardLayer.java`)."""

    n_in: int = 0
    n_out: int = 0

    def get_output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)

    def set_n_in(self, input_type: InputType, override: bool) -> None:
        if override or not self.n_in:
            self.n_in = input_type.flat_size()

    def default_preprocessor(self, input_type: InputType):
        from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor
        if input_type.kind == "cnn":
            return CnnToFeedForwardPreProcessor(
                input_type.height, input_type.width, input_type.channels
            )
        return None

    def param_shapes(self) -> Dict[str, Tuple[int, ...]]:
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,)}


@register_layer
@dataclass
class DenseLayer(FeedForwardLayer):
    """Fully-connected layer (reference: `nn/conf/layers/DenseLayer.java`)."""


@register_layer
@dataclass
class BaseOutputLayer(FeedForwardLayer):
    loss_function: Any = LossFunction.MCXENT

    def to_dict(self):
        d = super().to_dict()
        lf = self.loss_function
        d["loss_function"] = lf.value if isinstance(lf, LossFunction) else str(lf)
        return d


@register_layer
@dataclass
class OutputLayer(BaseOutputLayer):
    """Dense + loss output layer (reference: `nn/conf/layers/OutputLayer.java`)."""


@register_layer
@dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output layer for RNNs (reference: `RnnOutputLayer.java`)."""

    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def default_preprocessor(self, input_type: InputType):
        from deeplearning4j_tpu.nn.conf.preprocessors import FeedForwardToRnnPreProcessor
        if input_type.kind == "ff":
            return FeedForwardToRnnPreProcessor()
        return None


@register_layer
@dataclass
class LossLayer(BaseOutputLayer):
    """Loss-only layer, no params (reference: `nn/conf/layers/LossLayer.java`)."""

    def param_shapes(self):
        return {}

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type, override):
        self.n_in = self.n_out = input_type.flat_size()


@register_layer
@dataclass
class CenterLossOutputLayer(BaseOutputLayer):
    """Output layer with center loss (reference: `CenterLossOutputLayer.java`).

    Maintains per-class feature centers as non-trainable state updated with
    EMA rate `alpha`; adds `lambda_ * ||f - c_y||^2 / 2` to the loss.
    """

    alpha: float = 0.05
    lambda_: float = 2e-4

    def state_shapes(self):
        return {"centers": (self.n_out, self.n_in)}


@register_layer
@dataclass
class ActivationLayer(Layer):
    """Activation-only layer (reference: `ActivationLayer.java`)."""

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type, override):
        self.n_in = self.n_out = input_type.flat_size()


@register_layer
@dataclass
class DropoutLayer(FeedForwardLayer):
    """Dropout-only layer (reference: `DropoutLayer.java`)."""

    def param_shapes(self):
        return {}

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type, override):
        self.n_in = self.n_out = input_type.flat_size()


@register_layer
@dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index -> vector lookup (reference: `EmbeddingLayer.java`).

    Input: integer indices `[batch]` or one-hot `[batch, n_in]`. TPU-native
    implementation is a gather (`take`), not a onehot-matmul.

    `input_format` pins the interpretation: "auto" (float with last dim
    == n_in reads as one-hot, everything else as indices — ambiguous when
    the sequence length equals n_in), "ids" (always indices), "onehot"
    (always one-hot). The transformer zoo builders pin "ids".
    """

    has_bias: bool = True
    input_format: str = "auto"  # "auto" | "ids" | "onehot"

    def param_shapes(self):
        shapes = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes


@register_layer
@dataclass
class ConvolutionLayer(FeedForwardLayer):
    """2-D convolution (reference: `nn/conf/layers/ConvolutionLayer.java`).

    n_in = input channels, n_out = output filters. Kernel stored HWIO
    `[kh, kw, in, out]` (XLA-native); NHWC activations.
    """

    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: Optional[Any] = None  # None -> builder global (default TRUNCATE)
    dilation: Tuple[int, int] = (1, 1)
    has_bias: bool = True

    def __post_init__(self):
        self.kernel_size = _tuple2(self.kernel_size)
        self.stride = _tuple2(self.stride)
        self.padding = _tuple2(self.padding)
        self.dilation = _tuple2(self.dilation)

    def _out_hw(self, h: int, w: int) -> Tuple[int, int]:
        mode = ConvolutionMode.of(self.convolution_mode) or ConvolutionMode.TRUNCATE
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        if mode == ConvolutionMode.SAME:
            return (-(-h // sh), -(-w // sw))
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        if mode == ConvolutionMode.STRICT:
            if (h + 2 * ph - kh) % sh != 0 or (w + 2 * pw - kw) % sw != 0:
                raise ValueError(
                    f"ConvolutionMode.STRICT: input {h}x{w} with kernel {self.kernel_size}, "
                    f"stride {self.stride}, padding {self.padding} doesn't tile exactly "
                    f"(reference `ConvolutionMode.java` semantics; use TRUNCATE or SAME)"
                )
        return (oh, ow)

    def get_output_type(self, input_type: InputType) -> InputType:
        oh, ow = self._out_hw(input_type.height, input_type.width)
        return InputType.convolutional(oh, ow, self.n_out)

    def set_n_in(self, input_type: InputType, override: bool) -> None:
        if override or not self.n_in:
            self.n_in = input_type.channels

    def default_preprocessor(self, input_type: InputType):
        from deeplearning4j_tpu.nn.conf.preprocessors import FeedForwardToCnnPreProcessor
        if input_type.kind == "cnnflat":
            return FeedForwardToCnnPreProcessor(
                input_type.height, input_type.width, input_type.channels
            )
        return None

    def param_shapes(self):
        kh, kw = self.kernel_size
        shapes = {"W": (kh, kw, self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes


@register_layer
@dataclass
class SubsamplingLayer(Layer):
    """Spatial pooling (reference: `SubsamplingLayer.java`). No params."""

    pooling_type: Any = PoolingType.MAX
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: Optional[Any] = None
    pnorm: int = 2

    def __post_init__(self):
        self.kernel_size = _tuple2(self.kernel_size)
        self.stride = _tuple2(self.stride)
        self.padding = _tuple2(self.padding)

    def get_output_type(self, input_type: InputType) -> InputType:
        helper = ConvolutionLayer(
            kernel_size=self.kernel_size, stride=self.stride, padding=self.padding,
            convolution_mode=self.convolution_mode, n_out=input_type.channels,
        )
        oh, ow = helper._out_hw(input_type.height, input_type.width)
        return InputType.convolutional(oh, ow, input_type.channels)


@register_layer
@dataclass
class BatchNormalization(FeedForwardLayer):
    """Batch normalization (reference: `nn/conf/layers/BatchNormalization.java:28-30`:
    decay 0.9, eps 1e-5, minibatch flag, optional locked gamma/beta)."""

    decay: float = 0.9
    eps: float = 1e-5
    is_minibatch: bool = True
    lock_gamma_beta: bool = False
    gamma: float = 1.0
    beta: float = 0.0

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type, override):
        if override or not self.n_out:
            self.n_in = self.n_out = input_type.flat_size() if input_type.kind in ("ff", "rnn") \
                else input_type.channels

    def default_preprocessor(self, input_type):
        return None

    def param_shapes(self):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": (self.n_out,), "beta": (self.n_out,)}

    def state_shapes(self):
        return {"mean": (self.n_out,), "var": (self.n_out,)}


@register_layer
@dataclass
class BottleneckBlock(FeedForwardLayer):
    """Fused ResNet bottleneck block (PR 19): conv1x1 -> BN+act ->
    conv3x3 -> BN+act -> conv1x1 -> BN -> residual add -> act as ONE
    layer, dispatched through the `bottleneck_block` kernel seam
    (`kernels/bottleneck_block.py`). The unfused equivalent is the
    five-vertex chain `models/resnet.py::_bottleneck` emits; this layer
    is what `resnet50(fused_blocks=True)` emits instead — plain conv
    stacks are untouched.

    `filters` is the squeeze width (branch a/b); the block's output is
    `4 * filters` channels. `project=True` adds the 1x1 projection
    shortcut (stage boundaries); otherwise the input rides the residual
    unchanged (requires n_in == 4 * filters, the resnet invariant).
    BN hyperparameters mirror `BatchNormalization` (decay 0.9, eps 1e-5,
    minibatch stats in train mode).
    """

    filters: int = 64
    stride: Tuple[int, int] = (1, 1)
    project: bool = False
    decay: float = 0.9
    eps: float = 1e-5
    is_minibatch: bool = True

    def __post_init__(self):
        self.stride = _tuple2(self.stride)

    def branch_names(self) -> Tuple[str, ...]:
        return ("a", "b", "c") + (("proj",) if self.project else ())

    def get_output_type(self, input_type: InputType) -> InputType:
        sh, sw = self.stride
        return InputType.convolutional(
            -(-input_type.height // sh), -(-input_type.width // sw),
            4 * self.filters)

    def set_n_in(self, input_type: InputType, override: bool) -> None:
        if override or not self.n_in:
            self.n_in = input_type.channels
        self.n_out = 4 * self.filters

    def default_preprocessor(self, input_type: InputType):
        # NHWC in, NHWC out — never flatten (overrides FeedForwardLayer's
        # CnnToFeedForward default).
        return None

    def param_shapes(self):
        f1, f3 = self.filters, 4 * self.filters
        shapes = {
            "W_a": (1, 1, self.n_in, f1), "gamma_a": (f1,), "beta_a": (f1,),
            "W_b": (3, 3, f1, f1), "gamma_b": (f1,), "beta_b": (f1,),
            "W_c": (1, 1, f1, f3), "gamma_c": (f3,), "beta_c": (f3,),
        }
        if self.project:
            shapes.update({"W_proj": (1, 1, self.n_in, f3),
                           "gamma_proj": (f3,), "beta_proj": (f3,)})
        return shapes

    def state_shapes(self):
        f1, f3 = self.filters, 4 * self.filters
        shapes = {"mean_a": (f1,), "var_a": (f1,),
                  "mean_b": (f1,), "var_b": (f1,),
                  "mean_c": (f3,), "var_c": (f3,)}
        if self.project:
            shapes.update({"mean_proj": (f3,), "var_proj": (f3,)})
        return shapes


@register_layer
@dataclass
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference: `LocalResponseNormalization.java`;
    defaults k=2, n=5, alpha=1e-4, beta=0.75). No params."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75


@register_layer
@dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    def get_output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def default_preprocessor(self, input_type: InputType):
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor,
        )
        if input_type.kind == "ff":
            return FeedForwardToRnnPreProcessor()
        if input_type.kind == "cnn":
            return CnnToRnnPreProcessor(input_type.height, input_type.width, input_type.channels)
        return None


@register_layer
@dataclass
class GravesLSTM(BaseRecurrentLayer):
    """LSTM with peephole connections (reference: `nn/conf/layers/GravesLSTM.java`,
    impl semantics `nn/layers/recurrent/LSTMHelpers.java:58-160`).

    Params: `W` input weights `[n_in, 4*n_out]` (gate order i,f,o,g),
    `RW` recurrent weights `[n_out, 4*n_out]`, `pW` peepholes `[3*n_out]`
    (f,o,g order as in the reference's 3 extra columns), `b` `[4*n_out]` with
    forget-gate bias init. The reference packs peepholes into RW's last 3
    columns; we keep a separate leaf (same dof, cleaner sharding).
    """

    forget_gate_bias_init: float = 1.0
    gate_activation: Any = Activation.SIGMOID

    def param_shapes(self):
        return {
            "W": (self.n_in, 4 * self.n_out),
            "RW": (self.n_out, 4 * self.n_out),
            "pW": (3 * self.n_out,),
            "b": (4 * self.n_out,),
        }


@register_layer
@dataclass
class LSTM(BaseRecurrentLayer):
    """Standard LSTM without peepholes (cuDNN-compatible variant)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: Any = Activation.SIGMOID

    def param_shapes(self):
        return {
            "W": (self.n_in, 4 * self.n_out),
            "RW": (self.n_out, 4 * self.n_out),
            "b": (4 * self.n_out,),
        }


@register_layer
@dataclass
class GravesBidirectionalLSTM(BaseRecurrentLayer):
    """Bidirectional peephole LSTM (reference: `GravesBidirectionalLSTM.java`).
    Output is the sum of forward and backward passes (reference semantics)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: Any = Activation.SIGMOID

    def param_shapes(self):
        return {
            "W_f": (self.n_in, 4 * self.n_out),
            "RW_f": (self.n_out, 4 * self.n_out),
            "pW_f": (3 * self.n_out,),
            "b_f": (4 * self.n_out,),
            "W_b": (self.n_in, 4 * self.n_out),
            "RW_b": (self.n_out, 4 * self.n_out),
            "pW_b": (3 * self.n_out,),
            "b_b": (4 * self.n_out,),
        }


@register_layer
@dataclass
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} RW + b)."""

    def param_shapes(self):
        return {
            "W": (self.n_in, self.n_out),
            "RW": (self.n_out, self.n_out),
            "b": (self.n_out,),
        }


@register_layer
@dataclass
class LayerNormalization(FeedForwardLayer):
    """Per-example layer norm over the feature axis (gamma/beta learned).

    No reference equivalent (the reference predates LN; its normalizer is
    BatchNormalization) — added for the transformer model family
    (`models/zoo.transformer_lm`), where batch statistics are wrong for
    autoregressive training. Works on [B, F] and [B, T, F]."""

    eps: float = 1e-5
    activation: Any = "identity"

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type, override):
        self.n_in = self.n_out = input_type.flat_size()

    def param_shapes(self):
        return {"gamma": (self.n_out,), "beta": (self.n_out,)}


@register_layer
@dataclass
class PositionalEmbeddingLayer(FeedForwardLayer):
    """Learned position table added to a [B, T, F] sequence (GPT-style).

    No reference equivalent (predates transformers); feeds
    `models/zoo.transformer_lm`. `max_length` rows are allocated; forward
    slices the first T (T <= max_length enforced at trace time).

    `stateful=True` adds a position cursor to the layer's (undeclared)
    state, so single-token decode steps via `rnn_time_step` get the right
    position rows (set by `transformer_lm(decode_cache_length=...)`).
    Default False: every forward starts at position 0, preserving plain /
    tBPTT semantics."""

    max_length: int = 512
    stateful: bool = False
    activation: Any = "identity"

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type, override):
        self.n_in = self.n_out = input_type.flat_size()

    def param_shapes(self):
        return {"P": (self.max_length, self.n_out)}


@register_layer
@dataclass
class SelfAttentionLayer(BaseRecurrentLayer):
    """Multi-head self-attention over a [B, T, F] sequence.

    No reference equivalent (the reference predates attention; its
    long-sequence mechanism is tBPTT, `MultiLayerNetwork.java:1207`) —
    this is SURVEY.md §5's named TPU-native extension, surfaced through
    the config DSL. The impl (`nn/layers/attention.py`) picks the Pallas
    flash kernel, masked XLA dense, or mesh-sharded ring attention at
    trace time from the active `ParallelContext`.

    n_in = input feature size, n_out = model width (divisible by
    n_heads). `activation` defaults to identity (an attention block is
    linear after the softmax-weighted sum; set it explicitly to opt in).
    """

    n_heads: int = 4
    causal: bool = True
    # "auto" (Pallas flash; ring when seq-sharded) | "dense" (XLA oracle) |
    # "ulysses" (all-to-all head sharding when seq-sharded; flash otherwise)
    attention_impl: str = "auto"
    # KV-cache capacity for stateful decode via `rnn_time_step` (None
    # disables). The layer always emits its cache as undeclared state; the
    # engines persist it only on the stateful path, and XLA dead-code-
    # eliminates it everywhere else, so training cost is zero.
    decode_cache_length: Optional[int] = None
    activation: Any = "identity"

    def param_shapes(self):
        # No key bias: softmax is invariant to the per-query constant q·kB
        # adds to every score, so kB's true gradient is identically zero —
        # a degenerate parameter that adaptive updaters would random-walk.
        return {
            "Wq": (self.n_in, self.n_out), "qB": (self.n_out,),
            "Wk": (self.n_in, self.n_out),
            "Wv": (self.n_in, self.n_out), "vB": (self.n_out,),
            "Wo": (self.n_out, self.n_out), "oB": (self.n_out,),
        }


@register_layer
@dataclass
class MoELayer(FeedForwardLayer):
    """Mixture-of-experts FFN with GShard routing (top-1/top-2, capacity
    dropping, router jitter, load-balance aux loss).

    No reference equivalent (predates MoE; SURVEY.md §2.3 extension row).
    The engines fold `aux_loss_weight * load_balance_loss` into the
    training objective; under a `ParallelContext` with an expert axis the
    experts shard across the mesh (`nn/layers/moe.py`).

    n_in = n_out = model width; `expert_hidden` is each expert's FFN
    hidden size (the expert MLP's own ReLU is fixed — `activation`
    defaults to identity and applies to the combined output).
    """

    n_experts: int = 4
    expert_hidden: int = 0  # 0 -> 4 * n_in at build time
    capacity_factor: float = 1.25
    top_k: int = 2
    router_jitter: float = 0.0
    aux_loss_weight: float = 1e-2
    activation: Any = "identity"

    def set_n_in(self, input_type: InputType, override: bool) -> None:
        super().set_n_in(input_type, override)
        if not self.expert_hidden:
            self.expert_hidden = 4 * self.n_in

    def param_shapes(self):
        E, h = self.n_experts, self.expert_hidden or 4 * self.n_in
        return {
            "gate_w": (self.n_in, E),
            "w1": (E, self.n_in, h), "b_1": (E, h),
            "w2": (E, h, self.n_out), "b_2": (E, self.n_out),
        }


@register_layer
@dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over time or space (reference: `GlobalPoolingLayer.java`;
    SUM/AVG/MAX/PNORM, mask-aware)."""

    pooling_type: Any = PoolingType.MAX
    pooling_dimensions: Optional[Tuple[int, ...]] = None
    collapse_dimensions: bool = True
    pnorm: int = 2

    def get_output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        return input_type


@register_layer
@dataclass
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder (reference: `nn/conf/layers/AutoEncoder.java`,
    impl `nn/layers/feedforward/autoencoder/AutoEncoder.java`). Pretrainable."""

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss_function: Any = LossFunction.RECONSTRUCTION_CROSSENTROPY

    def param_shapes(self):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,), "vb": (self.n_in,)}

    def is_pretrainable(self):
        return True


@register_layer
@dataclass
class RBM(FeedForwardLayer):
    """Restricted Boltzmann machine (reference: `nn/conf/layers/RBM.java:83-86`,
    contrastive divergence in `nn/layers/feedforward/rbm/RBM.java:101`).
    Visible/hidden unit types: binary | gaussian | softmax | rectified."""

    visible_unit: str = "binary"
    hidden_unit: str = "binary"
    k: int = 1  # CD-k steps
    sparsity: float = 0.0
    loss_function: Any = LossFunction.RECONSTRUCTION_CROSSENTROPY

    def param_shapes(self):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,), "vb": (self.n_in,)}

    def is_pretrainable(self):
        return True


@register_layer
@dataclass
class VariationalAutoencoder(FeedForwardLayer):
    """VAE (reference: `nn/conf/layers/variational/VariationalAutoencoder.java`,
    impl `nn/layers/variational/VariationalAutoencoder.java:48-79`): own
    encoder/decoder MLP stacks, pluggable reconstruction distribution,
    n_out = latent size. Pretrainable; supervised forward uses the mean."""

    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    # "gaussian" | "bernoulli" | "exponential", a loss wrapper
    # ("loss", loss_function[, activation]), or a composite list of
    # (spec, data_size) pairs (reference: `conf/layers/variational/`
    # ReconstructionDistribution SPI incl. Composite + LossFunctionWrapper).
    reconstruction_distribution: Any = "gaussian"
    pzx_activation: Any = Activation.IDENTITY
    num_samples: int = 1

    def param_shapes(self):
        shapes: Dict[str, Tuple[int, ...]] = {}
        prev = self.n_in
        for i, size in enumerate(self.encoder_layer_sizes):
            shapes[f"eW{i}"] = (prev, size)
            shapes[f"eb{i}"] = (size,)
            prev = size
        shapes["pZXMeanW"] = (prev, self.n_out)
        shapes["pZXMeanB"] = (self.n_out,)
        shapes["pZXLogStd2W"] = (prev, self.n_out)
        shapes["pZXLogStd2B"] = (self.n_out,)
        prev = self.n_out
        for i, size in enumerate(self.decoder_layer_sizes):
            shapes[f"dW{i}"] = (prev, size)
            shapes[f"db{i}"] = (size,)
            prev = size
        from deeplearning4j_tpu.nn.layers.variational import dist_input_size
        dist_size = dist_input_size(self.reconstruction_distribution, self.n_in)
        shapes["pXZW"] = (prev, dist_size)
        shapes["pXZB"] = (dist_size,)
        return shapes

    def is_pretrainable(self):
        return True
