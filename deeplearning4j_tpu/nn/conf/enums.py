"""Core enumerations of the configuration DSL.

Mirrors the reference's enum surface (activations/losses/updaters/weight-init/
gradient-normalization/etc.; see reference `nn/conf/`, `nn/weights/WeightInit.java`,
`nn/conf/GradientNormalization.java`, `nn/api/OptimizationAlgorithm.java`) so a
DL4J user finds the same vocabulary, but values are plain strings so every config
JSON round-trips without a JVM.
"""

from __future__ import annotations

import enum


class _StrEnum(str, enum.Enum):
    """String-valued enum: JSON-serializes to its value, compares to strings."""

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.value

    @classmethod
    def of(cls, v):
        if v is None or isinstance(v, cls):
            return v
        return cls(str(v).lower())


class Activation(_StrEnum):
    """Activation functions (reference: ND4J `Activation` enum / `IActivation` SPI)."""

    SIGMOID = "sigmoid"
    TANH = "tanh"
    SOFTMAX = "softmax"
    IDENTITY = "identity"
    RELU = "relu"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    CUBE = "cube"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    SELU = "selu"
    GELU = "gelu"
    SWISH = "swish"


class LossFunction(_StrEnum):
    """Loss functions (reference: ND4J `ILossFunction` impls; SURVEY.md §2.4)."""

    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    SQUARED_LOSS = "squared_loss"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mean_absolute_percentage_error"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "mean_squared_logarithmic_error"
    XENT = "xent"  # binary cross entropy
    MCXENT = "mcxent"  # multi-class cross entropy
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    POISSON = "poisson"
    RMSE_XENT = "rmse_xent"


class Updater(_StrEnum):
    """Gradient updaters (reference: `nn/updater/LayerUpdater.java:240-272`)."""

    SGD = "sgd"
    ADAM = "adam"
    ADAMAX = "adamax"
    ADADELTA = "adadelta"
    NESTEROVS = "nesterovs"
    ADAGRAD = "adagrad"
    RMSPROP = "rmsprop"
    NONE = "none"


class WeightInit(_StrEnum):
    """Weight initialization schemes (reference: `nn/weights/WeightInit.java`)."""

    ZERO = "zero"
    ONES = "ones"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    NORMALIZED = "normalized"
    SIZE = "size"
    VI = "vi"
    DISTRIBUTION = "distribution"
    IDENTITY = "identity"


class GradientNormalization(_StrEnum):
    """Gradient normalization/clipping (reference: `nn/updater/LayerUpdater.java:181-221`)."""

    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalizel2perlayer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalizel2perparamtype"
    CLIP_ELEMENT_WISE_ABSOLUTE_VALUE = "clipelementwiseabsolutevalue"
    CLIP_L2_PER_LAYER = "clipl2perlayer"
    CLIP_L2_PER_PARAM_TYPE = "clipl2perparamtype"


class OptimizationAlgorithm(_StrEnum):
    """Optimization algorithms (reference: `nn/api/OptimizationAlgorithm.java`)."""

    STOCHASTIC_GRADIENT_DESCENT = "stochastic_gradient_descent"
    LINE_GRADIENT_DESCENT = "line_gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    LBFGS = "lbfgs"


class ConvolutionMode(_StrEnum):
    """Convolution padding semantics (reference: `nn/conf/ConvolutionMode.java:9-19`)."""

    STRICT = "strict"
    TRUNCATE = "truncate"
    SAME = "same"


class PoolingType(_StrEnum):
    """Pooling types (reference: `nn/conf/layers/PoolingType`-style; GlobalPooling SUM/AVG/MAX/PNORM)."""

    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"
    NONE = "none"


class BackpropType(_StrEnum):
    """Backprop style (reference: `MultiLayerConfiguration.java:66-68`)."""

    STANDARD = "standard"
    TRUNCATED_BPTT = "truncatedbptt"


class LearningRatePolicy(_StrEnum):
    """LR decay policies (reference: `nn/updater/LayerUpdater.java:134-158`)."""

    NONE = "none"
    EXPONENTIAL = "exponential"
    INVERSE = "inverse"
    POLY = "poly"
    SIGMOID = "sigmoid"
    STEP = "step"
    TORCH_STEP = "torchstep"
    SCHEDULE = "schedule"
    SCORE = "score"


class MaskState(_StrEnum):
    """Mask propagation state (reference: `nn/api/MaskState.java:19`)."""

    ACTIVE = "active"
    PASSTHROUGH = "passthrough"


class CacheMode(_StrEnum):
    NONE = "none"
    DEVICE = "device"
    HOST = "host"
