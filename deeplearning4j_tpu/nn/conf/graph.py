"""Graph vertex configurations for ComputationGraph.

Equivalent of the reference's `nn/conf/graph/` vertex configs (Merge,
ElementWise, Subset, Stack, Unstack, Scale, L2, L2Normalize, Preprocessor,
rnn/{LastTimeStep, DuplicateToTimeSeries}; see `nn/graph/vertex/impl/`).
Vertices are pure functions of their input arrays; backward is autodiff.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, layer_from_dict
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor,
    preprocessor_from_dict,
)

_VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_from_dict(d: dict):
    d = dict(d)
    kind = d.pop("@class")
    cls = _VERTEX_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"Unknown graph vertex: {kind}")
    return cls.from_dict(d)


@dataclass
class GraphVertexConf:
    """Base vertex config (reference SPI: `nn/graph/vertex/GraphVertex.java:37`)."""

    def apply(self, inputs, masks=None):
        raise NotImplementedError

    def get_output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def to_dict(self):
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            if k.startswith("_") or v is None:
                continue
            if isinstance(v, Layer):
                v = v.to_dict()
            elif isinstance(v, InputPreProcessor):
                v = v.to_dict()
            elif isinstance(v, tuple):
                v = list(v)
            d[k] = v
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


@register_vertex
@dataclass
class LayerVertex(GraphVertexConf):
    """Wraps a layer (+ optional preprocessor) as a vertex (reference: `LayerVertex.java`)."""

    layer: Optional[Layer] = None
    preprocessor: Optional[InputPreProcessor] = None

    def get_output_type(self, *input_types):
        it = input_types[0]
        if self.preprocessor is not None:
            it = self.preprocessor.get_output_type(it)
        return self.layer.get_output_type(it)

    @classmethod
    def from_dict(cls, d):
        return cls(
            layer=layer_from_dict(d["layer"]) if d.get("layer") else None,
            preprocessor=preprocessor_from_dict(d.get("preprocessor")),
        )


@register_vertex
@dataclass
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature (last) axis (reference: `MergeVertex.java`;
    the reference concatenates along dim 1 = channels/features in NCHW — the
    feature axis is last here)."""

    def apply(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=-1)

    def get_output_type(self, *input_types):
        first = input_types[0]
        if first.kind == "cnn":
            return InputType.convolutional(
                first.height, first.width, sum(t.channels for t in input_types)
            )
        total = sum(t.flat_size() for t in input_types)
        if first.kind == "rnn":
            return InputType.recurrent(total, first.timeseries_length)
        return InputType.feed_forward(total)


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertexConf):
    """Pointwise Add/Subtract/Product/Average/Max of equal-shape inputs
    (reference: `ElementWiseVertex.java`)."""

    op: str = "add"  # add | subtract | product | average | max

    def apply(self, inputs, masks=None):
        op = self.op.lower()
        out = inputs[0]
        if op == "add":
            for x in inputs[1:]:
                out = out + x
        elif op == "subtract":
            if len(inputs) != 2:
                raise ValueError("ElementWiseVertex subtract requires exactly 2 inputs")
            out = inputs[0] - inputs[1]
        elif op == "product":
            for x in inputs[1:]:
                out = out * x
        elif op == "average":
            out = sum(inputs) / len(inputs)
        elif op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"Unknown ElementWiseVertex op: {self.op}")
        return out


@register_vertex
@dataclass
class SubsetVertex(GraphVertexConf):
    """Feature-axis slice [from, to] inclusive (reference: `SubsetVertex.java`)."""

    from_index: int = 0
    to_index: int = 0

    def apply(self, inputs, masks=None):
        return inputs[0][..., self.from_index : self.to_index + 1]

    def get_output_type(self, *input_types):
        n = self.to_index - self.from_index + 1
        it = input_types[0]
        if it.kind == "rnn":
            return InputType.recurrent(n, it.timeseries_length)
        if it.kind == "cnn":
            return InputType.convolutional(it.height, it.width, n)
        return InputType.feed_forward(n)


@register_vertex
@dataclass
class StackVertex(GraphVertexConf):
    """Stack along batch axis (reference: `StackVertex.java`)."""

    def apply(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclass
class UnstackVertex(GraphVertexConf):
    """Unstack: take slice `from_index` of `stack_size` along batch axis
    (reference: `UnstackVertex.java`)."""

    from_index: int = 0
    stack_size: int = 1

    def apply(self, inputs, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step : (self.from_index + 1) * step]


@register_vertex
@dataclass
class ScaleVertex(GraphVertexConf):
    """Multiply by a fixed scalar (reference: `ScaleVertex.java`)."""

    scale_factor: float = 1.0

    def apply(self, inputs, masks=None):
        return inputs[0] * self.scale_factor


@register_vertex
@dataclass
class ShiftVertex(GraphVertexConf):
    """Add a fixed scalar (reference: `ShiftVertex.java`)."""

    shift_factor: float = 0.0

    def apply(self, inputs, masks=None):
        return inputs[0] + self.shift_factor


@register_vertex
@dataclass
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two inputs (reference: `L2Vertex.java`).
    Output [batch, 1]."""

    eps: float = 1e-8

    def apply(self, inputs, masks=None):
        a, b = inputs
        d2 = jnp.sum((a - b) ** 2, axis=tuple(range(1, a.ndim)))
        return jnp.sqrt(jnp.maximum(d2, self.eps))[:, None]

    def get_output_type(self, *input_types):
        return InputType.feed_forward(1)


@register_vertex
@dataclass
class L2NormalizeVertex(GraphVertexConf):
    """L2-normalize along feature axes (reference: `L2NormalizeVertex.java`)."""

    eps: float = 1e-8

    def apply(self, inputs, masks=None):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(x ** 2, axis=tuple(range(1, x.ndim)), keepdims=True))
        return x / jnp.maximum(norm, self.eps)


@register_vertex
@dataclass
class PreprocessorVertex(GraphVertexConf):
    """Standalone preprocessor as a vertex (reference: `PreprocessorVertex.java`)."""

    preprocessor: Optional[InputPreProcessor] = None

    def apply(self, inputs, masks=None):
        out, _ = self.preprocessor(inputs[0], masks[0] if masks else None)
        return out

    def get_output_type(self, *input_types):
        return self.preprocessor.get_output_type(input_types[0])

    @classmethod
    def from_dict(cls, d):
        return cls(preprocessor=preprocessor_from_dict(d.get("preprocessor")))


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertexConf):
    """[b,t,f] -> [b,f] at the last unmasked step (reference:
    `rnn/LastTimeStepVertex.java`)."""

    mask_array_input: Optional[str] = None

    def apply(self, inputs, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return x[:, -1, :]
        idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]

    def get_output_type(self, *input_types):
        return InputType.feed_forward(input_types[0].size)


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """[b,f] -> [b,t,f], t taken from a reference input (reference:
    `rnn/DuplicateToTimeSeriesVertex.java`)."""

    input_name: Optional[str] = None
    _time_steps: Optional[int] = None  # resolved at apply time by the engine

    def apply(self, inputs, masks=None, time_steps=None):
        x = inputs[0]
        t = time_steps if time_steps is not None else self._time_steps
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[1]))

    def get_output_type(self, *input_types):
        return InputType.recurrent(input_types[0].flat_size())


@register_vertex
@dataclass
class ReverseTimeSeriesVertex(GraphVertexConf):
    """Reverse along time, respecting masks (reference: `ReverseTimeSeriesVertex.java`):
    with a mask, only each example's unmasked prefix [0, len) is reversed in
    place; padding stays at the tail."""

    mask_array_input: Optional[str] = None

    def apply(self, inputs, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return x[:, ::-1, :]
        t = x.shape[1]
        lengths = jnp.sum(mask, axis=1).astype(jnp.int32)  # [b]
        pos = jnp.arange(t)[None, :]  # [1, t]
        # Index (len - 1 - pos) inside the prefix, identity in the padding.
        src = jnp.where(pos < lengths[:, None], lengths[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(x, src[:, :, None], axis=1)
