"""Input preprocessors: shape adapters between layer families.

Equivalent of the reference's `nn/conf/preprocessor/` (CnnToFeedForward,
FeedForwardToCnn, CnnToRnn, RnnToCnn, FeedForwardToRnn, RnnToFeedForward,
Reshape, Composable). Only forward transforms are defined — backward shape
restoration is autodiff's job in the TPU build.

Layouts are feature-last (NHWC / [batch, time, features]); see
`nn/conf/inputs.py`. Because dense layers here operate on the last axis and
broadcast over leading axes, Rnn<->FeedForward preprocessors are identity on
data and exist for config parity and mask handling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

_PREPROCESSOR_REGISTRY: Dict[str, type] = {}


def register_preprocessor(cls):
    _PREPROCESSOR_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_from_dict(d):
    if d is None:
        return None
    d = dict(d)
    kind = d.pop("@class")
    if kind == "ComposableInputPreProcessor":
        return ComposableInputPreProcessor(
            *[preprocessor_from_dict(p) for p in d["preprocessors"]]
        )
    cls = _PREPROCESSOR_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"Unknown preprocessor: {kind}")
    for key in ("target_shape",):
        if key in d and isinstance(d[key], list):
            d[key] = tuple(d[key])
    return cls(**d)


@dataclass
class InputPreProcessor:
    def __call__(self, x, mask=None):
        """Returns (transformed activations, transformed mask)."""
        return x, mask

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def to_dict(self):
        d = {"@class": type(self).__name__}
        d.update({k: v for k, v in self.__dict__.items() if not k.startswith("_")})
        return d


@register_preprocessor
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b,h,w,c] -> [b, h*w*c] (reference: `CnnToFeedForwardPreProcessor.java`)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        return x.reshape(x.shape[0], -1), mask

    def get_output_type(self, input_type):
        return InputType.feed_forward(
            input_type.height * input_type.width * input_type.channels
        )


@register_preprocessor
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[b, h*w*c] -> [b,h,w,c] (reference: `FeedForwardToCnnPreProcessor.java`).

    Note: the reference unflattens NCHW; we unflatten NHWC. Flat inputs in the
    reference's channel-major order must be converted at the data boundary
    (see `datasets/`): the MNIST-style c=1 case is layout-identical.
    """

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        return x.reshape(x.shape[0], self.input_height, self.input_width, self.num_channels), mask

    def get_output_type(self, input_type):
        return InputType.convolutional(self.input_height, self.input_width, self.num_channels)


@register_preprocessor
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[b*t, f] -> [b, t, f] in the reference; identity here (dense ops are
    feature-last and broadcast over time)."""

    def get_output_type(self, input_type):
        if input_type.kind == "ff":
            return InputType.recurrent(input_type.size)
        return input_type


@register_preprocessor
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, t, f] -> [b*t, f] in the reference; identity here."""

    def get_output_type(self, input_type):
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        return input_type


@register_preprocessor
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[b,h,w,c] -> [b, 1, h*w*c]: CNN features as a single-timestep sequence
    (reference: `CnnToRnnPreProcessor.java`, which maps [b*t,c,h,w] -> [b,c*h*w,t])."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        if x.ndim == 4:  # [b,h,w,c] — single step
            return x.reshape(x.shape[0], 1, -1), mask
        # [b,t,h,w,c]
        return x.reshape(x.shape[0], x.shape[1], -1), mask

    def get_output_type(self, input_type):
        return InputType.recurrent(
            input_type.height * input_type.width * input_type.channels
        )


@register_preprocessor
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    """[b,t,h*w*c] -> [b*t or b,t,h,w,c] (reference: `RnnToCnnPreProcessor.java`)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        b, t = x.shape[0], x.shape[1]
        return (
            x.reshape(b, t, self.input_height, self.input_width, self.num_channels),
            mask,
        )

    def get_output_type(self, input_type):
        return InputType.convolutional(self.input_height, self.input_width, self.num_channels)


@register_preprocessor
@dataclass
class ReshapePreProcessor(InputPreProcessor):
    """Free-form reshape keeping the batch axis (reference: `ReshapePreProcessor.java`)."""

    target_shape: Optional[Tuple[int, ...]] = None

    def __call__(self, x, mask=None):
        return x.reshape((x.shape[0],) + tuple(self.target_shape or ())), mask


@dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain of preprocessors (reference: `ComposableInputPreProcessor.java`)."""

    def __init__(self, *preprocessors):
        self.preprocessors = list(preprocessors)

    def __call__(self, x, mask=None):
        for p in self.preprocessors:
            x, mask = p(x, mask)
        return x, mask

    def get_output_type(self, input_type):
        for p in self.preprocessors:
            input_type = p.get_output_type(input_type)
        return input_type

    def to_dict(self):
        return {
            "@class": "ComposableInputPreProcessor",
            "preprocessors": [p.to_dict() for p in self.preprocessors],
        }


_PREPROCESSOR_REGISTRY["ComposableInputPreProcessor"] = ComposableInputPreProcessor
