"""Input preprocessors: shape adapters between layer families.

Equivalent of the reference's `nn/conf/preprocessor/` (CnnToFeedForward,
FeedForwardToCnn, CnnToRnn, RnnToCnn, FeedForwardToRnn, RnnToFeedForward,
Reshape, Composable). Only forward transforms are defined — backward shape
restoration is autodiff's job in the TPU build.

Layouts are feature-last (NHWC / [batch, time, features]); see
`nn/conf/inputs.py`. Because dense layers here operate on the last axis and
broadcast over leading axes, Rnn<->FeedForward preprocessors are identity on
data and exist for config parity and mask handling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

_PREPROCESSOR_REGISTRY: Dict[str, type] = {}


def register_preprocessor(cls):
    _PREPROCESSOR_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_from_dict(d):
    if d is None:
        return None
    d = dict(d)
    kind = d.pop("@class")
    if kind == "ComposableInputPreProcessor":
        return ComposableInputPreProcessor(
            *[preprocessor_from_dict(p) for p in d["preprocessors"]]
        )
    cls = _PREPROCESSOR_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"Unknown preprocessor: {kind}")
    for key in ("target_shape",):
        if key in d and isinstance(d[key], list):
            d[key] = tuple(d[key])
    return cls(**d)


@dataclass
class InputPreProcessor:
    def __call__(self, x, mask=None):
        """Returns (transformed activations, transformed mask)."""
        return x, mask

    def get_output_type(self, input_type: InputType) -> InputType:
        return input_type

    def to_dict(self):
        d = {"@class": type(self).__name__}
        d.update({k: v for k, v in self.__dict__.items() if not k.startswith("_")})
        return d


@register_preprocessor
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b,h,w,c] -> [b, h*w*c] (reference: `CnnToFeedForwardPreProcessor.java`)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        return x.reshape(x.shape[0], -1), mask

    def get_output_type(self, input_type):
        return InputType.feed_forward(
            input_type.height * input_type.width * input_type.channels
        )


@register_preprocessor
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[b, h*w*c] -> [b,h,w,c] (reference: `FeedForwardToCnnPreProcessor.java`).

    Note: the reference unflattens NCHW; we unflatten NHWC. Flat inputs in the
    reference's channel-major order must be converted at the data boundary
    (see `datasets/`): the MNIST-style c=1 case is layout-identical.
    """

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        return x.reshape(x.shape[0], self.input_height, self.input_width, self.num_channels), mask

    def get_output_type(self, input_type):
        return InputType.convolutional(self.input_height, self.input_width, self.num_channels)


@register_preprocessor
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[b*t, f] -> [b, t, f] in the reference; identity here (dense ops are
    feature-last and broadcast over time)."""

    def get_output_type(self, input_type):
        if input_type.kind == "ff":
            return InputType.recurrent(input_type.size)
        return input_type


@register_preprocessor
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, t, f] -> [b*t, f] in the reference; identity here."""

    def get_output_type(self, input_type):
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        return input_type


@register_preprocessor
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[b,h,w,c] -> [b, 1, h*w*c]: CNN features as a single-timestep sequence
    (reference: `CnnToRnnPreProcessor.java`, which maps [b*t,c,h,w] -> [b,c*h*w,t])."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        if x.ndim == 4:  # [b,h,w,c] — single step
            return x.reshape(x.shape[0], 1, -1), mask
        # [b,t,h,w,c]
        return x.reshape(x.shape[0], x.shape[1], -1), mask

    def get_output_type(self, input_type):
        return InputType.recurrent(
            input_type.height * input_type.width * input_type.channels
        )


@register_preprocessor
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    """[b,t,h*w*c] -> [b*t or b,t,h,w,c] (reference: `RnnToCnnPreProcessor.java`)."""

    input_height: int = 0
    input_width: int = 0
    num_channels: int = 0

    def __call__(self, x, mask=None):
        b, t = x.shape[0], x.shape[1]
        return (
            x.reshape(b, t, self.input_height, self.input_width, self.num_channels),
            mask,
        )

    def get_output_type(self, input_type):
        return InputType.convolutional(self.input_height, self.input_width, self.num_channels)


@register_preprocessor
@dataclass
class ReshapePreProcessor(InputPreProcessor):
    """Free-form reshape keeping the batch axis (reference: `ReshapePreProcessor.java`)."""

    target_shape: Optional[Tuple[int, ...]] = None

    def __call__(self, x, mask=None):
        return x.reshape((x.shape[0],) + tuple(self.target_shape or ())), mask


@dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain of preprocessors (reference: `ComposableInputPreProcessor.java`)."""

    def __init__(self, *preprocessors):
        self.preprocessors = list(preprocessors)

    def __call__(self, x, mask=None):
        for p in self.preprocessors:
            x, mask = p(x, mask)
        return x, mask

    def get_output_type(self, input_type):
        for p in self.preprocessors:
            input_type = p.get_output_type(input_type)
        return input_type

    def to_dict(self):
        return {
            "@class": "ComposableInputPreProcessor",
            "preprocessors": [p.to_dict() for p in self.preprocessors],
        }


_PREPROCESSOR_REGISTRY["ComposableInputPreProcessor"] = ComposableInputPreProcessor


# --------------------------------------------------------------------------
# uint8 network-input policy.
#
# uint8 on the wire is deliberately ambiguous: streamed image batches ship
# as bytes and want the device-side /255 ImagePreProcessingScaler (PERF.md
# §3), while embedding ids for small vocabularies also arrive as uint8 and
# must NOT be scaled (dividing ids by 255 floors every id to row 0 of the
# embedding table — silent corruption). The engines used to sniff
# `x.dtype == uint8` and always scale; the decision now lives here, keyed
# on declared model structure (tpulint rule JX006 enforces that this
# module stays the only place that inspects the uint8 wire format).

UINT8_SCALE = "scale"          # image bytes: astype(compute)/255
UINT8_IDS = "ids"              # embedding ids: astype(int32), never scaled
UINT8_AMBIGUOUS = "ambiguous"  # mixed consumers: raise when uint8 arrives


def _consumes_ids(layer) -> bool:
    """Does this first layer read integer ids (gather) rather than values?"""
    return (type(layer).__name__ == "EmbeddingLayer"
            and getattr(layer, "input_format", "auto") != "onehot")


def resolve_uint8_policy(consumers) -> str:
    """Decide what a uint8 network input means from its direct consumers
    (the first layer of a MultiLayerNetwork, or every vertex fed by one
    network input of a ComputationGraph). `None` entries (non-layer
    vertices: merge/elementwise/...) count as value consumers."""
    kinds = set()
    for layer in consumers:
        kinds.add(UINT8_IDS if layer is not None and _consumes_ids(layer)
                  else UINT8_SCALE)
    if not kinds:
        return UINT8_SCALE
    if len(kinds) > 1:
        return UINT8_AMBIGUOUS
    return kinds.pop()


def apply_uint8_policy(x, policy: str, compute_dtype):
    """Stage one network input for the traced forward pass: uint8 image
    bytes scale 0-255 -> 0-1 on device, uint8 ids cast to int32 unscaled,
    floats cast to the compute dtype, everything else passes through.
    Runs under trace — dtype and policy are static, so this adds no ops
    for non-uint8 inputs."""
    if x.dtype == jnp.uint8:
        if policy == UINT8_IDS:
            return x.astype(jnp.int32)
        if policy == UINT8_AMBIGUOUS:
            raise ValueError(
                "uint8 network input is ambiguous: it feeds both an "
                "ids-format EmbeddingLayer (wants raw ids) and a value "
                "consumer (wants /255 image scaling). Feed ids as "
                "int32/int64 or split the input so each consumer gets its "
                "own; refusing to guess rather than silently zeroing ids.")
        return x.astype(compute_dtype) / 255.0
    if jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(compute_dtype)
    return x
