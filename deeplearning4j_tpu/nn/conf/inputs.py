"""Input types for shape inference.

Equivalent of the reference's `nn/conf/inputs/InputType.java:41-77`
(FF / RNN / CNN / CNNFlat). Used by the builders to infer each layer's `n_in`
and to auto-insert input preprocessors between layer families.

Layout note (TPU-first): activations are feature-last —
FF `[batch, size]`, RNN `[batch, time, size]`, CNN NHWC `[batch, h, w, c]` —
because the last axis maps to the TPU lane dimension and NHWC is XLA's
preferred conv layout. The reference uses NCW/NCHW; converters at the
import/serialization boundary handle that.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class InputType:
    kind: str = "ff"  # ff | rnn | cnn | cnnflat
    size: int = 0  # ff/rnn feature size
    timeseries_length: Optional[int] = None  # rnn (None = variable)
    height: int = 0
    width: int = 0
    channels: int = 0

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=size)

    @staticmethod
    def recurrent(size: int, timeseries_length: Optional[int] = None) -> "InputType":
        return InputType(kind="rnn", size=size, timeseries_length=timeseries_length)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(
            kind="cnnflat", height=height, width=width, channels=channels,
            size=height * width * channels,
        )

    def flat_size(self) -> int:
        if self.kind in ("ff", "rnn"):
            return self.size
        return self.height * self.width * self.channels

    def to_dict(self):
        d = {"kind": self.kind}
        if self.kind in ("ff", "rnn"):
            d["size"] = self.size
        if self.kind == "rnn" and self.timeseries_length is not None:
            d["timeseries_length"] = self.timeseries_length
        if self.kind in ("cnn", "cnnflat"):
            d.update(height=self.height, width=self.width, channels=self.channels)
        return d

    @staticmethod
    def from_dict(d):
        if d is None:
            return None
        return InputType(
            kind=d.get("kind", "ff"),
            size=d.get("size", 0),
            timeseries_length=d.get("timeseries_length"),
            height=d.get("height", 0),
            width=d.get("width", 0),
            channels=d.get("channels", 0),
        )
