"""Parameter initialization and flat-view mapping.

Equivalent of the reference's `nn/params/*ParamInitializer` family plus the
flat param view machinery of `MultiLayerNetwork.init():384-473`: params live in
a pytree `{layer_key: {param_name: array}}`; `flatten`/`unflatten` provide the
reference's contiguous 1-D view (deterministic order: layer order, then the
layer's declared `param_shapes()` order) for checkpoint compat and
parameter-averaging-style interop.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.enums import WeightInit
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    BottleneckBlock,
    ConvolutionLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LSTM,
    Layer,
    MoELayer,
    VariationalAutoencoder,
    is_bias_param,
)
from deeplearning4j_tpu.nn.weights import init_weights


def _fans(conf: Layer, name: str, shape: Tuple[int, ...]) -> Tuple[float, float]:
    """Fan-in/out per param, following the reference's initializer conventions."""
    if isinstance(conf, ConvolutionLayer) and name == "W":
        kh, kw, cin, cout = shape
        return (cin * kh * kw, cout * kh * kw)
    if isinstance(conf, BottleneckBlock) and len(shape) == 4:
        # Per-branch conv kernels (HWIO): same fans as ConvolutionLayer
        # so fused and unfused blocks draw identical init statistics.
        kh, kw, cin, cout = shape
        return (cin * kh * kw, cout * kh * kw)
    if isinstance(conf, MoELayer) and len(shape) == 3:
        # Per-expert FFN tables [E, in, out]: fans are the PER-EXPERT matmul
        # dims, not the stacked leading axis.
        return (shape[1], shape[2])
    if len(shape) >= 2:
        return (shape[0], shape[1])
    return (shape[0], shape[0])


def init_layer_params(conf: Layer, rng: jax.Array, dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    """Initialize one layer's params from its config (weight-init scheme, bias
    init, LSTM forget-gate bias, BN gamma/beta constants)."""
    shapes = conf.param_shapes()
    if not shapes:
        return {}
    params: Dict[str, jnp.ndarray] = {}
    keys = jax.random.split(rng, len(shapes))
    bias_init = float(getattr(conf, "bias_init", 0.0) or 0.0)

    for key, (name, shape) in zip(keys, shapes.items()):
        if isinstance(conf, BatchNormalization):
            if name == "gamma":
                params[name] = jnp.full(shape, conf.gamma, dtype)
            else:
                params[name] = jnp.full(shape, conf.beta, dtype)
            continue
        if type(conf).__name__ == "LayerNormalization":
            params[name] = (jnp.ones(shape, dtype) if name == "gamma"
                            else jnp.zeros(shape, dtype))
            continue
        if isinstance(conf, BottleneckBlock) and name.startswith("gamma_"):
            # Per-branch BN scale: ones, like BatchNormalization's default
            # gamma (beta_* lands in the bias path below -> zeros).
            params[name] = jnp.ones(shape, dtype)
            continue
        is_bias = is_bias_param(name) and name != "beta"
        is_peephole = name.startswith("pW")
        if is_bias:
            arr = jnp.full(shape, bias_init, dtype)
            if isinstance(conf, (GravesLSTM, LSTM, GravesBidirectionalLSTM)) and name.startswith("b"):
                # Forget-gate bias init (reference: LSTMParamInitializer; gate
                # order i,f,o,g -> forget block is [n_out, 2*n_out)).
                n_out = conf.n_out
                arr = arr.at[n_out : 2 * n_out].set(conf.forget_gate_bias_init)
            params[name] = arr
        elif is_peephole:
            params[name] = jnp.zeros(shape, dtype)
        else:
            fan_in, fan_out = _fans(conf, name, shape)
            if isinstance(conf, (GravesLSTM, LSTM, GravesBidirectionalLSTM)):
                # Reference inits LSTM weight blocks with fan sizes nIn/nOut
                # (not the 4x packed dims).
                fan_in = conf.n_in if name.startswith("W") else conf.n_out
                fan_out = conf.n_out
            if isinstance(conf, VariationalAutoencoder):
                fan_in, fan_out = shape[0], shape[1]
            params[name] = init_weights(
                key, shape, fan_in, fan_out,
                scheme=WeightInit.of(conf.weight_init) or WeightInit.XAVIER,
                distribution=conf.dist, dtype=dtype,
            )
    if getattr(conf, "lora_rank", None):
        from deeplearning4j_tpu.nn import lora as _lora

        # Distinct subkey stream so adding adapters never perturbs the
        # base-weight draws (the base stays bitwise-reproducible).
        params.update(_lora.init_lora_params(
            conf, jax.random.fold_in(rng, len(shapes) + 1), dtype))
    return params


def cast_floating(tree, dtype):
    """Cast every floating leaf of a pytree, leaving integer/bool leaves
    (embedding ids, quantized tensors) untouched."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)


def prep_layer_params(lparams: Dict[str, jnp.ndarray], compute_dtype,
                      layer: Layer = None):
    """Per-use param prep shared by both engines' `_forward_fn` (traced):
    floating leaves cast to the policy's compute dtype, int8 leaves with a
    `<name>__scale` companion (post-training quantization —
    `checkpoint/quantize.py`) dequantize as `q * scale` AT the compute
    dtype, so XLA fuses the dequant into the consuming matmul/conv and the
    f32 weights never materialize in HBM. Default-policy nets trace the
    exact same cast as the old inline `tree_map`.

    LoRA adapter leaves (`nn/lora.py`) resolve here too: a weight with
    `<name>__lora_a` / `<name>__lora_b` siblings becomes
    `W_eff = base + scale * (A @ B)` at the compute dtype, where `base`
    is the (possibly dequantized-int8) weight — adapters compose with
    quantized bases and the rank-r delta fuses into the consuming
    matmul. (`<name>__lora_scale` is consumed by the `__scale` suffix
    skip below; only the factor pair needs explicit handling.)

    `layer` (optional, the conf) lets a layer opt out of engine-side
    dequantization: the fused BottleneckBlock keeps int8 weights and
    their `__scale` siblings intact so the Pallas body dequantizes
    in-register — one byte per weight over the wire instead of four.
    Its XLA fallback applies the exact dequant expression from here."""
    if type(layer).__name__ == "BottleneckBlock":
        out = {}
        for k, a in lparams.items():
            out[k] = (a.astype(compute_dtype)
                      if jnp.issubdtype(a.dtype, jnp.floating)
                      and not k.endswith("__scale") else a)
        return out
    out: Dict[str, jnp.ndarray] = {}
    for k, a in lparams.items():
        if k.endswith(("__scale", "__lora_a", "__lora_b")):
            continue  # consumed alongside their base tensor
        if isinstance(a, dict):  # nested sub-tree (defensive): recurse
            out[k] = prep_layer_params(a, compute_dtype)
            continue
        scale = lparams.get(k + "__scale")
        if scale is not None and jnp.issubdtype(a.dtype, jnp.integer):
            base = a.astype(compute_dtype) * scale.astype(compute_dtype)
        elif jnp.issubdtype(a.dtype, jnp.floating):
            base = a.astype(compute_dtype)
        else:
            out[k] = a
            continue
        la = lparams.get(k + "__lora_a")
        lb = lparams.get(k + "__lora_b")
        if la is not None and lb is not None:
            delta = la.astype(compute_dtype) @ lb.astype(compute_dtype)
            ls = lparams.get(k + "__lora_scale")
            if ls is not None:
                delta = delta * ls.astype(compute_dtype)
            base = base + delta
        out[k] = base
    return out


def init_layer_state(conf: Layer, dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    state = {}
    for name, shape in conf.state_shapes().items():
        if isinstance(conf, BatchNormalization) and name == "var":
            state[name] = jnp.ones(shape, dtype)
        elif isinstance(conf, BottleneckBlock) and name.startswith("var_"):
            state[name] = jnp.ones(shape, dtype)
        else:
            state[name] = jnp.zeros(shape, dtype)
    return state


def num_params(conf: Layer) -> int:
    return int(sum(np.prod(s) for s in conf.param_shapes().values()))


def flatten_params(params: Dict[str, Dict[str, jnp.ndarray]], layer_keys: List[str],
                   param_orders: Dict[str, List[str]]) -> np.ndarray:
    """Flatten to the reference-style contiguous 1-D view (c-order per param)."""
    chunks = []
    for lk in layer_keys:
        for pn in param_orders[lk]:
            chunks.append(np.asarray(params[lk][pn]).reshape(-1))
    if not chunks:
        return np.zeros((0,), np.float32)
    return np.concatenate(chunks)


def unflatten_params(flat: np.ndarray, template: Dict[str, Dict[str, jnp.ndarray]],
                     layer_keys: List[str], param_orders: Dict[str, List[str]]):
    """Inverse of `flatten_params`, shaped like `template`."""
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    pos = 0
    for lk in layer_keys:
        out[lk] = {}
        for pn in param_orders[lk]:
            ref = template[lk][pn]
            n = int(np.prod(ref.shape))
            out[lk][pn] = jnp.asarray(
                np.asarray(flat[pos : pos + n]).reshape(ref.shape), ref.dtype
            )
            pos += n
    if pos != flat.size:
        raise ValueError(f"Flat param length {flat.size} != expected {pos}")
    return out
