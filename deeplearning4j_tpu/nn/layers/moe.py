"""Mixture-of-experts FFN as a first-class DSL layer.

Makes `parallel/expert.py`'s GShard-style routed FFN reachable from the
config DSL: `MoELayer` in a `NeuralNetConfiguration` trains through the
engines with top-1/top-2 routing, capacity dropping, router jitter, and
the load-balance auxiliary loss folded into the network objective (the
engine collects the `_aux_loss` state entry each MoE layer emits and adds
it to the loss — `nn/multilayer.py._loss_from_preout`). Under an active
`ParallelContext` with an expert axis, the per-expert einsum batch is
sharding-constrained to that axis, so the SAME DSL model trains
expert-parallel with GSPMD-inserted all-to-alls (no reference equivalent;
the reference predates MoE — SURVEY.md §2.3 extension row).
"""

from __future__ import annotations

import jax

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.layers.common import layer_input_dropout
from deeplearning4j_tpu.parallel.context import current_context


def moe_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """x: [B, n_in] or [B, T, n_in] -> same leading shape with n_out.

    Emits `{"_aux_loss": w * aux}` in the returned state — the engines pop
    this reserved key into the training objective (never persisted)."""
    from deeplearning4j_tpu.parallel import expert as expert_mod

    drop_rng = jitter_rng = None
    if rng is not None:
        # Independent streams: dropout and router jitter must not consume
        # the same key (identical bits => correlated draws).
        drop_rng, jitter_rng = jax.random.split(rng)
    x = layer_input_dropout(conf, x, drop_rng, train)
    lead = x.shape[:-1]
    tokens = x.reshape(-1, x.shape[-1])
    ffn_params = {
        "gate_w": params["gate_w"],
        "w1": params["w1"], "b1": params["b_1"],
        "w2": params["w2"], "b2": params["b_2"],
    }
    ctx = current_context()
    mesh = expert_axis = None
    if ctx is not None and ctx.expert_axis is not None and ctx.axis_size("expert") > 1:
        mesh, expert_axis = ctx.mesh, ctx.expert_axis
    kwargs = dict(
        capacity_factor=conf.capacity_factor, top_k=conf.top_k,
        rng=jitter_rng if train else None, jitter_eps=conf.router_jitter,
        return_aux=True,
    )
    if mesh is not None:
        kwargs.update(mesh=mesh, expert_axis=expert_axis)
    y, aux = expert_mod.moe_ffn(ffn_params, tokens, **kwargs)
    out = activations.resolve(conf.activation)(y.reshape(lead + (conf.n_out,)))
    new_state = dict(state)
    new_state["_aux_loss"] = conf.aux_loss_weight * aux
    return out, new_state, mask
