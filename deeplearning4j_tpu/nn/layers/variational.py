"""Variational autoencoder implementation.

Equivalent of the reference's `nn/layers/variational/VariationalAutoencoder.java:48-79`
(1063 LoC): own encoder/decoder MLP stacks, pluggable reconstruction
distribution SPI (reference `nn/conf/layers/variational/
ReconstructionDistribution.java` with Gaussian / Bernoulli / Exponential /
Composite impls), reparameterization-trick sampling. Supervised forward =
encoder mean (the reference's activate()); the ELBO pretrain loss is
`vae_pretrain_loss`, driven by the layerwise pretrain loop.

A distribution spec is one of:
- a string: "gaussian" | "bernoulli" | "exponential";
- a loss wrapper ("loss", loss_function[, activation]) — any ILossFunction
  as the reconstruction "distribution" (reference:
  `nn/conf/layers/variational/LossFunctionWrapper.java` — negLogProbability
  delegates to the wrapped loss's per-example score; activation defaults
  to identity);
- for the composite (`CompositeReconstructionDistribution`), a list of
  (spec, data_size) pairs partitioning the feature axis (entries may
  themselves be loss wrappers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations


# --------------------------------------------------------------------------
# Reconstruction-distribution SPI


def _is_loss_wrapper(dist) -> bool:
    """("loss", loss_function[, activation]) spec — LossFunctionWrapper."""
    return (isinstance(dist, (list, tuple)) and len(dist) in (2, 3)
            and isinstance(dist[0], str) and dist[0] == "loss")


def dist_input_size(dist, data_size: int) -> int:
    """Decoder-output width for `data_size` features (reference:
    `ReconstructionDistribution.distributionInputSize`)."""
    if _is_loss_wrapper(dist):
        return data_size  # LossFunctionWrapper.distributionInputSize
    if isinstance(dist, (list, tuple)) and not isinstance(dist, str):
        if sum(size for _, size in dist) != data_size:
            raise ValueError(
                "composite reconstruction distribution sizes "
                f"{[s for _, s in dist]} must sum to the data size {data_size}")
        total = 0
        for name, size in dist:
            total += dist_input_size(name, size)
        return total
    if dist == "gaussian":
        return 2 * data_size   # [mean, log var] per feature
    if dist in ("bernoulli", "exponential"):
        return data_size
    raise ValueError(f"unknown reconstruction distribution {dist!r}")


def neg_log_prob(dist, x, pre):
    """Per-example negative log-probability [B] given decoder pre-output
    (reference: `exampleNegLogProbability` of each distribution impl)."""
    if _is_loss_wrapper(dist):
        # LossFunctionWrapper: the wrapped loss's per-example score stands
        # in for -log p(x|z) (`LossFunctionWrapper.exampleNegLogProbability`).
        from deeplearning4j_tpu.nn import losses as losses_mod

        activation = dist[2] if len(dist) > 2 else "identity"
        return losses_mod.compute_per_example(dist[1], x, pre, activation)
    if isinstance(dist, (list, tuple)) and not isinstance(dist, str):
        # Composite: slice x by data sizes and pre by distribution input
        # sizes, in order (reference `CompositeReconstructionDistribution
        # .java:143-160`).
        total = 0.0
        x_off = 0
        p_off = 0
        for name, size in dist:
            p_size = dist_input_size(name, size)
            total = total + neg_log_prob(
                name, x[:, x_off:x_off + size], pre[:, p_off:p_off + p_size])
            x_off += size
            p_off += p_size
        return total
    if dist == "bernoulli":
        p = jnp.clip(jax.nn.sigmoid(pre), 1e-7, 1 - 1e-7)
        return -jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log(1 - p), axis=-1)
    if dist == "gaussian":
        dmean, dlogv = jnp.split(pre, 2, axis=-1)
        return 0.5 * jnp.sum(
            dlogv + (x - dmean) ** 2 / jnp.exp(dlogv) + jnp.log(2 * jnp.pi),
            axis=-1)
    if dist == "exponential":
        # gamma = pre (identity activation); lambda = exp(gamma);
        # log p(x) = gamma - lambda * x (reference
        # `ExponentialReconstructionDistribution.java:61-68`).
        lam = jnp.exp(pre)
        return -jnp.sum(pre - lam * x, axis=-1)
    raise ValueError(f"unknown reconstruction distribution {dist!r}")


def _mlp(x, params, prefix, n_layers, act):
    for i in range(n_layers):
        x = act(x @ params[f"{prefix}W{i}"] + params[f"{prefix}b{i}"])
    return x


def vae_encode(conf, params, x):
    act = activations.resolve(conf.activation)
    h = _mlp(x, params, "e", len(conf.encoder_layer_sizes), act)
    pzx_act = activations.resolve(conf.pzx_activation)
    mean = pzx_act(h @ params["pZXMeanW"] + params["pZXMeanB"])
    log_var = h @ params["pZXLogStd2W"] + params["pZXLogStd2B"]
    return mean, log_var


def vae_decode(conf, params, z):
    act = activations.resolve(conf.activation)
    h = _mlp(z, params, "d", len(conf.decoder_layer_sizes), act)
    return h @ params["pXZW"] + params["pXZB"]


def vae_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    mean, _ = vae_encode(conf, params, x)
    return mean, state, mask


def vae_pretrain_loss(conf, params, x, rng):
    """Negative ELBO, averaged over the batch (reference: computeGradientAndScore
    of the VAE layer — reconstruction log-prob + KL(q(z|x) || N(0,I)))."""
    mean, log_var = vae_encode(conf, params, x)
    total = 0.0
    for s in range(conf.num_samples):
        eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
        z = mean + jnp.exp(0.5 * log_var) * eps
        dec = vae_decode(conf, params, z)
        total = total + neg_log_prob(conf.reconstruction_distribution, x, dec)
    recon = total / conf.num_samples
    kl = -0.5 * jnp.sum(1 + log_var - mean ** 2 - jnp.exp(log_var), axis=-1)
    return jnp.mean(recon + kl)


def vae_reconstruction_prob(conf, params, x, rng, num_samples=None):
    """Per-example reconstruction log-probability estimate (reference:
    `VariationalAutoencoder.reconstructionLogProbability`)."""
    ns = num_samples or conf.num_samples
    mean, log_var = vae_encode(conf, params, x)
    logps = []
    for s in range(ns):
        eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
        z = mean + jnp.exp(0.5 * log_var) * eps
        dec = vae_decode(conf, params, z)
        logps.append(-neg_log_prob(conf.reconstruction_distribution, x, dec))
    return jax.scipy.special.logsumexp(jnp.stack(logps), axis=0) - jnp.log(float(ns))
