"""Variational autoencoder implementation.

Equivalent of the reference's `nn/layers/variational/VariationalAutoencoder.java:48-79`
(1063 LoC): own encoder/decoder MLP stacks, pluggable reconstruction
distribution (gaussian | bernoulli), reparameterization-trick sampling.
Supervised forward = encoder mean (the reference's activate()); the ELBO
pretrain loss is `vae_pretrain_loss`, driven by the layerwise pretrain loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations


def _mlp(x, params, prefix, n_layers, act):
    for i in range(n_layers):
        x = act(x @ params[f"{prefix}W{i}"] + params[f"{prefix}b{i}"])
    return x


def vae_encode(conf, params, x):
    act = activations.resolve(conf.activation)
    h = _mlp(x, params, "e", len(conf.encoder_layer_sizes), act)
    pzx_act = activations.resolve(conf.pzx_activation)
    mean = pzx_act(h @ params["pZXMeanW"] + params["pZXMeanB"])
    log_var = h @ params["pZXLogStd2W"] + params["pZXLogStd2B"]
    return mean, log_var


def vae_decode(conf, params, z):
    act = activations.resolve(conf.activation)
    h = _mlp(z, params, "d", len(conf.decoder_layer_sizes), act)
    return h @ params["pXZW"] + params["pXZB"]


def vae_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    mean, _ = vae_encode(conf, params, x)
    return mean, state, mask


def vae_pretrain_loss(conf, params, x, rng):
    """Negative ELBO, averaged over the batch (reference: computeGradientAndScore
    of the VAE layer — reconstruction log-prob + KL(q(z|x) || N(0,I)))."""
    mean, log_var = vae_encode(conf, params, x)
    total = 0.0
    for s in range(conf.num_samples):
        eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
        z = mean + jnp.exp(0.5 * log_var) * eps
        dec = vae_decode(conf, params, z)
        if conf.reconstruction_distribution == "bernoulli":
            p = jax.nn.sigmoid(dec)
            recon = -jnp.sum(
                x * jnp.log(jnp.clip(p, 1e-7, 1.0))
                + (1 - x) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0)),
                axis=-1,
            )
        else:  # gaussian: decoder outputs [mean, log_var] per feature
            dmean, dlogv = jnp.split(dec, 2, axis=-1)
            recon = 0.5 * jnp.sum(
                dlogv + (x - dmean) ** 2 / jnp.exp(dlogv) + jnp.log(2 * jnp.pi), axis=-1
            )
        total = total + recon
    recon = total / conf.num_samples
    kl = -0.5 * jnp.sum(1 + log_var - mean ** 2 - jnp.exp(log_var), axis=-1)
    return jnp.mean(recon + kl)


def vae_reconstruction_prob(conf, params, x, rng, num_samples=None):
    """Per-example reconstruction log-probability estimate (reference:
    `VariationalAutoencoder.reconstructionLogProbability`)."""
    ns = num_samples or conf.num_samples
    mean, log_var = vae_encode(conf, params, x)
    logps = []
    for s in range(ns):
        eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
        z = mean + jnp.exp(0.5 * log_var) * eps
        dec = vae_decode(conf, params, z)
        if conf.reconstruction_distribution == "bernoulli":
            p = jnp.clip(jax.nn.sigmoid(dec), 1e-7, 1 - 1e-7)
            logp = jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log(1 - p), axis=-1)
        else:
            dmean, dlogv = jnp.split(dec, 2, axis=-1)
            logp = -0.5 * jnp.sum(
                dlogv + (x - dmean) ** 2 / jnp.exp(dlogv) + jnp.log(2 * jnp.pi), axis=-1
            )
        logps.append(logp)
    return jax.scipy.special.logsumexp(jnp.stack(logps), axis=0) - jnp.log(float(ns))
