"""Convolution and pooling layer implementations.

Equivalent of the reference's `nn/layers/convolution/` (ConvolutionLayer
im2col+gemm path + cuDNN helper, SubsamplingLayer). TPU-native: a single
`lax.conv_general_dilated` in NHWC/HWIO — XLA tiles it onto the MXU directly,
so the reference's im2col staging and the cuDNN helper SPI both disappear
(`ConvolutionLayer.java:265`, `ConvolutionHelper.java:32-38`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.conf.enums import ConvolutionMode, PoolingType
from deeplearning4j_tpu.nn.layers.common import (
    inverted_dropout,
    layer_input_dropout,
    maybe_drop_connect,
)

_DIMS = ("NHWC", "HWIO", "NHWC")


def _conv_padding(conf, h, w):
    mode = ConvolutionMode.of(conf.convolution_mode) or ConvolutionMode.TRUNCATE
    if mode == ConvolutionMode.SAME:
        return "SAME"
    ph, pw = conf.padding
    return [(ph, ph), (pw, pw)]


def conv2d_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    x = layer_input_dropout(conf, x, rng, train)
    # Reference applies DropConnect to conv kernels too
    # (`ConvolutionLayer.java:218-219`).
    out = jax.lax.conv_general_dilated(
        x,
        maybe_drop_connect(conf, params["W"], rng, train).astype(x.dtype),
        window_strides=conf.stride,
        padding=_conv_padding(conf, x.shape[1], x.shape[2]),
        rhs_dilation=conf.dilation,
        dimension_numbers=_DIMS,
    )
    if "b" in params:
        out = out + params["b"].astype(out.dtype)
    out = activations.resolve(conf.activation)(out)
    return out, state, mask


def subsampling_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    ptype = PoolingType.of(conf.pooling_type) or PoolingType.MAX
    kh, kw = conf.kernel_size
    sh, sw = conf.stride
    mode = ConvolutionMode.of(conf.convolution_mode) or ConvolutionMode.TRUNCATE
    if mode == ConvolutionMode.SAME:
        padding = "SAME"
    else:
        ph, pw = conf.padding
        padding = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
    window = (1, kh, kw, 1)
    strides = (1, sh, sw, 1)

    if ptype == PoolingType.MAX:
        out = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, window, strides, padding
        )
    elif ptype in (PoolingType.AVG, PoolingType.SUM):
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, padding)
        if ptype == PoolingType.AVG:
            out = out / (kh * kw)
    elif ptype == PoolingType.PNORM:
        p = float(conf.pnorm)
        out = jax.lax.reduce_window(
            jnp.abs(x) ** p, 0.0, jax.lax.add, window, strides, padding
        ) ** (1.0 / p)
    else:
        raise ValueError(f"Unsupported pooling type: {conf.pooling_type}")
    return out, state, mask


def lrn_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Cross-channel local response normalization (reference:
    `nn/layers/normalization/LocalResponseNormalization.java:66`):
    y = x / (k + alpha * sum_{window n} x_j^2)^beta, channels last."""
    n = int(conf.n)
    sq = x * x
    window_sum = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding=[(0, 0), (0, 0), (0, 0), (n // 2, (n - 1) // 2)],
    )
    return x / (conf.k + conf.alpha * window_sum) ** conf.beta, state, mask
