"""Global pooling layer implementation.

Equivalent of the reference's `nn/layers/pooling/GlobalPoolingLayer.java:41`:
pool over time ([b,t,f] -> [b,f], mask-aware) or space ([b,h,w,c] -> [b,c]),
types SUM/AVG/MAX/PNORM.
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import PoolingType


def global_pooling_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    ptype = PoolingType.of(conf.pooling_type) or PoolingType.MAX
    if x.ndim == 3:  # [b, t, f] over time
        axes = (1,)
        m = mask[..., None] if mask is not None else None
    elif x.ndim == 4:  # [b, h, w, c] over space
        axes = (1, 2)
        m = None
    else:
        raise ValueError(f"GlobalPooling expects 3-D or 4-D input, got {x.ndim}-D")

    if ptype == PoolingType.MAX:
        if m is not None:
            x = jnp.where(m > 0, x, -jnp.inf)
        out = jnp.max(x, axis=axes)
    elif ptype == PoolingType.SUM:
        if m is not None:
            x = x * m
        out = jnp.sum(x, axis=axes)
    elif ptype == PoolingType.AVG:
        if m is not None:
            out = jnp.sum(x * m, axis=axes) / jnp.maximum(jnp.sum(m, axis=axes), 1.0)
        else:
            out = jnp.mean(x, axis=axes)
    elif ptype == PoolingType.PNORM:
        p = float(conf.pnorm)
        if m is not None:
            x = x * m
        out = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
    else:
        raise ValueError(f"Unsupported global pooling type: {conf.pooling_type}")
    # Mask is consumed: output is per-example (reference collapseDimensions).
    return out, state, None
