"""Recurrent layer implementations: GravesLSTM, LSTM, bidirectional, SimpleRnn.

Equivalent of the reference's `nn/layers/recurrent/LSTMHelpers.java:58-160`
(activateHelper) — but as a `lax.scan` over time: the per-timestep Java loop
with gemm+axpy becomes one compiled scan whose body is a single fused
`[b, n_in + n_out] x [n_in + n_out, 4*n_out]` matmul on the MXU.

Semantics (reference parity):
- gate order i, f, o, g in the packed weight matrices;
- Graves peepholes: i and f see c_{t-1}, o sees c_t (`pW` = [p_i, p_f, p_o]);
- gate activation sigmoid (or hard-sigmoid), cell/output activation from conf
  (default tanh);
- masking: at masked steps state carries through and output is zeroed
  (variable-length sequences, reference `GravesLSTM.feedForwardMaskArray`);
- bidirectional output = forward + backward sum (reference
  `GravesBidirectionalLSTM` ADD mode).

Layout: x is [batch, time, features] (feature-last; reference is [b, f, t]).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.kernels import lstm_cell as _lstm_kernel
from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.layers.common import (
    inverted_dropout,
    layer_input_dropout,
    maybe_drop_connect,
)


def _lstm_scan(conf, params, x, mask, h0, c0, peephole: bool, reverse: bool = False,
               suffix: str = ""):
    """Run an LSTM over [b,t,f]; returns (outputs [b,t,n_out], (hT, cT))."""
    W = params["W" + suffix]
    RW = params["RW" + suffix]
    b = params["b" + suffix]
    n_out = conf.n_out
    gate_act = activations.resolve(conf.gate_activation)
    cell_act = activations.resolve(conf.activation)
    if peephole:
        pW = params["pW" + suffix]
        pw = (pW[:n_out], pW[n_out:2 * n_out], pW[2 * n_out:])
    else:
        pw = None

    # Precompute input projections for all timesteps in one big MXU matmul.
    xw = x @ W + b  # [b, t, 4*n_out]

    # Dispatch seam (kernels/lstm_cell.py): resolved ONCE per signature
    # before the scan body exists — the Pallas fused cell on TPU when the
    # registry picks it, else the bit-identical XLA body.
    cell = _lstm_kernel.resolve_cell(
        batch=x.shape[0], n_out=n_out, dtype=x.dtype, peephole=peephole,
        masked=mask is not None, gate_activation=conf.gate_activation,
        activation=conf.activation, gate_act=gate_act, cell_act=cell_act)

    def step(carry, inp):
        h_prev, c_prev = carry
        xw_t, m_t = inp
        h, c, out = cell(xw_t, h_prev, c_prev, RW, pw, m_t)
        return (h, c), out

    xs = jnp.swapaxes(xw, 0, 1)  # [t, b, 4n]
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else None
    (hT, cT), outs = jax.lax.scan(
        step, (h0, c0), (xs, ms), reverse=reverse
    )
    return jnp.swapaxes(outs, 0, 1), (hT, cT)


def _zeros_state(x, n_out):
    b = x.shape[0]
    return jnp.zeros((b, n_out), x.dtype), jnp.zeros((b, n_out), x.dtype)


def lstm_apply(conf, params, state, x, *, rng=None, train=False, mask=None,
               peephole=True):
    """GravesLSTM / LSTM forward. `state` (if non-None dict with h/c) seeds the
    initial hidden state — used by `rnn_time_step` stateful inference
    (reference: `MultiLayerNetwork.rnnTimeStep:2230`)."""
    x = layer_input_dropout(conf, x, rng, train)
    # DropConnect applies to the input weights only (LSTMHelpers.java:98-101).
    params = {**params, "W": maybe_drop_connect(conf, params["W"], rng, train)}
    if state and "h" in state:
        h0, c0 = state["h"], state["c"]
    else:
        h0, c0 = _zeros_state(x, conf.n_out)
    outs, (hT, cT) = _lstm_scan(conf, params, x, mask, h0, c0, peephole)
    return outs, {"h": hT, "c": cT}, mask


def graves_lstm_apply(conf, params, state, x, **kw):
    return lstm_apply(conf, params, state, x, peephole=True, **kw)


def standard_lstm_apply(conf, params, state, x, **kw):
    return lstm_apply(conf, params, state, x, peephole=False, **kw)


def bidirectional_lstm_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    x = layer_input_dropout(conf, x, rng, train)
    if rng is not None and getattr(conf, "use_drop_connect", False):
        r_f, r_b = jax.random.split(rng)
        params = {**params,
                  "W_f": maybe_drop_connect(conf, params["W_f"], r_f, train),
                  "W_b": maybe_drop_connect(conf, params["W_b"], r_b, train)}
    h0, c0 = _zeros_state(x, conf.n_out)
    fwd, _ = _lstm_scan(conf, params, x, mask, h0, c0, True, reverse=False, suffix="_f")
    bwd, _ = _lstm_scan(conf, params, x, mask, h0, c0, True, reverse=True, suffix="_b")
    return fwd + bwd, state, mask


def simple_rnn_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    x = layer_input_dropout(conf, x, rng, train)
    act = activations.resolve(conf.activation)
    if state and "h" in state:
        h0 = state["h"]
    else:
        h0 = jnp.zeros((x.shape[0], conf.n_out), x.dtype)
    xw = x @ maybe_drop_connect(conf, params["W"], rng, train) + params["b"]

    def step(h_prev, inp):
        xw_t, m_t = inp
        h = act(xw_t + h_prev @ params["RW"])
        if m_t is not None:
            m = m_t[:, None]
            h = m * h + (1.0 - m) * h_prev
        return h, h

    xs = jnp.swapaxes(xw, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else None
    hT, outs = jax.lax.scan(step, h0, (xs, ms))
    outs = jnp.swapaxes(outs, 0, 1)
    if mask is not None:
        outs = outs * mask[..., None]
    return outs, {"h": hT}, mask
