"""Shared helpers for layer implementations."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def inverted_dropout(x: jnp.ndarray, retain: Optional[float], rng, train: bool) -> jnp.ndarray:
    """Inverted dropout on input activations (reference: `util/Dropout.java`).

    `retain` is the probability of keeping a unit; 0/1/None disables. Scaling
    by 1/retain at train time keeps inference a no-op.
    """
    if not train or retain is None or retain <= 0.0 or retain >= 1.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, retain, x.shape)
    return jnp.where(keep, x / retain, 0.0)


def layer_input_dropout(conf, x: jnp.ndarray, rng, train: bool) -> jnp.ndarray:
    """Input-activation dropout, SKIPPED when the layer is in DropConnect
    mode (reference: `BaseLayer.applyDropOutIfNecessary:487` requires
    `!conf.isUseDropConnect()` — the two modes are mutually exclusive)."""
    if getattr(conf, "use_drop_connect", False):
        return x
    return inverted_dropout(x, conf.dropout, rng, train)


def maybe_drop_connect(conf, W: jnp.ndarray, rng, train: bool) -> jnp.ndarray:
    """DropConnect on an input-weight matrix: when `use_drop_connect` is
    set, the layer's dropout rate is applied to W (inverted scaling) at
    train time (reference: `Dropout.applyDropConnect` called from
    `BaseLayer.preOutput:371-373` and `LSTMHelpers.java:98-101` — input
    weights only, never recurrent weights)."""
    retain = conf.dropout
    if (not train or rng is None or not getattr(conf, "use_drop_connect", False)
            or retain is None or retain <= 0.0 or retain >= 1.0):
        return W
    keep = jax.random.bernoulli(rng, retain, W.shape)
    return jnp.where(keep, W / retain, 0.0)


def apply_mask(x: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Zero masked timesteps. x: [b, t, f], mask: [b, t]."""
    if mask is None:
        return x
    return x * mask[..., None]
