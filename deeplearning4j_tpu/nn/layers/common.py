"""Shared helpers for layer implementations."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def inverted_dropout(x: jnp.ndarray, retain: Optional[float], rng, train: bool) -> jnp.ndarray:
    """Inverted dropout on input activations (reference: `util/Dropout.java`).

    `retain` is the probability of keeping a unit; 0/1/None disables. Scaling
    by 1/retain at train time keeps inference a no-op.
    """
    if not train or retain is None or retain <= 0.0 or retain >= 1.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, retain, x.shape)
    return jnp.where(keep, x / retain, 0.0)


def apply_mask(x: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Zero masked timesteps. x: [b, t, f], mask: [b, t]."""
    if mask is None:
        return x
    return x * mask[..., None]
