"""Feed-forward layer implementations: Dense, Output, Embedding, Activation,
Dropout, AutoEncoder, RBM (supervised path), CenterLossOutput features.

Equivalent of the reference's `nn/layers/feedforward/` + `BaseLayer.java`
forward math. All functions are pure; backward is autodiff. Dense ops act on
the LAST axis and broadcast over leading axes, so the same code serves
[batch, f] and [batch, time, f] (the reference reshapes via Rnn<->FF
preprocessors instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.layers.common import inverted_dropout


def dense_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    x = inverted_dropout(x, conf.dropout, rng, train)
    out = x @ params["W"]
    if "b" in params:
        out = out + params["b"]
    out = activations.resolve(conf.activation)(out)
    return out, state, mask


def preoutput(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Linear pre-activation (used by output layers for stable fused losses)."""
    x = inverted_dropout(x, conf.dropout, rng, train)
    out = x @ params["W"]
    if "b" in params:
        out = out + params["b"]
    return out, state, mask


def embedding_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Embedding lookup (reference: `nn/layers/feedforward/embedding/EmbeddingLayer.java`).

    TPU-native: a gather instead of the reference's onehot-matmul. Accepts
    integer indices [b], [b,1], [b,t] or one-hot [..., n_in].
    """
    if jnp.issubdtype(x.dtype, jnp.floating) and x.shape[-1] == conf.n_in:
        idx = jnp.argmax(x, axis=-1)
    else:
        idx = x.astype(jnp.int32)
        if idx.ndim >= 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
    out = jnp.take(params["W"], idx, axis=0)
    if "b" in params:
        out = out + params["b"]
    out = activations.resolve(conf.activation)(out)
    return out, state, mask


def activation_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    return activations.resolve(conf.activation)(x), state, mask


def dropout_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    return inverted_dropout(x, conf.dropout, rng, train), state, mask


def autoencoder_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Supervised forward = encode (reference: `AutoEncoder.java` encode)."""
    return dense_apply(conf, params, state, x, rng=rng, train=train, mask=mask)


def autoencoder_reconstruct(conf, params, x, rng=None, corrupt=False):
    """Encode+decode with optional masking-noise corruption (pretrain path;
    reference: `AutoEncoder.java` getCorruptedInput/encode/decode)."""
    act = activations.resolve(conf.activation)
    if corrupt and rng is not None and conf.corruption_level > 0:
        keep = jax.random.bernoulli(rng, 1.0 - conf.corruption_level, x.shape)
        x = jnp.where(keep, x, 0.0)
    y = act(x @ params["W"] + params["b"])
    z = act(y @ params["W"].T + params["vb"])
    return z


def rbm_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Supervised forward = propUp (reference: `nn/layers/feedforward/rbm/RBM.java`)."""
    pre = x @ params["W"] + params["b"]
    if conf.hidden_unit == "gaussian":
        out = pre
    elif conf.hidden_unit == "rectified":
        out = jax.nn.relu(pre)
    elif conf.hidden_unit == "softmax":
        out = jax.nn.softmax(pre, axis=-1)
    else:
        out = jax.nn.sigmoid(pre)
    return out, state, mask
