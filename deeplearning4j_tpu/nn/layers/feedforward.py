"""Feed-forward layer implementations: Dense, Output, Embedding, Activation,
Dropout, AutoEncoder, RBM (supervised path), CenterLossOutput features.

Equivalent of the reference's `nn/layers/feedforward/` + `BaseLayer.java`
forward math. All functions are pure; backward is autodiff. Dense ops act on
the LAST axis and broadcast over leading axes, so the same code serves
[batch, f] and [batch, time, f] (the reference reshapes via Rnn<->FF
preprocessors instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.layers.common import (
    inverted_dropout,
    layer_input_dropout,
    maybe_drop_connect,
)


def dense_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    x = layer_input_dropout(conf, x, rng, train)
    out = x @ maybe_drop_connect(conf, params["W"], rng, train)
    if "b" in params:
        out = out + params["b"]
    out = activations.resolve(conf.activation)(out)
    return out, state, mask


def preoutput(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Linear pre-activation (used by output layers for stable fused losses)."""
    x = layer_input_dropout(conf, x, rng, train)
    out = x @ maybe_drop_connect(conf, params["W"], rng, train)
    if "b" in params:
        out = out + params["b"]
    return out, state, mask


def embedding_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Embedding lookup (reference: `nn/layers/feedforward/embedding/EmbeddingLayer.java`).

    TPU-native: a gather instead of the reference's onehot-matmul. Accepts
    integer indices [b], [b,1], [b,t] or one-hot [..., n_in].
    """
    fmt = getattr(conf, "input_format", "auto")
    onehot = (fmt == "onehot" if fmt != "auto"
              else jnp.issubdtype(x.dtype, jnp.floating)
              and x.shape[-1] == conf.n_in)
    if onehot:
        idx = jnp.argmax(x, axis=-1)
    else:
        idx = x.astype(jnp.int32)
        if idx.ndim >= 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
    out = jnp.take(params["W"], idx, axis=0)
    if "b" in params:
        out = out + params["b"]
    out = activations.resolve(conf.activation)(out)
    return out, state, mask


def activation_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    return activations.resolve(conf.activation)(x), state, mask


def dropout_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    return inverted_dropout(x, conf.dropout, rng, train), state, mask


def autoencoder_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Supervised forward = encode (reference: `AutoEncoder.java` encode)."""
    return dense_apply(conf, params, state, x, rng=rng, train=train, mask=mask)


def autoencoder_reconstruct(conf, params, x, rng=None, corrupt=False):
    """Encode+decode with optional masking-noise corruption (pretrain path;
    reference: `AutoEncoder.java` getCorruptedInput/encode/decode)."""
    act = activations.resolve(conf.activation)
    if corrupt and rng is not None and conf.corruption_level > 0:
        keep = jax.random.bernoulli(rng, 1.0 - conf.corruption_level, x.shape)
        x = jnp.where(keep, x, 0.0)
    y = act(x @ params["W"] + params["b"])
    z = act(y @ params["W"].T + params["vb"])
    return z


def autoencoder_pretrain_loss(conf, params, x, rng):
    """Denoising-AE reconstruction loss (reference: `AutoEncoder.computeGradientAndScore`
    via the configured reconstruction loss, default cross-entropy)."""
    from deeplearning4j_tpu.nn import losses as losses_mod

    z = autoencoder_reconstruct(conf, params, x, rng=rng, corrupt=True)
    # z is already post-activation; pass identity so score uses it directly.
    return losses_mod.score(conf.loss_function, x, z, "identity")


def _rbm_free_energy(conf, params, v):
    """Free energy F(v) = -v.vb - sum softplus(vW + b) (binary hidden units)."""
    wx_b = v @ params["W"] + params["b"]
    vbias = v @ params["vb"]
    return -vbias - jnp.sum(jax.nn.softplus(wx_b), axis=-1)


def rbm_pretrain_loss(conf, params, x, rng):
    """CD-k contrastive divergence as a differentiable surrogate (reference:
    `nn/layers/feedforward/rbm/RBM.java:101` contrastiveDivergence).

    Gibbs-sample v_k with k steps (stop-gradient), then
    loss = mean(F(v)) - mean(F(v_k)): autodiff of this is exactly the CD-k
    gradient — the functional TPU formulation of the reference's sampled
    positive/negative phase updates.
    """
    v = x

    def sample_h(v, key):
        p = jax.nn.sigmoid(v @ params["W"] + params["b"])
        if conf.hidden_unit == "binary":
            return jax.random.bernoulli(key, p).astype(v.dtype), p
        return p, p

    def sample_v(h, key):
        pre = h @ params["W"].T + params["vb"]
        if conf.visible_unit == "gaussian":
            return pre + jax.random.normal(key, pre.shape, pre.dtype), pre
        p = jax.nn.sigmoid(pre)
        if conf.visible_unit == "binary":
            return jax.random.bernoulli(key, p).astype(v.dtype), p
        return p, p

    vk = v
    for step in range(max(1, conf.k)):
        kh = jax.random.fold_in(rng, 2 * step)
        kv = jax.random.fold_in(rng, 2 * step + 1)
        h, _ = sample_h(vk, kh)
        vk, _ = sample_v(h, kv)
    vk = jax.lax.stop_gradient(vk)
    return jnp.mean(_rbm_free_energy(conf, params, v)) - jnp.mean(
        _rbm_free_energy(conf, params, vk))


def rbm_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    """Supervised forward = propUp (reference: `nn/layers/feedforward/rbm/RBM.java`)."""
    pre = x @ params["W"] + params["b"]
    if conf.hidden_unit == "gaussian":
        out = pre
    elif conf.hidden_unit == "rectified":
        out = jax.nn.relu(pre)
    elif conf.hidden_unit == "softmax":
        out = jax.nn.softmax(pre, axis=-1)
    else:
        out = jax.nn.sigmoid(pre)
    return out, state, mask


def positional_embedding_apply(conf, params, state, x, *, rng=None,
                               train=False, mask=None):
    """x: [B, T, F] -> x + P[pos:pos+T] (learned GPT-style position table,
    `nn/conf/layers.py::PositionalEmbeddingLayer`).

    With `conf.stateful`, a position cursor rides undeclared state: a
    fresh forward starts at 0 (== P[:T]); stateful decode via
    `rnn_time_step` resumes where the previous call stopped, so
    single-token steps get the RIGHT position rows. Stateless (default)
    always adds P[:T] — the cursor must be OPT-IN because tBPTT's
    carry_rnn path would otherwise advance it across chunks, silently
    changing existing models' training."""
    T = x.shape[1]
    if T > conf.max_length:
        raise ValueError(
            f"sequence length {T} exceeds PositionalEmbeddingLayer "
            f"max_length {conf.max_length}")
    if not getattr(conf, "stateful", False):
        return x + params["P"][:T], state, mask
    start = state.get("pos", jnp.int32(0))
    if jnp.ndim(start):
        # Per-slot cursors ([B] int32): gather each row's own position rows.
        idx = jnp.clip(start[:, None] + jnp.arange(T)[None, :],
                       0, conf.max_length - 1)
        rows = params["P"][idx]                  # [B, T, F]
    else:
        rows = jax.lax.dynamic_slice(
            params["P"], (start, jnp.int32(0)), (T, params["P"].shape[1]))
    return x + rows, {"pos": start + jnp.int32(T)}, mask
