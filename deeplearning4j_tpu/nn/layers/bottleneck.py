"""Fused ResNet bottleneck block layer.

Thin adapter over the `bottleneck_block` kernel seam
(`kernels/bottleneck_block.py`): the whole conv/BN/act/residual chain is
one dispatch — XLA fallback is the unfused vertex chain verbatim, the
Pallas path keeps the intermediates in VMEM. Batch statistics come back
as kernel outputs; the EMA update lives HERE (engine-side, the same
expression as `normalization.py::batchnorm_apply`) so training semantics
are identical to the unfused layers under either impl.
"""

from __future__ import annotations

from deeplearning4j_tpu.kernels import bottleneck_block as _kernel


def bottleneck_apply(conf, params, state, x, *, rng=None, train=False,
                     mask=None):
    out, stats = _kernel.bottleneck_forward(
        x, params, state,
        stride=conf.stride, project=conf.project, eps=conf.eps,
        activation=conf.activation,
        train=bool(train) and conf.is_minibatch)
    if stats is None:
        return out, state, mask
    decay = conf.decay
    new_state = {k: decay * state[k] + (1.0 - decay) * stats[k]
                 for k in stats}
    return out, new_state, mask
