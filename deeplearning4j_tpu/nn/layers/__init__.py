"""Layer implementation registry.

Maps a layer-config class name to its pure apply function — the TPU analog of
the reference's conf->impl instantiation (`conf/layers/*.instantiate()`);
there is no helper SPI because XLA lowers everything (SURVEY.md §7).

Uniform signature:
    apply(conf, params, state, x, *, rng, train, mask) -> (out, new_state, out_mask)
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.layers import (
    attention,
    bottleneck,
    convolution,
    feedforward,
    moe,
    normalization,
    pooling,
    recurrent,
    variational,
)

LAYER_IMPLS = {
    "DenseLayer": feedforward.dense_apply,
    "OutputLayer": feedforward.preoutput,  # loss fused at the network level
    "RnnOutputLayer": feedforward.preoutput,
    "CenterLossOutputLayer": feedforward.preoutput,
    "LossLayer": lambda conf, params, state, x, **kw: (x, state, kw.get("mask")),
    "ActivationLayer": feedforward.activation_apply,
    "DropoutLayer": feedforward.dropout_apply,
    "EmbeddingLayer": feedforward.embedding_apply,
    "AutoEncoder": feedforward.autoencoder_apply,
    "RBM": feedforward.rbm_apply,
    "ConvolutionLayer": convolution.conv2d_apply,
    "BottleneckBlock": bottleneck.bottleneck_apply,
    "SubsamplingLayer": convolution.subsampling_apply,
    "LocalResponseNormalization": convolution.lrn_apply,
    "BatchNormalization": normalization.batchnorm_apply,
    "LayerNormalization": normalization.layernorm_apply,
    "PositionalEmbeddingLayer": feedforward.positional_embedding_apply,
    "GravesLSTM": recurrent.graves_lstm_apply,
    "LSTM": recurrent.standard_lstm_apply,
    "GravesBidirectionalLSTM": recurrent.bidirectional_lstm_apply,
    "SimpleRnn": recurrent.simple_rnn_apply,
    "GlobalPoolingLayer": pooling.global_pooling_apply,
    "SelfAttentionLayer": attention.self_attention_apply,
    "MoELayer": moe.moe_apply,
    "VariationalAutoencoder": variational.vae_apply,
}

# Layers whose forward emits a *pre-activation* that the network turns into a
# loss (the reference's BaseOutputLayer family).
OUTPUT_LAYER_TYPES = {
    "OutputLayer", "RnnOutputLayer", "LossLayer", "CenterLossOutputLayer",
}

# Layerwise-pretrainable layers (reference: pretrain() RBM/AE/VAE path).
PRETRAIN_LOSSES = {
    "VariationalAutoencoder": variational.vae_pretrain_loss,
    "AutoEncoder": feedforward.autoencoder_pretrain_loss,
    "RBM": feedforward.rbm_pretrain_loss,
}


def get_impl(conf):
    name = type(conf).__name__
    impl = LAYER_IMPLS.get(name)
    if impl is None:
        raise ValueError(f"No implementation registered for layer type {name}")
    return impl
