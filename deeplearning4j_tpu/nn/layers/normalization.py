"""Batch normalization implementation.

Equivalent of the reference's `nn/layers/normalization/BatchNormalization.java:55`
(+ cuDNN helper path, subsumed by XLA fusion). Works for dense [b,f], sequence
[b,t,f], and NHWC [b,h,w,c] inputs — stats reduce over all axes but the last.

Running stats live in the layer *state* pytree (decay-EMA, reference decay 0.9,
eps 1e-5); train/inference selection is a static python flag, so each mode
compiles to its own fused XLA program (no in-graph branching).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations


def batchnorm_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    axes = tuple(range(x.ndim - 1))
    if train and conf.is_minibatch:
        # Single-pass stats: mean and mean-of-squares fuse into ONE read of x
        # (jnp.var would re-read the activation for (x-mean)^2 — the train
        # step is HBM-bandwidth bound on TPU, so each avoided pass counts).
        mean = jnp.mean(x, axis=axes)
        var = jnp.mean(x * x, axis=axes) - mean * mean
        decay = conf.decay
        new_state = {
            "mean": decay * state["mean"] + (1.0 - decay) * mean,
            "var": decay * state["var"] + (1.0 - decay) * var,
        }
    else:
        mean = state["mean"]
        var = state["var"]
        new_state = state
    xhat = (x - mean) / jnp.sqrt(var + conf.eps)
    if conf.lock_gamma_beta or not params:
        out = conf.gamma * xhat + conf.beta
    else:
        out = params["gamma"] * xhat + params["beta"]
    out = activations.resolve(conf.activation)(out)
    return out, new_state, mask


def layernorm_apply(conf, params, state, x, *, rng=None, train=False,
                    mask=None):
    """Layer norm over the trailing feature axis (no running state — the
    statistics are per-example, so train == inference; the transformer
    family's normalizer, `nn/conf/layers.py::LayerNormalization`)."""
    from deeplearning4j_tpu.nn.layers.common import layer_input_dropout

    x = layer_input_dropout(conf, x, rng, train)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
    out = (x - mu) * jax.lax.rsqrt(var + conf.eps)
    out = out * params["gamma"] + params["beta"]
    from deeplearning4j_tpu.nn import activations

    return activations.resolve(conf.activation)(out), state, mask
