"""Batch normalization implementation.

Equivalent of the reference's `nn/layers/normalization/BatchNormalization.java:55`
(+ cuDNN helper path, subsumed by XLA fusion). Works for dense [b,f], sequence
[b,t,f], and NHWC [b,h,w,c] inputs — stats reduce over all axes but the last.

Running stats live in the layer *state* pytree (decay-EMA, reference decay 0.9,
eps 1e-5); train/inference selection is a static python flag, so each mode
compiles to its own fused XLA program (no in-graph branching).
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.kernels import norm_act as _norm_kernel


def batchnorm_apply(conf, params, state, x, *, rng=None, train=False, mask=None):
    axes = tuple(range(x.ndim - 1))
    if train and conf.is_minibatch:
        # Single-pass stats: mean and mean-of-squares fuse into ONE read of x
        # (jnp.var would re-read the activation for (x-mean)^2 — the train
        # step is HBM-bandwidth bound on TPU, so each avoided pass counts).
        mean = jnp.mean(x, axis=axes)
        var = jnp.mean(x * x, axis=axes) - mean * mean
        decay = conf.decay
        new_state = {
            "mean": decay * state["mean"] + (1.0 - decay) * mean,
            "var": decay * state["var"] + (1.0 - decay) * var,
        }
    else:
        mean = state["mean"]
        var = state["var"]
        new_state = state
    # Normalize + affine + activation through the kernel dispatch seam
    # (kernels/norm_act.py): the XLA fallback is the literal pre-registry
    # expression; the Pallas path fuses the chain into one VMEM pass.
    if conf.lock_gamma_beta or not params:
        gamma, beta = conf.gamma, conf.beta
    else:
        gamma, beta = params["gamma"], params["beta"]
    out = _norm_kernel.batchnorm_norm_act(x, mean, var, gamma, beta,
                                          conf.eps, conf.activation)
    return out, new_state, mask


def layernorm_apply(conf, params, state, x, *, rng=None, train=False,
                    mask=None):
    """Layer norm over the trailing feature axis (no running state — the
    statistics are per-example, so train == inference; the transformer
    family's normalizer, `nn/conf/layers.py::LayerNormalization`)."""
    from deeplearning4j_tpu.nn.layers.common import layer_input_dropout

    x = layer_input_dropout(conf, x, rng, train)
    # Stats + normalize + affine + activation through the kernel dispatch
    # seam (kernels/norm_act.py; XLA fallback is the pre-registry code).
    out = _norm_kernel.layernorm_norm_act(x, params["gamma"], params["beta"],
                                          conf.eps, conf.activation)
    return out, state, mask
