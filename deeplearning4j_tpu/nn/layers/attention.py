"""Multi-head self-attention as a first-class DSL layer.

The reference framework predates attention (its long-sequence story is
tBPTT, `MultiLayerNetwork.java:1207`); SURVEY.md §5 names attention with
ring/Ulysses sequence parallelism as the TPU-native extension. Round 4
shipped the kernels as standalone functions (`parallel/sequence.py`,
`ops/flash_attention.py`); this module makes them reachable from the
framework's own config DSL: `SelfAttentionLayer` in a
`NeuralNetConfiguration` builds a model whose jitted train step computes
attention through

- the Pallas flash kernel (single device, no mask — `impl="auto"`),
- XLA dense attention with key masking (when a features mask is present),
- ring attention over the active mesh's sequence axis, selected at trace
  time from the installed `parallel.context.ParallelContext` — the same
  DSL model trains sequence-sharded under `ParallelWrapper(...,
  seq_axis=...)` with zero config changes.

The layer is an ordinary engine citizen: gradient-checked
(`tests/test_gradientcheck.py`), serialized to JSON/YAML, updater/L2
semantics identical to every other layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.layers.common import layer_input_dropout
from deeplearning4j_tpu.parallel.context import current_context

_NEG = -1e30


def _masked_dense_attention(q, k, v, mask, causal, scale):
    """Dense attention with key-position masking. q/k/v: [B, T, H, D];
    mask: [B, T] (1 = real, 0 = padded). Masked KEYS are excluded from
    every softmax; masked QUERY rows produce zeros (their downstream loss
    contribution is masked anyway, and zeros keep them finite)."""
    acc = jnp.promote_types(q.dtype, jnp.float32)
    qt, kt, vt = (jnp.swapaxes(a, 1, 2).astype(acc) for a in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    s = jnp.where(mask[:, None, None, :] > 0, s, _NEG)
    if causal:
        T = s.shape[-1]
        s = jnp.where(jnp.triu(jnp.ones((T, T), bool), 1)[None, None], _NEG, s)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(m <= _NEG / 2, 0.0, p)  # fully-masked rows -> all-zero p
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    o = jnp.einsum("bhqk,bhkd->bhqd", p / denom, vt)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


def _cached_decode_attention(q, kc, vc, pos, causal):
    """Decode-step attention against a fixed-size KV cache. q: [B, T, H, D]
    (the NEW positions, globally at [pos, pos+T)); kc/vc: [B, L, H, D] with
    valid keys in [0, pos+T). Causal: query i sees keys <= pos+i.

    `pos` is either a scalar cursor (every row at the same position — the
    single-sequence decode path) or a [B] vector of per-row cursors (the
    continuous-batching scheduler, where each slot is at its own depth)."""
    B, T, H, D = q.shape
    L = kc.shape[1]
    acc = jnp.promote_types(q.dtype, jnp.float32)
    qt = jnp.swapaxes(q, 1, 2).astype(acc) * (D ** -0.5)
    kt = jnp.swapaxes(kc, 1, 2).astype(acc)
    vt = jnp.swapaxes(vc, 1, 2).astype(acc)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt)
    kpos = jnp.arange(L)
    pos_b = jnp.reshape(pos, (-1, 1))            # [1,1] scalar / [B,1] vector
    if causal:
        limit = pos_b + 1 + jnp.arange(T)[None, :]  # query i sees < pos+i+1
    else:
        limit = jnp.broadcast_to(pos_b + T, (pos_b.shape[0], T))
    s = jnp.where(kpos[None, None, None, :] < limit[:, None, :, None],
                  s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


def self_attention_apply(conf, params, state, x, *, rng=None, train=False,
                         mask=None):
    """x: [B, T, n_in] -> [B, T, n_out] multi-head self-attention.

    Path selection (trace-time, static):
    1. KV cache present in `state` (stateful decode via rnn_time_step,
       `conf.decode_cache_length`) -> fixed-size cached attention;
    2. active ParallelContext with a >1 sequence axis -> ring attention
       (sequence-sharded exact attention; requires causal or no mask);
    3. features mask present -> XLA dense with key masking;
    4. otherwise -> `parallel.sequence.attention` (Pallas flash kernel for
       `impl="auto"`, dense oracle for `impl="dense"`).

    With `decode_cache_length` set the layer ALWAYS returns its cache
    entries (k_cache/v_cache/kv_pos) as undeclared state: the engines
    persist them only on the stateful-inference path and XLA eliminates
    the dead outputs everywhere else.
    """
    from deeplearning4j_tpu.parallel import sequence as seq_mod

    x = layer_input_dropout(conf, x, rng, train)
    B, T, _ = x.shape
    H = conf.n_heads
    if conf.n_out % H:
        raise ValueError(
            f"SelfAttentionLayer n_out ({conf.n_out}) must be divisible by "
            f"n_heads ({H})")
    Dh = conf.n_out // H

    def proj(w, b=None):
        h = x @ params[w]
        if b is not None:
            h = h + params[b]
        return h.reshape(B, T, H, Dh)

    q = proj("Wq", "qB")
    k = proj("Wk")  # key bias is a softmax no-op (see conf.param_shapes)
    v = proj("Wv", "vB")
    scale = Dh ** -0.5

    L = conf.decode_cache_length
    if L and "k_pages" in state:
        # Paged decode step: KV lives in a pool of fixed-size pages shared
        # by all slots (`models/kv_pool.py` owns the refcounts/CoW); this
        # branch scatters the new k/v rows through the per-slot page table
        # and reads through the `flash_attention_paged` kernel seam. The
        # pool guarantees every page in a slot's write range has refcount 1
        # (CoW before dispatch), so rows never collide; free slots' table
        # rows are all-zero, landing their writes on the reserved zero
        # page. Garbage rows (pad tails, zero page) sit at masked key
        # positions whose softmax weight underflows to exactly 0.0, which
        # keeps this path bit-identical to the dense cache under the XLA
        # dense-gather fallback.
        from deeplearning4j_tpu.kernels import flash_attention as _fa

        pos = state["kv_pos"]                       # [B] int32 cursors
        pt = state["page_table"]                    # [B, NP] int32
        kp, vp = state["k_pages"], state["v_pages"]
        page = kp.shape[1]
        gpos = pos[:, None] + jnp.arange(T)[None, :]           # [B, T]
        # Free slots' cursors grow unbounded; clip keeps the gather legal
        # and their writes stay on the zero page regardless.
        phys = jnp.take_along_axis(pt, jnp.clip(gpos // page, 0,
                                                pt.shape[1] - 1), axis=1)
        off = gpos % page
        kp = kp.at[phys.reshape(-1), off.reshape(-1)].set(
            k.reshape(B * T, H, Dh))
        vp = vp.at[phys.reshape(-1), off.reshape(-1)].set(
            v.reshape(B * T, H, Dh))
        ctx = current_context()
        if (ctx is not None and ctx.model_axis is not None
                and ctx.axis_size("model") > 1
                and H % ctx.axis_size("model") == 0):
            # Tensor-parallel decode (PERF.md §28): pin the page storage to
            # its head partitioning THROUGH the scatter, so XLA never
            # round-trips pages to a replicated layout between steps — q/k/v
            # arrive head-sharded from the column-parallel projections, the
            # scatter and the paged read stay shard-local, and the step's
            # only collective is Wo's row-parallel all-reduce.
            from deeplearning4j_tpu.parallel import mesh as _mesh_mod

            _pin = _mesh_mod.kv_page_sharding(ctx.mesh, 4, ctx.model_axis)
            kp = jax.lax.with_sharding_constraint(kp, _pin)
            vp = jax.lax.with_sharding_constraint(vp, _pin)
        o = _fa.paged_decode_attention(q, kp, vp, pt, pos, conf.causal)
        out = o.reshape(B, T, conf.n_out) @ params["Wo"] + params["oB"]
        out = activations.resolve(conf.activation)(out)
        return out, {"k_pages": kp, "v_pages": vp, "page_table": pt,
                     "kv_pos": pos + jnp.int32(T)}, mask

    if L and "kv_pos" in state:
        # Stateful decode step: fold the new k/v into the cache at the
        # cursor, attend against the valid prefix.
        pos = state["kv_pos"]
        zero = jnp.zeros((), jnp.int32)
        if jnp.ndim(pos):
            # Per-slot cursors ([B] int32): each row lands at its own depth.
            upd = jax.vmap(lambda c, u, p: jax.lax.dynamic_update_slice(
                c, u, (p, zero, zero)))
            kc = upd(state["k_cache"], k, pos)
            vc = upd(state["v_cache"], v, pos)
        else:
            kc = jax.lax.dynamic_update_slice(state["k_cache"], k,
                                              (zero, pos, zero, zero))
            vc = jax.lax.dynamic_update_slice(state["v_cache"], v,
                                              (zero, pos, zero, zero))
        o = _cached_decode_attention(q, kc, vc, pos, conf.causal)
        out = o.reshape(B, T, conf.n_out) @ params["Wo"] + params["oB"]
        out = activations.resolve(conf.activation)(out)
        return out, {"k_cache": kc, "v_cache": vc,
                     "kv_pos": pos + jnp.int32(T)}, mask

    ctx = current_context()
    if ctx is not None and ctx.seq_axis is not None and ctx.axis_size("seq") > 1:
        if mask is not None and not conf.causal:
            raise ValueError(
                "sequence-sharded non-causal attention with a features mask "
                "is not supported; pad to full length or drop the seq axis")
        # impl="ulysses" opts into the all-to-all variant (cheaper
        # collectives at moderate T; needs n_heads % axis == 0); anything
        # else sequence-sharded takes the ring.
        sp = (seq_mod.ulysses_attention
              if conf.attention_impl == "ulysses" else seq_mod.ring_attention)
        o = sp(q, k, v, ctx.mesh, seq_axis=ctx.seq_axis,
               batch_axis=ctx.data_axis, causal=conf.causal, scale=scale)
    elif mask is not None:
        o = _masked_dense_attention(q, k, v, mask, conf.causal, scale)
    else:
        o = seq_mod.attention(q, k, v, causal=conf.causal, scale=scale,
                              impl=conf.attention_impl)
    out = o.reshape(B, T, conf.n_out) @ params["Wo"] + params["oB"]
    out = activations.resolve(conf.activation)(out)
    new_state = state
    if L and T <= L:
        # Prime the decode cache (undeclared state: persists only via
        # rnn_time_step; dead code elsewhere). T > L skips priming — the
        # plain forward must keep working on sequences longer than the
        # cache; the engines' rnn_time_step guards capacity host-side.
        pad = [(0, 0), (0, L - T), (0, 0), (0, 0)]
        new_state = {
            "k_cache": jnp.pad(k, pad), "v_cache": jnp.pad(v, pad),
            "kv_pos": jnp.int32(T),
        }
    return out, new_state, mask
