"""Transfer learning: freeze-by-layer fine-tuning and the LoRA wiring.

Equivalent of the reference's `nn/transferlearning/TransferLearning.java`
builder + `FrozenLayer` wrapper — recast for pytree engines. A frozen
layer here is not a wrapper object but a *spec*: `frozen_spec` computes,
from the layer configs (`Layer.frozen` / `Layer.lora_rank`), the set of
param leaves excluded from training. Both engines consume the spec the
same way:

- updater-state init runs over the TRAINABLE subtree only, so frozen
  leaves get no Adam/RMSProp moments (a fully-frozen layer's opt entry
  is `()`) — the HBM cost of fine-tuning scales with the trainable
  params, not the model;
- `_train_step` differentiates the trainable subtree only (frozen leaves
  are closed over as constants inside the loss), so the backward never
  materializes their grads and XLA prunes the corresponding dead
  backward compute. This is also what makes LoRA-over-int8 possible:
  quantized base leaves are integers, which `jax.grad` refuses — frozen,
  they simply ride along as data.

Frozen stored leaves pass through the train step as the SAME arrays
(bitwise-unchanged, no copy). The spec is empty for ordinary nets, and
every split/merge below is the identity in that case — the pre-transfer
jit programs are byte-identical.

`TransferLearning(net)` is the user-facing builder: freeze a prefix or
named layers, attach LoRA adapters (`nn/lora.py`), and `build()` a new
engine sharing the base param arrays.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import lora as lora_mod

FrozenSpec = Dict[str, FrozenSet[str]]


def frozen_spec(layer_items, params_tree) -> FrozenSpec:
    """`{layer_key: frozenset(param names excluded from training)}` from
    the layer configs. Only layers with `frozen=True` or `lora_rank` set
    contribute — an unconfigured net yields `{}` and every consumer
    below degenerates to the identity.

    Within a contributing layer: all base leaves freeze (including
    biases, quantization `__scale` companions and the constant
    `__lora_scale`); the `__lora_a`/`__lora_b` factor pair stays
    trainable unless the layer is ALSO marked `frozen=True` (a fully
    frozen layer, adapters included)."""
    spec: FrozenSpec = {}
    for lk, conf in layer_items:
        lparams = (params_tree or {}).get(lk)
        if not isinstance(lparams, dict) or not lparams:
            continue
        layer_frozen = bool(getattr(conf, "frozen", None))
        has_lora = bool(getattr(conf, "lora_rank", None) or 0)
        if not layer_frozen and not has_lora:
            continue
        names = set()
        for name in lparams:
            if name.endswith((lora_mod.LORA_A, lora_mod.LORA_B)):
                if layer_frozen:
                    names.add(name)
            else:
                names.add(name)
        if names:
            spec[lk] = frozenset(names)
    return spec


def split_tree(tree, spec: FrozenSpec):
    """(trainable, frozen) halves of a params tree. Both keep EVERY layer
    key (empty dicts where a side has nothing), so jit signatures, the
    loss-scaling `tree_map(sel, ...)` selects, and `_apply_updates`' keyed
    iteration all see structure-stable trees. Arrays are never copied."""
    trainable: Dict[str, Any] = {}
    frozen: Dict[str, Any] = {}
    for lk, lparams in tree.items():
        names = spec.get(lk)
        if not names or not isinstance(lparams, dict):
            trainable[lk] = lparams
            frozen[lk] = {}
            continue
        trainable[lk] = {k: a for k, a in lparams.items() if k not in names}
        frozen[lk] = {k: a for k, a in lparams.items() if k in names}
    return trainable, frozen


def merge_tree(trainable, frozen):
    """Inverse of `split_tree`: the full tree, frozen leaves re-attached
    as the same array objects."""
    out: Dict[str, Any] = {}
    for lk, lparams in trainable.items():
        fro = (frozen or {}).get(lk) or {}
        if fro and isinstance(lparams, dict):
            merged = dict(lparams)
            merged.update(fro)
            out[lk] = merged
        else:
            out[lk] = lparams
    return out


def _layer_items(net) -> List[Tuple[str, Any]]:
    """(layer_key, layer conf) pairs for either engine, in canonical
    order (MLN: index order; graph: topological order of layer vertices)."""
    if hasattr(net, "layer_vertices"):
        order = [n for n in net.conf.topological_order()
                 if n in net.layer_vertices]
        return [(n, net.layer_vertices[n].layer) for n in order]
    return list(zip(net.layer_keys, net.layers))


class TransferLearning:
    """Builder for a fine-tuning copy of an initialized engine (reference:
    `TransferLearning.Builder` / `.GraphBuilder`).

    >>> tuned = (TransferLearning(base)
    ...          .freeze_up_to("layer_2")      # feature extractor
    ...          .add_lora(rank=8, alpha=16)   # adapters on eligible layers
    ...          .build())

    `build()` returns a NEW engine of the same class: its conf is a deep
    copy with `frozen` / `lora_rank` / `lora_alpha` stamped onto the
    layer configs (so checkpoints, clones and AOT fingerprints carry the
    transfer setup), its base params are COPIES of the source net's (the
    train step donates its param buffers — shared arrays would be
    invalidated under the source net), and fresh LoRA leaves are drawn
    where requested. The source net is never mutated."""

    def __init__(self, net):
        if getattr(net, "params_tree", None) is None:
            raise ValueError(
                "TransferLearning needs an initialized net (call init())")
        self._net = net
        self._items = _layer_items(net)
        self._keys = [k for k, _ in self._items]
        self._freeze: set = set()
        self._lora: Dict[str, Tuple[int, Optional[float]]] = {}

    def _resolve(self, ident) -> str:
        if isinstance(ident, int):
            if not 0 <= ident < len(self._keys):
                raise ValueError(
                    f"layer index {ident} out of range 0..{len(self._keys) - 1}")
            return self._keys[ident]
        key = str(ident)
        if key not in self._keys:
            raise ValueError(
                f"unknown layer {ident!r}; layers: {self._keys}")
        return key

    # ------------------------------------------------------------ freezing

    def freeze_up_to(self, ident) -> "TransferLearning":
        """Freeze every layer up to and including `ident` (the reference's
        `setFeatureExtractor`)."""
        key = self._resolve(ident)
        self._freeze.update(self._keys[: self._keys.index(key) + 1])
        return self

    def freeze(self, *idents) -> "TransferLearning":
        """Freeze specific layers by index or key/vertex name."""
        self._freeze.update(self._resolve(i) for i in idents)
        return self

    # ---------------------------------------------------------------- lora

    def add_lora(self, rank: int, alpha: Optional[float] = None,
                 layers=None) -> "TransferLearning":
        """Attach rank-`r` LoRA adapters (`nn/lora.py`). `layers=None`
        targets every eligible layer (one with 2-D weights); naming an
        ineligible layer explicitly raises. A LoRA layer's base params
        are implicitly frozen — only the adapter factors train."""
        rank = int(rank)
        if rank <= 0:
            raise ValueError(f"lora rank must be positive, got {rank}")
        if layers is None:
            chosen = [k for k, conf in self._items
                      if lora_mod.lora_target_names(conf)]
            if not chosen:
                raise ValueError("no LoRA-eligible layer (2-D weights) found")
        else:
            chosen = []
            for ident in layers:
                key = self._resolve(ident)
                conf = dict(self._items)[key]
                if not lora_mod.lora_target_names(conf):
                    raise ValueError(
                        f"layer {key!r} ({type(conf).__name__}) has no 2-D "
                        f"weight to adapt")
                chosen.append(key)
        for key in chosen:
            self._lora[key] = (rank, alpha)
        return self

    # --------------------------------------------------------------- build

    def _conf_items(self, conf) -> Dict[str, Any]:
        if hasattr(conf, "vertices"):
            out = {}
            for name in self._keys:
                out[name] = conf.vertices[name].layer
            return out
        return {self._keys[i]: conf.layers[i] for i in range(len(self._keys))}

    def build(self):
        conf = copy.deepcopy(self._net.conf)
        citems = self._conf_items(conf)
        for key in self._freeze:
            citems[key].frozen = True
        for key, (rank, alpha) in self._lora.items():
            citems[key].lora_rank = rank
            if alpha is not None:
                citems[key].lora_alpha = float(alpha)

        new_net = type(self._net)(conf)
        pol = new_net.dtype_policy
        pdt = jnp.float32 if pol.low_precision_params else pol.jnp_param
        rng = jax.random.PRNGKey(conf.global_conf.seed ^ 0x10A)
        # Copy every base leaf: the jitted train step donates its param
        # buffers, so arrays shared with the source net would be deleted
        # under it on the tuned net's first fit.
        params: Dict[str, Any] = jax.tree_util.tree_map(
            jnp.array, {lk: (dict(lp) if isinstance(lp, dict) else lp)
                        for lk, lp in self._net.params_tree.items()})
        for i, key in enumerate(self._keys):
            if key in self._lora:
                params.setdefault(key, {})
                params[key].update(lora_mod.init_lora_params(
                    citems[key], jax.random.fold_in(rng, i), dtype=pdt))
        new_net.init(params=params)
        # Carry non-trainable state (BN running stats, center-loss centers).
        for lk, s in (self._net.state or {}).items():
            if lk in new_net.state:
                new_net.state[lk] = dict(s)
        return new_net
