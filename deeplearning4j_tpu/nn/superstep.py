"""Superstep program-body helpers shared by both engines (PERF.md §13).

The fused K-iteration train program can iterate two ways — same math, same
RNG/clock threading, ONE device dispatch either way:

- `lax.scan` (the default): trace/compile time O(1) in K; the body lowers
  once, exactly like the per-batch program, so the result is bit-for-bit
  identical to K sequential per-batch steps on every backend.
- unrolled (`DL4J_TPU_SUPERSTEP_SCAN=0`): a CPU perf escape hatch. XLA:CPU
  cannot route convolutions inside a `while` loop (what scan lowers to)
  through its optimized Eigen kernels — a conv body inside scan runs ~13x
  slower than the same body at top level (measured: 132 ms vs 10 ms per
  iteration for LeNet's first conv, single-core CPU; TPU is unaffected).
  Unrolling restores the fast kernels at O(K) trace time — but XLA then
  optimizes ACROSS iterations (fusion/reassociation), so results are
  float-close, not bit-identical, to the per-batch loop. Hence opt-in.

The choice is a STATIC part of the program (it changes the lowered HLO), so
the engines pass it into the `_get_jit` cache key alongside `k` — and
alongside `kernel_config()`, the kernel-registry selection under which the
superstep body (LSTM cells, norm+act, the fused optimizer update carried
through `(params, state, opt_state, clock)`) traces its dispatch seams.
Resolution is hoisted to SIGNATURE level: a restacked block with an
already-seen `(k, scan, kernels, shapes)` identity is a jit-cache hit, so
`kernels.registry` never re-runs its `is_available` probes per block
(`registry.probe_count()` holds the line in tests/test_kernels.py).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def kernel_config():
    """The kernel-registry selection this superstep program traces under
    — passed by both engines as a `_get_jit` static so the fused-vs-
    fallback choice is explicit program identity (also folded in globally
    by `nn/jit_cache.py`; here it additionally lands in the AOT
    fingerprint's `static` list and the StepProfiler's program key)."""
    from deeplearning4j_tpu.kernels import registry

    return registry.config_key()


def use_scan() -> bool:
    """Loop shape for the superstep program: scan unless
    `DL4J_TPU_SUPERSTEP_SCAN=0` opts into the unrolled shape (CPU conv
    speed over bit-exactness — see module docstring)."""
    env = os.environ.get("DL4J_TPU_SUPERSTEP_SCAN")
    if env:
        return env not in ("0", "false", "False")
    return True


def superstep_loop(body, carry, xs, k: int, scan: bool):
    """Run `body` over the leading [K] axis of the `xs` pytree and return
    `(carry, losses)` with `losses` a `[K]` vector — `lax.scan` when `scan`,
    else a K-step unrolled loop with identical carry threading. `None`
    leaves in `xs` (absent masks) are empty pytrees in both shapes: scan
    passes them through untouched, and the unrolled indexer never sees
    them."""
    if scan:
        return jax.lax.scan(body, carry, xs)
    losses = []
    for i in range(k):
        inp = jax.tree_util.tree_map(lambda a: a[i], xs)
        carry, loss = body(carry, inp)
        losses.append(loss)
    return carry, jnp.stack(losses)
