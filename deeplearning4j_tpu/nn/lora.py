"""LoRA adapters as sibling low-rank param leaves (Hu et al., "LoRA").

A layer configured with `lora_rank=r` grows, for every 2-D weight `W` in
its `param_shapes()`, three sibling leaves in the SAME layer param dict:

    W__lora_a      [n_in, r]   gaussian-init (trainable)
    W__lora_b      [r, n_out]  zero-init (trainable; zero => delta starts 0)
    W__lora_scale  [] f32      alpha / r (constant, never trained)

The effective weight is resolved inside jit at the `prep_layer_params`
seam (`nn/params.py`): `W_eff = W + scale * (A @ B)`, computed at the
policy compute dtype so XLA fuses the rank-r delta into the consuming
matmul. Because the base weight is dequantized at the same seam, adapters
compose with int8 post-training-quantized bases (`q * qscale + AB`)
without ever materializing a dense f32 weight.

Storing adapters as sibling leaves (not a parallel module tree) means
checkpointing, sharding, flat-view and serving code see one ordinary
pytree; `extract_adapter` / `merge_adapter` convert between a full tree
and the tiny delta-only tree that `checkpoint/adapters.py` persists and
`serving/host.py` hot-swaps per request.

Freezing of the base weights (and the updater-state exclusion that makes
LoRA fine-tuning cheap) lives in `nn/transfer.py`.
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import Layer

LORA_A = "__lora_a"
LORA_B = "__lora_b"
LORA_SCALE = "__lora_scale"
_SUFFIXES = (LORA_A, LORA_B, LORA_SCALE)

# A-factor init stddev (Hu et al. init: A ~ N(0, sigma^2), B = 0, so the
# delta starts at exactly zero and the first forward equals the base).
_A_INIT_STD = 0.02


def is_lora_leaf(name: str) -> bool:
    return name.endswith(_SUFFIXES)


def base_name(name: str) -> str:
    """`W__lora_a` -> `W` (identity for non-adapter names)."""
    for suf in _SUFFIXES:
        if name.endswith(suf):
            return name[: -len(suf)]
    return name


def lora_target_names(conf: Layer) -> List[str]:
    """The layer weights that take adapters: its declared 2-D weight
    params (Dense/Output/Embedding W, attention Wq/Wk/Wv/Wo, LSTM W/RW,
    positional tables). Conv HWIO 4-D and MoE stacked 3-D tables are
    excluded — the low-rank factorization below is a plain matmul."""
    shapes = conf.param_shapes()
    return [k for k in conf.weight_param_keys() if len(shapes[k]) == 2]


def init_lora_params(conf: Layer, rng: jax.Array,
                     dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    """Fresh adapter leaves for one layer config (empty when `lora_rank`
    is unset). Scale is kept in its own f32 scalar leaf rather than baked
    into A so a checkpointed adapter records alpha/r explicitly."""
    r = int(getattr(conf, "lora_rank", None) or 0)
    if r <= 0:
        return {}
    alpha = float(getattr(conf, "lora_alpha", None) or r)
    shapes = conf.param_shapes()
    out: Dict[str, jnp.ndarray] = {}
    for i, name in enumerate(lora_target_names(conf)):
        n_in, n_out = shapes[name]
        key = jax.random.fold_in(rng, i)
        out[name + LORA_A] = (
            jax.random.normal(key, (n_in, r), dtype) * _A_INIT_STD)
        out[name + LORA_B] = jnp.zeros((r, n_out), dtype)
        out[name + LORA_SCALE] = jnp.asarray(alpha / r, jnp.float32)
    return out


def extract_adapter(params_tree: Dict[str, Dict[str, jnp.ndarray]]
                    ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """The delta-only subtree: every `__lora_*` leaf, keyed like the full
    tree. Layers without adapters are omitted (keeps checkpoints tiny)."""
    out: Dict[str, Dict[str, jnp.ndarray]] = {}
    for lk, lparams in params_tree.items():
        if not isinstance(lparams, dict):
            continue
        leaves = {k: a for k, a in lparams.items() if is_lora_leaf(k)}
        if leaves:
            out[lk] = leaves
    return out


def strip_adapter(params_tree: Dict[str, Dict[str, jnp.ndarray]]
                  ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """The base-only tree: same structure with every `__lora_*` leaf
    removed (all layer keys retained)."""
    return {
        lk: ({k: a for k, a in lparams.items() if not is_lora_leaf(k)}
             if isinstance(lparams, dict) else lparams)
        for lk, lparams in params_tree.items()
    }


def merge_adapter(base_tree: Dict[str, Dict[str, jnp.ndarray]],
                  adapter: Dict[str, Dict[str, jnp.ndarray]]
                  ) -> Dict[str, Dict[str, jnp.ndarray]]:
    """A full serving tree: the base tree (arrays shared, never copied)
    overlaid with one adapter's leaves. Passing `adapter=None` returns a
    plain shallow copy — the no-adapter serving path."""
    out = {
        lk: (dict(lparams) if isinstance(lparams, dict) else lparams)
        for lk, lparams in base_tree.items()
    }
    for lk, leaves in (adapter or {}).items():
        if lk not in out:
            raise KeyError(
                f"adapter layer {lk!r} not present in base tree "
                f"(layers: {sorted(base_tree)})")
        out[lk].update(leaves)
    return out


def adapter_nbytes(adapter: Dict[str, Dict[str, jnp.ndarray]]) -> int:
    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(adapter))


def adapter_rank(adapter: Dict[str, Dict[str, jnp.ndarray]]) -> int:
    """The (max) rank across an adapter's factor pairs — the `r` knob as
    recoverable from the leaves themselves."""
    r = 0
    for lparams in adapter.values():
        for k, a in lparams.items():
            if k.endswith(LORA_A) and getattr(a, "ndim", 0) == 2:
                r = max(r, int(a.shape[1]))
    return r
