"""Loss functions.

TPU-native equivalent of ND4J's `ILossFunction` impls (consumed by the reference's
output layers; inventory in SURVEY.md §2.4). Each loss takes the *pre-activation*
(`preout`) plus the output activation name, so that softmax+MCXENT and
sigmoid+XENT lower to numerically-stable fused log-softmax / logit forms — the
gradient comes from jax autodiff of the jitted score, not hand-written backprop.

Shape convention: features on the LAST axis. `[batch, features]` for dense,
`[batch, time, features]` for sequences (the reference uses NCW `[batch, nOut, time]`;
feature-last is the TPU-friendly layout — lane dimension = features).
Masks are `[batch]` or `[batch, time]`, 1.0 = keep.

Returns per-example (and per-timestep) losses with the feature axis reduced;
callers average over examples to produce the score (reference semantics:
loss / minibatch + L1/L2 terms, `MultiLayerNetwork.java:1838`).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.conf.enums import Activation, LossFunction

_EPS = 1e-7


def _act_name(activation) -> str:
    if isinstance(activation, Activation):
        return activation.value
    if isinstance(activation, str):
        return activation.lower()
    return ""


def compute_per_example(
    loss: Union[str, LossFunction],
    labels: jnp.ndarray,
    preout: jnp.ndarray,
    activation: Union[str, Activation, None] = Activation.IDENTITY,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Per-example loss, feature axis reduced. Mask (if given) zeroes masked steps."""
    key = loss.value if isinstance(loss, LossFunction) else str(loss).lower()
    act = _act_name(activation)

    if (jnp.issubdtype(jnp.asarray(labels).dtype, jnp.integer)
            and jnp.ndim(labels) == jnp.ndim(preout) - 1):
        # SPARSE class-id labels ([B] / [B, T] ints) — a TPU-native
        # extension beyond the reference's one-hot-only contract: at LM
        # vocabulary sizes the one-hot [B, T, V] tensor is the dominant
        # batch payload (B=8, T=1024, V=50k fp32 = 1.6 GB), while ids are
        # KBs. Cross-entropy only; other losses need dense targets.
        # Ids MUST be in [0, V): under jit the gather clamps out-of-range
        # ids silently (no data-dependent errors in XLA); `Evaluation`
        # range-checks loudly on host, so run eval on new data pipelines.
        if key not in (LossFunction.MCXENT.value,
                       LossFunction.NEGATIVELOGLIKELIHOOD.value):
            raise ValueError(
                f"integer class-id labels are only supported for "
                f"mcxent/negativeloglikelihood, not {key!r}")
        ids = jnp.asarray(labels, jnp.int32)[..., None]
        if act == Activation.SOFTMAX.value:
            # -log p[id] = logsumexp(z) - z[id]: gathers ONE logit per
            # position instead of materializing the full [.., V]
            # log-softmax intermediate.
            picked = jnp.take_along_axis(preout, ids, axis=-1)[..., 0]
            per = jax.scipy.special.logsumexp(preout, axis=-1) - picked
        else:
            out = activations.resolve(activation)(preout)
            logp = jnp.log(jnp.clip(out, _EPS, 1.0))
            per = -jnp.take_along_axis(logp, ids, axis=-1)[..., 0]
        if mask is not None:
            per = per * mask
        return per

    if key in (LossFunction.MCXENT.value, LossFunction.NEGATIVELOGLIKELIHOOD.value):
        if act == Activation.SOFTMAX.value:
            logp = jax.nn.log_softmax(preout, axis=-1)
        else:
            out = activations.resolve(activation)(preout)
            logp = jnp.log(jnp.clip(out, _EPS, 1.0))
        per = -jnp.sum(labels * logp, axis=-1)
    elif key == LossFunction.XENT.value:
        if act == Activation.SIGMOID.value:
            # stable binary cross-entropy from logits
            per = jnp.sum(
                jnp.maximum(preout, 0) - preout * labels + jnp.log1p(jnp.exp(-jnp.abs(preout))),
                axis=-1,
            )
        else:
            out = jnp.clip(activations.resolve(activation)(preout), _EPS, 1.0 - _EPS)
            per = -jnp.sum(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out), axis=-1)
    elif key == LossFunction.RECONSTRUCTION_CROSSENTROPY.value:
        out = jnp.clip(activations.resolve(activation)(preout), _EPS, 1.0 - _EPS)
        per = -jnp.sum(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out), axis=-1)
    elif key in (LossFunction.MSE.value, LossFunction.SQUARED_LOSS.value, LossFunction.L2.value):
        out = activations.resolve(activation)(preout)
        per = jnp.sum((out - labels) ** 2, axis=-1)
        if key == LossFunction.MSE.value:
            per = per / labels.shape[-1]
    elif key in (LossFunction.L1.value, LossFunction.MEAN_ABSOLUTE_ERROR.value):
        out = activations.resolve(activation)(preout)
        per = jnp.sum(jnp.abs(out - labels), axis=-1)
        if key == LossFunction.MEAN_ABSOLUTE_ERROR.value:
            per = per / labels.shape[-1]
    elif key == LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR.value:
        out = activations.resolve(activation)(preout)
        per = 100.0 * jnp.mean(jnp.abs((labels - out) / jnp.where(jnp.abs(labels) < _EPS, _EPS, labels)), axis=-1)
    elif key == LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR.value:
        out = activations.resolve(activation)(preout)
        per = jnp.mean((jnp.log1p(jnp.maximum(out, -1 + _EPS)) - jnp.log1p(jnp.maximum(labels, -1 + _EPS))) ** 2, axis=-1)
    elif key == LossFunction.COSINE_PROXIMITY.value:
        out = activations.resolve(activation)(preout)
        num = jnp.sum(labels * out, axis=-1)
        den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
        per = -num / jnp.maximum(den, _EPS)
    elif key == LossFunction.HINGE.value:
        out = activations.resolve(activation)(preout)
        per = jnp.sum(jnp.maximum(0.0, 1.0 - labels * out), axis=-1)
    elif key == LossFunction.SQUARED_HINGE.value:
        out = activations.resolve(activation)(preout)
        per = jnp.sum(jnp.maximum(0.0, 1.0 - labels * out) ** 2, axis=-1)
    elif key == LossFunction.KL_DIVERGENCE.value:
        out = jnp.clip(activations.resolve(activation)(preout), _EPS, 1.0)
        lab = jnp.clip(labels, _EPS, 1.0)
        per = jnp.sum(lab * (jnp.log(lab) - jnp.log(out)), axis=-1)
    elif key == LossFunction.POISSON.value:
        out = jnp.clip(activations.resolve(activation)(preout), _EPS, None)
        per = jnp.sum(out - labels * jnp.log(out), axis=-1)
    elif key == LossFunction.RMSE_XENT.value:
        out = jnp.clip(activations.resolve(activation)(preout), _EPS, 1.0 - _EPS)
        xent = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
        per = jnp.sqrt(jnp.sum(xent ** 2, axis=-1))
    else:
        raise ValueError(f"Unknown loss function: {loss!r}")

    if mask is not None:
        per = per * mask
    return per


def effective_batch_size(labels: jnp.ndarray, mask: Optional[jnp.ndarray] = None):
    """Rows of the batch that participate in the loss.

    Equals the minibatch size (the reference's divisor,
    `BaseOutputLayer.computeScore`) whenever every example has at least one
    unmasked entry — the only source of entirely-masked rows is this
    framework's data-parallel batch padding (`parallel/wrapper.py`), which
    must not dilute the score or the gradients of the real examples.
    """
    if mask is None:
        return float(labels.shape[0])
    m = mask != 0
    if m.ndim > 1:
        m = jnp.any(m, axis=tuple(range(1, m.ndim)))
    return jnp.maximum(jnp.sum(m.astype(jnp.float32)), 1.0)


def score(
    loss: Union[str, LossFunction],
    labels: jnp.ndarray,
    preout: jnp.ndarray,
    activation: Union[str, Activation, None] = Activation.IDENTITY,
    mask: Optional[jnp.ndarray] = None,
    average: bool = True,
) -> jnp.ndarray:
    """Scalar score: per-example losses reduced over the batch (and time).

    Reference semantics (`BaseOutputLayer.computeScore`,
    `/root/reference/deeplearning4j-nn/.../layers/BaseOutputLayer.java:98-101`):
    the per-entry losses (every timestep of a sequence, masked entries zeroed)
    are SUMMED and divided by the minibatch size only — never by time length
    or by the unmasked count. RNN losses therefore scale with sequence length,
    exactly as in the reference. (Rows whose mask is entirely zero — produced
    only by data-parallel batch padding — are excluded from the divisor, see
    `effective_batch_size`.)
    """
    per = compute_per_example(loss, labels, preout, activation, mask)
    total = jnp.sum(per)
    if not average:
        return total
    return total / effective_batch_size(labels, mask)
