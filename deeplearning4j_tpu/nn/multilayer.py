"""MultiLayerNetwork: the sequential network engine.

Equivalent of the reference's `nn/multilayer/MultiLayerNetwork.java` (2527 LoC)
— but where the reference is a mutable object graph dispatching per-op kernels,
this engine compiles the whole model into pure jitted programs:

- `init()` builds the params/state pytrees (the reference's flattened param
  view `:384-473` is available via `params()`/`set_params()` for checkpoint
  parity, but the pytree is the source of truth);
- `fit()` drives one jitted `train_step` per minibatch: forward + loss +
  autodiff backward + gradient normalization + updater + param update all fuse
  into a single XLA executable with donated buffers (the reference's
  Solver/StochasticGradientDescent/updater/stepFunction stack,
  `optimize/solvers/StochasticGradientDescent.java:51-72`, collapses into it);
- truncated BPTT (`doTruncatedBPTT:1138`) = chunked scan with state carried
  across chunks as data (gradient truncation falls out of step boundaries);
- `rnn_time_step` (`:2230`) = same forward with persistent hidden state.
"""

from __future__ import annotations

import copy
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import activations as activations_mod
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn import params as params_mod
from deeplearning4j_tpu.nn.conf.enums import (
    BackpropType,
    LossFunction,
    OptimizationAlgorithm,
)
from deeplearning4j_tpu.nn.conf.dtype_policy import resolve_policy
from deeplearning4j_tpu.nn.conf.layers import CenterLossOutputLayer, is_bias_param
from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf import preprocessors as preprocessors_mod
from deeplearning4j_tpu.nn.layers import OUTPUT_LAYER_TYPES, get_impl
from deeplearning4j_tpu.ops import grad_norm as grad_norm_mod
from deeplearning4j_tpu.ops import schedules as schedules_mod
from deeplearning4j_tpu.ops import updaters as updaters_mod
from deeplearning4j_tpu.nn import jit_cache as jit_cache_mod
from deeplearning4j_tpu.nn import superstep as _superstep
from deeplearning4j_tpu.nn import transfer as transfer_mod
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets import staging as _staging
from deeplearning4j_tpu.datasets.iterators import (
    Superbatch,
    SuperbatchIterator,
    maybe_reset,
    transfer_cast,
)
from deeplearning4j_tpu import observability as _obs

# Hot-loop series resolved once at import (observability/metrics.py rule 2).
_M_ITERS = _obs.metrics.counter(
    "dl4j_train_iterations_total", "Completed training iterations",
    label_names=("engine",)).labels(engine="mln")
_M_EPOCHS = _obs.metrics.counter(
    "dl4j_train_epochs_total", "Completed fit() epochs",
    label_names=("engine",)).labels(engine="mln")
_M_DISPATCH_FAMILY = _obs.metrics.histogram(
    "dl4j_step_dispatch_seconds",
    "Host time to dispatch one staged batch (async — completion is NOT "
    "awaited; see dl4j_step_latency_seconds from StepProfiler for settled "
    "latency); `k` = train iterations fused into the dispatch (superstep)",
    label_names=("engine", "k"))
_M_DISPATCH_K = {1: _M_DISPATCH_FAMILY.labels(engine="mln", k="1")}


def _dispatch_observe(k: int, seconds: float) -> None:
    child = _M_DISPATCH_K.get(k)
    if child is None:  # few distinct k values per process; cache children
        child = _M_DISPATCH_FAMILY.labels(engine="mln", k=str(k))
        _M_DISPATCH_K[k] = child
    child.observe(seconds)
_M_H2D = _obs.metrics.counter(
    "dl4j_host_to_device_bytes_total",
    "Host-resident bytes staged to device with training batches",
    label_names=("engine",)).labels(engine="mln")
_M_JIT_HIT = _obs.metrics.counter(
    "dl4j_jit_cache_hits_total", "Engine jit-program cache hits",
    label_names=("engine",)).labels(engine="mln")
_M_JIT_MISS = _obs.metrics.counter(
    "dl4j_jit_cache_misses_total",
    "Engine jit-program cache misses (a new program will trace+compile)",
    label_names=("engine",)).labels(engine="mln")
_M_INPUT_WAIT = _obs.metrics.histogram(
    "dl4j_input_wait_seconds",
    "Host seconds blocked in iterator-next waiting for the next batch "
    "(input starvation; the device is idle while this accrues)",
    label_names=("source",)).labels(source="mln")


_cast_floating = params_mod.cast_floating

# Keys in `opt_state` that are NOT layer entries: the f32 master param tree
# (low-precision param policies) and the (scale, good_count) loss-scale
# carry. `_apply_updates` iterates layer keys only, so these pass through
# untouched and re-attach after each update.
_RESERVED_OPT_KEYS = ("_master", "_ls")


def _as_dataset(data, labels=None) -> DataSet:
    if isinstance(data, DataSet):
        return data
    if labels is None and isinstance(data, tuple) and len(data) == 2:
        data, labels = data  # score((x, y)) / fit((x, y)) convenience form
    return DataSet(np.asarray(data), None if labels is None else np.asarray(labels))


class MultiLayerNetwork:
    """Sequential network engine (see module docstring)."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.layer_keys = [f"layer_{i}" for i in range(len(conf.layers))]
        self.params_tree: Optional[Dict[str, Dict[str, jnp.ndarray]]] = None
        self.state: Dict[str, Dict[str, jnp.ndarray]] = {}
        self.opt_state: Optional[Dict[str, Any]] = None
        self.iteration = 0
        self.epoch = 0
        self._score = float("nan")
        self.listeners: List[Any] = []
        self._rnn_state: Dict[str, Dict[str, jnp.ndarray]] = {}
        self._clock = None  # on-device (step, rng) carry; see _device_clock
        self._initialized = False
        self._collect_stats = False
        self.last_training_stats: Dict[str, Any] = {}
        # Precision policy (nn/conf/dtype_policy.py): explicit `dtype_policy`
        # wins, else the legacy `dtype` string maps onto the matching preset.
        self.dtype_policy = resolve_policy(conf.global_conf)
        self._compute_dtype = self.dtype_policy.jnp_compute
        self._loss_dtype = (
            jnp.float64
            if self.dtype_policy.resolved_param_dtype == "float64"
            else jnp.float32
        )
        self._output_dtype = self.dtype_policy.jnp_output
        self._jit_cache: Dict[Any, Any] = {}


    @property
    def score_value(self) -> float:
        """Loss of the most recent iteration. Reading this syncs with the
        device (the train loop itself never blocks — important over
        high-latency device transports)."""
        v = self._score
        return float(v) if v is not None else float("nan")

    @score_value.setter
    def score_value(self, v):
        self._score = v

    # ------------------------------------------------------------------ init

    def init(self, params: Optional[Dict[str, Dict[str, jnp.ndarray]]] = None) -> "MultiLayerNetwork":
        g = self.conf.global_conf
        pol = self.dtype_policy
        root = jax.random.PRNGKey(g.seed)
        # Low-precision param policies still INITIALIZE at f32 — the f32
        # draw is the master copy, params are its cast. State (BN running
        # stats) always stays at the master precision.
        pdt = jnp.float32 if pol.low_precision_params else pol.jnp_param
        keys = jax.random.split(root, max(len(self.layers), 1))
        master = None
        if params is None:
            params = {
                lk: params_mod.init_layer_params(layer, keys[i], dtype=pdt)
                for i, (lk, layer) in enumerate(zip(self.layer_keys, self.layers))
            }
            if pol.low_precision_params:
                master = params
                params = _cast_floating(params, pol.jnp_param)
        elif pol.low_precision_params:
            master = _cast_floating(params, jnp.float32)
        self.params_tree = params
        self.state = {
            lk: params_mod.init_layer_state(layer, dtype=pdt)
            for lk, layer in zip(self.layer_keys, self.layers)
            if layer.state_shapes()
        }
        self._updaters = [
            updaters_mod.create(
                layer.updater,
                momentum=layer.momentum if layer.momentum is not None else g.momentum,
                adam_mean_decay=layer.adam_mean_decay if layer.adam_mean_decay is not None else g.adam_mean_decay,
                adam_var_decay=layer.adam_var_decay if layer.adam_var_decay is not None else g.adam_var_decay,
                rho=layer.rho if layer.rho is not None else g.rho,
                rms_decay=layer.rms_decay if layer.rms_decay is not None else g.rms_decay,
                epsilon=layer.epsilon if layer.epsilon is not None else g.epsilon,
            )
            for layer in self.layers
        ]
        self._schedules = [
            schedules_mod.make_schedule(
                float(layer.learning_rate if layer.learning_rate is not None else g.learning_rate),
                g.lr_policy, g.lr_policy_decay_rate, g.lr_policy_power,
                g.lr_policy_steps, g.max_num_iterations, g.lr_schedule,
            )
            for layer in self.layers
        ]
        # Transfer learning / LoRA (nn/transfer.py): frozen leaves get NO
        # updater state — opt_state is built over the trainable subtree
        # (a fully-frozen layer's entry is ()). Empty spec (the common
        # case) keeps the structures byte-identical to before.
        self._frozen_spec = transfer_mod.frozen_spec(
            zip(self.layer_keys, self.layers), self.params_tree)
        base = master if master is not None else self.params_tree
        opt_src = (transfer_mod.split_tree(base, self._frozen_spec)[0]
                   if self._frozen_spec else base)
        self.opt_state = {
            lk: (() if lk in self._frozen_spec and not opt_src[lk]
                 else self._updaters[i].init(opt_src[lk]))
            for i, lk in enumerate(self.layer_keys)
        }
        # Reserved opt_state keys (never layer keys): the f32 master params
        # and the on-device loss-scale carry ride INSIDE opt_state so jit
        # signatures, donation, the superstep scan carry, and checkpoint
        # trees all pick them up without any shape change.
        if master is not None:
            self.opt_state["_master"] = master
        if pol.uses_loss_scaling:
            self.opt_state["_ls"] = (
                jnp.float32(pol.initial_loss_scale), jnp.float32(0.0))
        self._train_rng = jax.random.PRNGKey(g.seed ^ 0x5EED)
        self._clock = None
        self._initialized = True
        return self

    @property
    def _uint8_policy(self) -> str:
        """How a uint8 network input is staged, from the first layer's
        declared structure (see `nn/conf/preprocessors.py`): embedding ids
        are cast, image bytes are /255-scaled."""
        return preprocessors_mod.resolve_uint8_policy(
            [self.layers[0]] if self.layers else [])

    # ------------------------------------------------------------- clock
    # The (step, rng) pair lives ON DEVICE and is advanced inside the jitted
    # train step. Converting a host scalar per iteration costs milliseconds
    # over a high-latency device transport (measured ~7ms for a np scalar on
    # a tunneled TPU), so the hot loop never transfers: one async dispatch
    # per step, all-device arguments.

    def _device_clock(self):
        if self._clock is None:
            self._clock = (
                jax.device_put(np.float32(self.iteration)),
                self._train_rng,
            )
        return self._clock

    # --------------------------------------------------------------- forward

    def _forward_fn(self, params, state, x, rng, train: bool, fmask,
                    upto: Optional[int] = None, collect: bool = False,
                    keep_rnn_state: bool = False):
        """Pure forward pass (traced). Returns (final, new_state, activations, aux)."""
        cdt = self._compute_dtype
        # Device-side ImagePreProcessingScaler (reference:
        # `ImagePreProcessingScaler.java` scales 0-255 -> 0-1 on HOST):
        # shipping bytes and scaling on device quarters the host->device
        # traffic of streamed image batches (PERF.md §3). The uint8
        # interpretation (image bytes vs embedding ids) is decided by the
        # first layer's declared structure, not sniffed from the dtype.
        x = preprocessors_mod.apply_uint8_policy(
            jnp.asarray(x), self._uint8_policy, cdt)
        mask = fmask
        new_state: Dict[str, Any] = {}
        acts: List[jnp.ndarray] = []
        aux: Dict[str, Any] = {}
        n = len(self.layers) if upto is None else upto
        for i in range(n):
            layer = self.layers[i]
            lk = self.layer_keys[i]
            if i in self.conf.input_preprocessors:
                x, mask = self.conf.input_preprocessors[i](x, mask)
            if isinstance(layer, CenterLossOutputLayer):
                aux["center_loss_input"] = x
                aux["centers"] = state.get(lk, {}).get("centers")
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            # Params stored at param_dtype, cast (or dequantized) to the
            # policy's compute dtype at use (nn/params.py).
            lparams = params_mod.prep_layer_params(params.get(lk, {}), cdt,
                                                   layer=layer)
            lstate = state.get(lk, {})
            x, lstate_new, mask = get_impl(layer)(
                layer, lparams, lstate, x, rng=lrng, train=train, mask=mask
            )
            if lstate_new and "_aux_loss" in lstate_new:
                # Reserved key: auxiliary loss terms (MoE load balance) are
                # collected into the objective, never persisted as state.
                lstate_new = dict(lstate_new)
                aux["aux_loss"] = aux.get("aux_loss", 0.0) + lstate_new.pop(
                    "_aux_loss")
            if lstate_new:
                # Only persist what the layer declares (BN stats) unless the
                # caller wants rnn hidden state carried (tbptt / rnn_time_step).
                declared = set(layer.state_shapes())
                keep = {k: v for k, v in lstate_new.items()
                        if k in declared or keep_rnn_state}
                if keep:
                    new_state[lk] = keep
            if collect:
                acts.append(x)
        return x, new_state, acts, aux

    def _output_activation(self, preout):
        layer = self.layers[-1]
        if type(layer).__name__ in OUTPUT_LAYER_TYPES:
            return activations_mod.resolve(layer.activation)(preout)
        return preout

    def _get_jit(self, kind: str, **static):
        # Key construction/lookup + compile-cache store hook shared with
        # ComputationGraph (see nn/jit_cache.py).
        return jit_cache_mod.get_jit(self, _M_JIT_HIT, _M_JIT_MISS,
                                     kind, **static)

    def warmup(self, data=None, kinds=None, background: bool = False,
               batch_size: int = 32):
        """Pre-compile (or AOT-load) the jit programs for an example
        batch's signature without running them — params/optimizer/RNG are
        untouched. See `compilation.warmup.warmup_net` for the `data` /
        `kinds` / `background` contract."""
        from deeplearning4j_tpu.compilation import warmup as warmup_mod

        return warmup_mod.warmup_net(self, data, kinds=kinds,
                                     background=background,
                                     batch_size=batch_size)

    def _build_jit(self, kind: str, train=False, keep_rnn_state=False,
                   advance=False, collect=False, algo=None, k=None,
                   scan=True, kernels=None):
        # `k`/`scan` select the superstep program shape (`nn/superstep.py`)
        # and are part of the `_get_jit` cache key: each distinct block
        # length registers as its own cached program, so StepProfiler's
        # jit-cache-growth heuristic classifies a tail block's first call as
        # compile, not steady-state execute. `kernels` is pure program
        # identity (the kernel-registry selection the trace resolves under,
        # `nn/superstep.py::kernel_config`) — never read here.
        if kind == "solver_step":
            from jax.flatten_util import ravel_pytree

            from deeplearning4j_tpu.optimize import solvers as solvers_mod

            g = self.conf.global_conf
            iterations = max(1, g.iterations)
            mls = max(1, int(g.max_num_line_search_iterations))

            def solver_fn(params, state, x, y, fmask, lmask):
                w0, unravel = ravel_pytree(params)

                def loss_flat(w):
                    p = unravel(w)
                    preout, _, _, aux = self._forward_fn(
                        p, state, x, None, False, fmask)
                    return self._loss_from_preout(p, preout, y, lmask, aux)[0]

                w, loss = solvers_mod.minimize(
                    algo, loss_flat, w0, iterations=iterations,
                    max_line_search=mls)
                return unravel(w), loss

            return jax.jit(solver_fn, donate_argnums=(0,))
        if kind == "output":
            def output_fn(params, state, x, fmask, rng):
                final, new_state, _, _ = self._forward_fn(
                    params, state, x, rng, train, fmask, keep_rnn_state=keep_rnn_state
                )
                out = self._output_activation(final.astype(self._output_dtype))
                return out, new_state
            return jax.jit(output_fn)
        if kind == "score":
            def score_fn(params, state, x, y, fmask, lmask):
                preout, _, _, aux = self._forward_fn(params, state, x, None, False, fmask)
                return self._loss_from_preout(params, preout, y, lmask, aux)[0]
            return jax.jit(score_fn)
        if kind == "train_step":
            def step_plain(params, state, opt_state, x, y, fmask, lmask, clock):
                step, key = clock
                key, sub = jax.random.split(key)
                out = self._train_step(params, state, opt_state, x, y, fmask,
                                       lmask, step, sub, carry_rnn=False)
                return out + ((step + 1.0, key),)
            return jax.jit(step_plain, donate_argnums=(0, 2))
        if kind == "train_superstep":
            # K full train iterations as ONE dispatch: a fused loop (`lax.scan` by
            # default, opt-in unrolled — `nn/superstep.py`) over the
            # leading [K] axis of a stacked superbatch, carrying
            # (params, state, opt_state, clock) with donated buffers and
            # returning the K per-step losses as a vector (PERF.md §13).
            # The body advances the clock exactly like `step_plain`
            # (`key, sub = split(key)` then `step + 1.0`), so the RNG split
            # chain — and therefore dropout masks, BN batch-stat order, and
            # updater step counts — is bit-for-bit identical to K
            # sequential `_fit_one` calls.
            def step_super(params, state, opt_state, xs, ys, fmasks, lmasks,
                           clock):
                def body(carry, inp):
                    params, state, opt_state, (step, key) = carry
                    x, y, fm, lm = inp
                    key, sub = jax.random.split(key)
                    params, state, opt_state, loss = self._train_step(
                        params, state, opt_state, x, y, fm, lm, step, sub,
                        carry_rnn=False)
                    return (params, state, opt_state, (step + 1.0, key)), loss

                (params, state, opt_state, clock), losses = _superstep.superstep_loop(
                    body, (params, state, opt_state, clock),
                    (xs, ys, fmasks, lmasks), k, scan)
                return params, state, opt_state, losses, clock
            return jax.jit(step_super, donate_argnums=(0, 2))
        if kind == "train_step_stats":
            def step_stats(params, state, opt_state, x, y, fmask, lmask, clock):
                step, key = clock
                key, sub = jax.random.split(key)
                out = self._train_step(params, state, opt_state, x, y, fmask,
                                       lmask, step, sub, carry_rnn=False,
                                       collect_stats=True)
                return out + ((step + 1.0, key),)
            return jax.jit(step_stats, donate_argnums=(0, 2))
        if kind == "train_step_tbptt":
            # `advance` is static: all chunks of one sequence share the same
            # step value (reference: one optimize iteration per sequence);
            # only the final chunk ticks the clock. `collect` adds the
            # StatsListener scalars (grad/update/param mean magnitudes).
            def step_tbptt(params, state, opt_state, x, y, fmask, lmask, clock, eb):
                step, key = clock
                key, sub = jax.random.split(key)
                out = self._train_step(params, state, opt_state, x, y, fmask,
                                       lmask, step, sub, carry_rnn=True, eb=eb,
                                       collect_stats=collect)
                new_step = step + 1.0 if advance else step
                return out + ((new_step, key),)
            return jax.jit(step_tbptt, donate_argnums=(0, 2))
        if kind == "train_step_tbptt_scan":
            # The WHOLE tBPTT pass as ONE jitted program: chunk 0 unrolled
            # (it CREATES the rnn-carry entries in `state`, so the carry
            # structure is only scan-stable from chunk 1 on), the full-length
            # middle chunks as a `lax.scan`, and any short remainder chunk
            # unrolled at its TRUE length — no padding, so BatchNorm batch
            # stats and masked losses see exactly the data the per-chunk
            # host loop saw. The host loop it replaces pays one dispatch
            # round-trip per chunk, which over a high-latency transport
            # dominates the compute (measured ~13 ms per extra dispatch on
            # the tunneled v5e vs 5.6 ms for the entire 100-step scan —
            # PERF.md §4). Note each distinct sequence length t compiles its
            # own program (the old loop reused [B, fwd] chunk programs
            # across t); bucket/pad sequence lengths host-side if feeding
            # many distinct lengths.
            fwd = int(self.conf.tbptt_fwd_length)

            def chunked(a, n):
                if a is None:
                    return None
                # [B, n*fwd, ...] -> [n, B, fwd, ...] (scan axis leading)
                b = a.shape[0]
                a = a.reshape((b, n, fwd) + a.shape[2:])
                return jnp.moveaxis(a, 1, 0)

            def at(a, i):
                return None if a is None else a[i]

            def tslice(a, sl):
                return None if a is None else a[:, sl]

            def step_scan(params, state, opt_state, x, y, fmask, lmask,
                          clock, eb):
                step, key = clock
                t = x.shape[1]
                n_full = t // fwd  # >= 1: _fit_dispatch requires t > fwd
                rem = t - n_full * fwd
                # Same RNG chain as the per-chunk stats path (`step_tbptt`
                # does `key, sub = split(key)` per chunk), so attaching a
                # StatsListener never changes training numerics.
                subs = []
                for _ in range(n_full + (1 if rem else 0)):
                    key, sub = jax.random.split(key)
                    subs.append(sub)

                full = slice(0, n_full * fwd)
                xs, ys = chunked(tslice(x, full), n_full), chunked(tslice(y, full), n_full)
                fs, ls = (chunked(tslice(fmask, full), n_full),
                          chunked(tslice(lmask, full), n_full))

                params, state, opt_state, loss = self._train_step(
                    params, state, opt_state, xs[0], ys[0], at(fs, 0),
                    at(ls, 0), step, subs[0], carry_rnn=True, eb=eb)

                if n_full > 1:
                    def body(carry, inp):
                        params, state, opt_state = carry
                        cx, cy, cf, cl, sub = inp
                        params, state, opt_state, closs = self._train_step(
                            params, state, opt_state, cx, cy, cf, cl, step,
                            sub, carry_rnn=True, eb=eb)
                        return (params, state, opt_state), closs

                    (params, state, opt_state), losses = jax.lax.scan(
                        body, (params, state, opt_state),
                        (at(xs, slice(1, None)), at(ys, slice(1, None)),
                         at(fs, slice(1, None)), at(ls, slice(1, None)),
                         jnp.stack(subs[1:n_full])))
                    loss = losses[-1]
                if rem:
                    tail = slice(n_full * fwd, t)
                    params, state, opt_state, loss = self._train_step(
                        params, state, opt_state, tslice(x, tail),
                        tslice(y, tail), tslice(fmask, tail),
                        tslice(lmask, tail), step, subs[-1],
                        carry_rnn=True, eb=eb)
                return (params, state, opt_state, loss,
                        (step + 1.0, key))
            return jax.jit(step_scan, donate_argnums=(0, 2))
        if kind == "feedforward":
            def ff_fn(params, state, x, fmask, rng):
                _, new_state, acts, _ = self._forward_fn(
                    params, state, x, rng, train, fmask, collect=True
                )
                return acts, new_state
            return jax.jit(ff_fn)
        raise ValueError(kind)

    # ----------------------------------------------------------------- loss

    def _l1_l2_penalty(self, params):
        """L1/L2 terms added at score time (reference: `Layer.calcL1/calcL2`,
        score semantics SURVEY.md §2.4). Applied to weight params only."""
        total = 0.0
        for lk, layer in zip(self.layer_keys, self.layers):
            l1 = float(layer.l1 or 0.0)
            l2 = float(layer.l2 or 0.0)
            if (l1 == 0.0 and l2 == 0.0) or lk not in params:
                continue
            for wk in layer.weight_param_keys():
                if wk not in params[lk]:
                    continue
                w = params[lk][wk].astype(self._loss_dtype)
                if l2:
                    total = total + 0.5 * l2 * jnp.sum(w * w)
                if l1:
                    total = total + l1 * jnp.sum(jnp.abs(w))
        return total

    def _loss_from_preout(self, params, preout, y, lmask, aux, eb=None):
        layer = self.layers[-1]
        name = type(layer).__name__
        if name not in OUTPUT_LAYER_TYPES:
            raise ValueError(
                f"Last layer ({name}) is not an output layer; cannot compute loss"
            )
        preout = preout.astype(self._loss_dtype)
        # `eb` overrides the divisor for tBPTT chunks: a row fully masked
        # within ONE chunk of a variable-length batch still counts toward the
        # reference's divide-by-minibatch (computed from the full-sequence
        # mask in `_fit_tbptt`), while data-parallel padding rows never do.
        if eb is None:
            eb = losses_mod.effective_batch_size(y, lmask)
        data_loss = losses_mod.score(
            layer.loss_function, y, preout, layer.activation, lmask,
            average=False,
        ) / eb
        extra_state = {}
        if isinstance(layer, CenterLossOutputLayer):
            feats = aux["center_loss_input"].astype(self._loss_dtype)
            centers = aux["centers"]
            cls = (jnp.asarray(y, jnp.int32)
                   if jnp.issubdtype(jnp.asarray(y).dtype, jnp.integer)
                   else jnp.argmax(y, axis=-1))
            c = centers[cls]
            # Row weights: the labels mask excludes data-parallel padding rows
            # from both the center-loss term and the center updates.
            w = jnp.ones(y.shape[0], self._loss_dtype) if lmask is None else (
                lmask.reshape(y.shape[0], -1)[:, 0].astype(self._loss_dtype))
            data_loss = data_loss + 0.5 * layer.lambda_ * jnp.sum(
                w * jnp.sum((feats - c) ** 2, axis=-1)
            ) / eb
            # EMA center update (reference: CenterLossOutputLayer center updates)
            diff = (c - feats) * w[:, None]
            num = jax.ops.segment_sum(diff, cls, num_segments=layer.n_out)
            cnt = jax.ops.segment_sum(w.astype(jnp.float32), cls,
                                      num_segments=layer.n_out)
            new_centers = centers - layer.alpha * num / (1.0 + cnt)[:, None]
            extra_state = {self.layer_keys[-1]: {"centers": new_centers}}
        if "aux_loss" in aux:
            # Layer-emitted auxiliary objectives (MoE load balance), already
            # scaled by their layer's weight; batch-size-invariant means, so
            # not divided by eb.
            data_loss = data_loss + aux["aux_loss"]
        # Reference: `score += fullNetworkL1 + fullNetworkL2; score /= miniBatch`
        # (BaseOutputLayer.java:100-101) and the matching gradient
        # `(g + l2*w)/miniBatch` (LayerUpdater.postApply:104-108) — so the
        # penalty is divided by the batch size inside the differentiated loss.
        return data_loss + self._l1_l2_penalty(params) / eb, extra_state

    # ----------------------------------------------------------- train step

    def _train_step(self, params, state, opt_state, x, y, fmask, lmask, step, rng,
                    carry_rnn=False, eb=None, collect_stats=False):
        pol = self.dtype_policy
        scaling = pol.uses_loss_scaling
        lowp = pol.low_precision_params
        # Transfer learning / LoRA: differentiate the TRAINABLE subtree
        # only — frozen leaves (incl. int8 bases, which jax.grad refuses)
        # close over the loss as constants, their grads are never built,
        # and they re-attach to the outputs as the same arrays. Empty
        # spec: identity, the traced program is unchanged.
        spec = getattr(self, "_frozen_spec", None)
        if spec:
            params, frozen_stored = transfer_mod.split_tree(params, spec)
        else:
            frozen_stored = None

        def loss_fn(p):
            if frozen_stored is not None:
                p = transfer_mod.merge_tree(p, frozen_stored)
            preout, new_state, _, aux = self._forward_fn(
                p, state, x, rng, True, fmask, keep_rnn_state=carry_rnn
            )
            loss, extra_state = self._loss_from_preout(p, preout, y, lmask, aux, eb)
            for lk, s in extra_state.items():
                new_state.setdefault(lk, {}).update(s)
            return loss, new_state

        if scaling:
            # Dynamic loss scaling (f16-class compute): backward runs on the
            # SCALED loss so small grads survive the f16 representable range;
            # grads unscale in f32 afterwards. The (scale, good_count) pair is
            # part of opt_state — device-resident, so a fused superstep scan
            # carries it with zero host round-trips.
            scale, good = opt_state["_ls"]

            def scaled_loss_fn(p):
                loss, new_state = loss_fn(p)
                return loss * scale.astype(loss.dtype), (loss, new_state)

            (_, (loss, new_state)), grads = jax.value_and_grad(
                scaled_loss_fn, has_aux=True)(params)
            grads = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32) / scale, grads)
            finite = jnp.bool_(True)
            for leaf in jax.tree_util.tree_leaves(grads):
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
        else:
            (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if lowp:
                grads = _cast_floating(grads, jnp.float32)

        # Low-precision params: updates apply to the f32 MASTER copy (and
        # f32 updater state); stored params are its cast, so tiny updates
        # never underflow bf16/f16 quantization.
        base = opt_state["_master"] if lowp else params
        frozen_master = None
        if spec and lowp:
            base, frozen_master = transfer_mod.split_tree(base, spec)
        new_base, new_opt, stats = self._apply_updates(
            base, grads, opt_state, step, collect_stats=collect_stats)

        if scaling:
            # Skip-step on non-finite scaled grads: every updated leaf
            # selects its OLD value (params, updater state, batch stats),
            # then the scale backs off; after `growth_interval` consecutive
            # finite steps it grows. All `jnp.where` on device — no host
            # sync, superstep-safe.
            def sel(n, o):
                return jnp.where(finite, n, o)

            new_base = jax.tree_util.tree_map(sel, new_base, base)
            new_opt = jax.tree_util.tree_map(
                sel, new_opt, {lk: opt_state[lk] for lk in new_opt})
            new_state = {
                lk: {k: (sel(v, state[lk][k])
                         if lk in state and k in state[lk] else v)
                     for k, v in s.items()}
                for lk, s in new_state.items()
            }
            new_good = jnp.where(finite, good + 1.0, jnp.float32(0.0))
            grow = new_good >= jnp.float32(pol.loss_scale_growth_interval)
            new_scale = jnp.where(
                finite,
                jnp.where(grow,
                          scale * jnp.float32(pol.loss_scale_growth_factor),
                          scale),
                scale * jnp.float32(pol.loss_scale_backoff_factor))
            new_good = jnp.where(grow, jnp.float32(0.0), new_good)

        if lowp:
            new_params = _cast_floating(new_base, pol.jnp_param)
            if frozen_stored is not None:
                # Frozen STORED leaves pass through untouched (no recast);
                # the master keeps its frozen f32 copies alongside.
                new_params = transfer_mod.merge_tree(new_params, frozen_stored)
                new_opt["_master"] = transfer_mod.merge_tree(
                    new_base, frozen_master)
            else:
                new_opt["_master"] = new_base
        elif frozen_stored is not None:
            new_params = transfer_mod.merge_tree(new_base, frozen_stored)
        else:
            new_params = new_base
        if scaling:
            new_opt["_ls"] = (new_scale, new_good)

        # Merge persistent-state updates (BN stats / rnn carries) over old state.
        merged_state = dict(state)
        for lk, s in new_state.items():
            merged = dict(merged_state.get(lk, {}))
            merged.update(s)
            merged_state[lk] = merged
        if collect_stats:
            return new_params, merged_state, new_opt, loss, stats
        return new_params, merged_state, new_opt, loss

    def _apply_updates(self, params, grads, opt_state, step,
                       collect_stats=False):
        """Per-layer gradient-normalize + updater + param update (traced) —
        the reference's LayerUpdater stack. Shared by `_train_step` and
        `parallel/pipeline_trainer.py`'s pipelined step."""
        g = self.conf.global_conf
        sign = 1.0 if g.minimize else -1.0
        new_params: Dict[str, Any] = {}
        new_opt: Dict[str, Any] = {}
        stats: Dict[str, Any] = {}
        for i, (lk, layer) in enumerate(zip(self.layer_keys, self.layers)):
            lgrads = grads.get(lk, {})
            if not lgrads:
                new_params[lk] = params.get(lk, {})
                new_opt[lk] = opt_state.get(lk, ())
                continue
            lgrads = grad_norm_mod.normalize_layer_gradients(
                lgrads, layer.gradient_normalization,
                float(layer.gradient_normalization_threshold or 1.0),
            )
            lr = self._schedules[i](step)
            st, deltas = self._updaters[i].update(opt_state[lk], lgrads, lr, step)
            base_lr = float(layer.learning_rate if layer.learning_rate is not None else g.learning_rate)
            bias_lr = float(layer.bias_learning_rate if layer.bias_learning_rate is not None else base_lr)
            if bias_lr != base_lr and base_lr != 0.0:
                factor = bias_lr / base_lr
                # is_bias_param covers every bias name (b, b_f/b_b for
                # bidirectional RNNs, vb/eb/db for RBM/VAE, beta for BN) —
                # reference `LayerUpdater.java:243` applies biasLearningRate
                # per param TYPE, not only to params literally named "b".
                deltas = {k: (d * factor if is_bias_param(k) else d)
                          for k, d in deltas.items()}
            new_params[lk] = {
                k: params[lk][k] - sign * deltas[k] for k in params[lk]
            }
            new_opt[lk] = st
            if collect_stats:
                # Per-param mean magnitudes of gradient/update/param, computed
                # in-jit so only scalars cross the device boundary (reference
                # StatsListener "mean magnitudes", BaseStatsListener.java:273).
                stats[lk] = {
                    k: {
                        "grad_mm": jnp.mean(jnp.abs(lgrads[k])),
                        "update_mm": jnp.mean(jnp.abs(deltas[k])),
                        "param_mm": jnp.mean(jnp.abs(new_params[lk][k])),
                    }
                    for k in lgrads
                }
        return new_params, new_opt, stats

    # ------------------------------------------------------------------ fit

    def fit(self, data, labels=None):
        """Train over an iterator/DataSet/(x, y) pair — one pass
        (reference: `MultiLayerNetwork.fit(DataSetIterator)` `:976`)."""
        if not self._initialized:
            self.init()
        if labels is not None or isinstance(data, DataSet) or (
                isinstance(data, tuple) and len(data) == 2
                and not isinstance(data[0], DataSet)):
            # The DataSet guard keeps a 2-element tuple OF DataSets (a valid
            # small iterator) from being misread as an (x, y) pair.
            iterator = [_as_dataset(data, labels)]
        else:
            iterator = data
        maybe_reset(iterator)

        g = self.conf.global_conf
        if self.conf.pretrain:
            if not hasattr(iterator, "reset") and not isinstance(iterator, (list, tuple)):
                # One-shot iterable: materialize so both the pretrain pass and
                # the backprop pass see the data.
                iterator = list(iterator)
            self.pretrain(iterator)
            maybe_reset(iterator)
        for listener in self.listeners:
            listener.on_epoch_start(self)
        with _obs.tracer.span("mln.fit", cat="train", epoch=self.epoch):
            if self.conf.backprop:
                k = self._superstep_k()
                src = self._superstep_wrap(iterator, k) if k > 1 else iterator
                # Overlap host->device transfers with compute: multi-batch
                # epochs stream through a background DeviceStager (single
                # batches and already-staging sources pass through).
                src = _staging.maybe_stage(
                    src, net=self, engine="mln",
                    transfer_dtype=getattr(self.dtype_policy,
                                           "transfer_dtype", None))
                src_it = iter(src)
                try:
                    while True:
                        # iterator-next is timed separately: with async/staged
                        # input tiers this wait is pure device starvation.
                        t_wait = time.perf_counter()
                        try:
                            ds = next(src_it)
                        except StopIteration:
                            break
                        self._last_input_wait = time.perf_counter() - t_wait
                        _M_INPUT_WAIT.observe(self._last_input_wait)
                        self._fit_dispatch(ds)
                finally:
                    # An abandoned epoch must not leave staged HBM buffers.
                    _staging.close_stager(src_it)
                    _staging.close_stager(src)
        self.epoch += 1
        _M_EPOCHS.inc()
        for listener in self.listeners:
            listener.on_epoch_end(self)
        return self

    def _fit_dispatch(self, ds):
        """tBPTT/plain/superstep dispatch + iterations loop for one staged
        batch (or stacked `Superbatch`) — shared by `fit()` and
        `ParallelWrapper` so sharded training honors the same backprop-type
        config. Also the engine's observability choke point: every training
        path (plain / tBPTT / solver / superstep, local or sharded) stages
        batches through here, and `StepProfiler` patches this method on the
        instance."""
        tdt = getattr(self.dtype_policy, "transfer_dtype", None)
        if tdt is not None:
            ds = transfer_cast(ds, tdt)
        h2d = _obs.host_nbytes(ds.features, ds.labels,
                               ds.features_mask, ds.labels_mask)
        _M_H2D.inc(h2d)
        it0 = self.iteration
        t0 = time.perf_counter()
        with _obs.iteration_span("mln", it0 + 1):
            try:
                return self._fit_dispatch_inner(ds)
            except Exception as e:
                # Forensics for uncaught dispatch failures: the bundle is
                # written before the exception unwinds the fit loop.
                _obs.flight.on_crash("mln.dispatch", e)
                raise
            finally:
                dt = time.perf_counter() - t0
                _dispatch_observe(int(getattr(ds, "k", 1)), dt)
                _M_ITERS.inc(max(0, self.iteration - it0))
                _obs.flight.record_step(
                    "mln", self.iteration, loss=self._score, seconds=dt,
                    k=int(getattr(ds, "k", 1)), h2d_bytes=h2d,
                    input_wait=getattr(self, "_last_input_wait", None),
                    jit_hits=_M_JIT_HIT.get(), jit_misses=_M_JIT_MISS.get())

    def _fit_dispatch_inner(self, ds):
        if isinstance(ds, Superbatch):
            # Stacked K-block: `_superstep_k` already gated out the solver /
            # tBPTT / stats / multi-iteration paths before blocks formed.
            return self._fit_superstep(ds)
        g = self.conf.global_conf
        algo = OptimizationAlgorithm.of(g.optimization_algo)
        if algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            return self._fit_solver(ds, algo)
        tbptt = BackpropType.of(self.conf.backprop_type) == BackpropType.TRUNCATED_BPTT
        for _ in range(max(1, g.iterations)):
            if tbptt and ds.features.ndim == 3 and ds.features.shape[1] > self.conf.tbptt_fwd_length:
                self._fit_tbptt(ds)
            else:
                self._fit_one(ds)

    # -------------------------------------------------------------- superstep

    def _superstep_k(self) -> int:
        """Effective superstep K for this engine: the `superstep_k` config
        knob (env `DL4J_TPU_SUPERSTEP_K` overrides), gated to 0 — per-batch
        dispatch — whenever a path needs per-iteration host visibility or
        its own dispatch structure: stats-collecting listeners
        (`_collect_stats`, same precedent as the tBPTT scan), truncated
        BPTT (already scan-fused per sequence), solver optimizers, and
        multi-`iterations` batches."""
        env = os.environ.get("DL4J_TPU_SUPERSTEP_K")
        g = self.conf.global_conf
        try:
            k = int(env) if env else int(getattr(g, "superstep_k", 0) or 0)
        except ValueError:
            return 0
        if (k < 2 or self._collect_stats
                or max(1, g.iterations) != 1
                or BackpropType.of(self.conf.backprop_type)
                == BackpropType.TRUNCATED_BPTT
                or OptimizationAlgorithm.of(g.optimization_algo)
                != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
            return 0
        return k

    def _check_sgd_only_policy(self, what: str) -> None:
        pol = self.dtype_policy
        if pol.low_precision_params or pol.uses_loss_scaling:
            raise ValueError(
                f"{what} does not support dtype policy {pol.name!r}: "
                "low-precision param storage (f32 master copies) and "
                "dynamic loss scaling are SGD-train-step features; use a "
                "float32 / float64 / mixed_bfloat16 policy here")

    def _superstep_wrap(self, iterator, k: int):
        """Wrap `iterator` in a `SuperbatchIterator`, caching the wrapper on
        the base iterator so a device-cached epoch restacks once, not per
        `fit()` call. The policy's `transfer_dtype` rides along so staged
        superbatches ship at the reduced dtype (halved H2D bytes)."""
        tdt = self.dtype_policy.transfer_dtype
        if isinstance(iterator, SuperbatchIterator):
            return iterator
        wrapper = getattr(iterator, "_superbatch_wrapper", None)
        if (isinstance(wrapper, SuperbatchIterator)
                and wrapper.base is iterator and wrapper.k == k
                and getattr(wrapper, "transfer_dtype", None) == tdt):
            wrapper.net = self  # staging budget follows the current net
            return wrapper
        wrapper = SuperbatchIterator(iterator, k, transfer_dtype=tdt,
                                     net=self)
        try:
            iterator._superbatch_wrapper = wrapper
        except (AttributeError, TypeError):
            pass  # lists/tuples/slots: re-wrapped per fit(), still correct
        return wrapper

    def _fit_superstep(self, sb: Superbatch):
        """One dispatch, K train iterations (see `train_superstep` in
        `_build_jit`). The returned `[K]` loss vector fans out to listeners
        per iteration, so ScoreIterationListener etc. observe the same
        (iteration, score) sequence as the per-batch loop — scores stay
        device scalars until someone reads `score_value`."""
        k = int(sb.k)
        if k == 1:  # defensive: SuperbatchIterator yields raw singletons
            return self._fit_one(DataSet(sb.features[0],
                                         None if sb.labels is None else sb.labels[0],
                                         None if sb.features_mask is None else sb.features_mask[0],
                                         None if sb.labels_mask is None else sb.labels_mask[0]))
        step_fn = self._get_jit("train_superstep", k=k,
                                scan=_superstep.use_scan(),
                                kernels=_superstep.kernel_config())
        (self.params_tree, self.state, self.opt_state, losses,
         self._clock) = step_fn(
            self.params_tree, self.state, self.opt_state,
            jnp.asarray(sb.features), jnp.asarray(sb.labels),
            None if sb.features_mask is None else jnp.asarray(sb.features_mask),
            None if sb.labels_mask is None else jnp.asarray(sb.labels_mask),
            self._device_clock(),
        )
        for i in range(k):
            self._score = losses[i]  # device scalar; sync deferred
            self.iteration += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration)

    def _fit_solver(self, ds: DataSet, algo):
        """Full-batch LBFGS/CG/line-search optimize of one batch (reference:
        `Solver.java:41-110` dispatching to `optimize/solvers/`); the whole
        `iterations`-step solver loop is one jitted XLA computation
        (`optimize/solvers.py`). Deterministic forward (no dropout, BN
        running stats) so the line search sees a stable objective."""
        self._check_sgd_only_policy("solver optimizers (LBFGS/CG/line search)")
        g = self.conf.global_conf
        fn = self._get_jit("solver_step", algo=str(algo))
        self.params_tree, loss = fn(
            self.params_tree, self.state,
            jnp.asarray(ds.features), jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
        )
        self._score = loss
        self.iteration += max(1, g.iterations)
        # Per-layer grad/update stats are an SGD-path feature; clear any
        # stale snapshot from a previous SGD run so a StatsListener attached
        # on the solver path never reports stats from another optimizer.
        self.last_training_stats = {}
        # Deviation from the reference: `BaseOptimizer` fires listeners once
        # per SOLVER ITERATION; the jitted whole-loop solver surfaces one
        # callback per batch (iteration count still advances by
        # g.iterations), trading listener granularity for an XLA-fused loop.
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)

    # ------------------------------------------------------------- pretrain

    def pretrain(self, iterator, epochs: int = 1):
        """Layerwise unsupervised pretraining of AE/RBM/VAE layers (reference:
        `MultiLayerNetwork.pretrain()` `:164` — feed data forward to each
        pretrainable layer, optimize that layer's unsupervised loss)."""
        from deeplearning4j_tpu.nn.layers import PRETRAIN_LOSSES

        self._check_sgd_only_policy("layerwise pretraining")
        if not self._initialized:
            self.init()
        if isinstance(iterator, DataSet):
            iterator = [iterator]
        elif not hasattr(iterator, "reset") and not isinstance(iterator, (list, tuple)):
            iterator = list(iterator)  # one-shot iterable: every layer/epoch needs it
        for i, layer in enumerate(self.layers):
            if not layer.is_pretrainable():
                continue
            loss_impl = PRETRAIN_LOSSES.get(type(layer).__name__)
            if loss_impl is None:
                continue
            for _ in range(max(1, epochs)):
                maybe_reset(iterator)
                for ds in iterator:
                    self._pretrain_step(i, layer, loss_impl,
                                        jnp.asarray(ds.features))
        return self

    def _pretrain_step(self, layer_idx: int, layer, loss_impl, x):
        lk = self.layer_keys[layer_idx]
        key = ("pretrain", layer_idx)
        if key not in self._jit_cache:
            prep = self.conf.input_preprocessors.get(layer_idx)

            def step_fn(lparams, opt_state, full_params, state, x, clock):
                step, key = clock
                key, rng = jax.random.split(key)

                def loss_fn(lp):
                    # Forward through the frozen stack below this layer.
                    h, _, _, _ = self._forward_fn(
                        {**full_params, lk: lp}, state, x, None, False, None,
                        upto=layer_idx,
                    )
                    if prep is not None:
                        h, _ = prep(h, None)
                    return loss_impl(layer, lp, h, rng)

                loss, grads = jax.value_and_grad(loss_fn)(lparams)
                lr = self._schedules[layer_idx](step)
                st, deltas = self._updaters[layer_idx].update(opt_state, grads, lr, step)
                new_lp = {k: lparams[k] - deltas[k] for k in lparams}
                return new_lp, st, loss, (step + 1.0, key)

            # No donation: the layer's param buffers also appear inside
            # full_params (arg 2), so they cannot be safely donated.
            self._jit_cache[key] = jax.jit(step_fn)
        step_fn = self._jit_cache[key]
        new_lp, new_opt, loss, self._clock = step_fn(
            self.params_tree[lk], self.opt_state[lk], self.params_tree,
            self.state, x, self._device_clock(),
        )
        self.params_tree = {**self.params_tree, lk: new_lp}
        self.opt_state = {**self.opt_state, lk: new_opt}
        self._score = loss
        self.iteration += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)

    def _next_rng(self):
        if self._clock is not None:
            # The rng stream's continuation lives in the device clock; pull it
            # back to the host-side attribute before splitting.
            self._train_rng = self._clock[1]
            self._clock = None
        self._train_rng, sub = jax.random.split(self._train_rng)
        return sub

    def _fit_one(self, ds: DataSet):
        collect = self._collect_stats
        step_fn = self._get_jit("train_step_stats" if collect else "train_step")
        out = step_fn(
            self.params_tree, self.state, self.opt_state,
            jnp.asarray(ds.features),
            jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
            self._device_clock(),
        )
        if collect:
            self.params_tree, self.state, self.opt_state, loss, stats, self._clock = out
            self.last_training_stats = stats  # device scalars, fetched lazily
        else:
            self.params_tree, self.state, self.opt_state, loss, self._clock = out
        self._score = loss  # device scalar; sync deferred to score_value
        self.iteration += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)

    def _fit_tbptt(self, ds: DataSet):
        """Truncated BPTT (reference: `doTruncatedBPTT:1138`): chunk the time
        axis; rnn state carries across chunks as data (implicit gradient
        truncation at chunk boundaries)."""
        if any(getattr(l, "decode_cache_length", None) for l in self.layers):
            raise ValueError(
                "truncated BPTT carries undeclared layer state across "
                "chunks, which would thread attention KV caches into "
                "training; unset decode_cache_length (it is an inference "
                "feature) or use standard backprop")
        fwd = self.conf.tbptt_fwd_length
        t = ds.features.shape[1]
        n_chunks = math.ceil(t / fwd)
        saved_state = self.state
        # Divisor from the FULL-sequence mask: a row masked out of one chunk
        # (shorter sequence) still counts, reference divide-by-minibatch.
        eb = jax.device_put(np.float32(
            losses_mod.effective_batch_size(ds.features, ds.labels_mask)
        ))
        sparse_labels = (ds.labels is not None
                         and np.issubdtype(np.asarray(ds.labels).dtype,
                                           np.integer)
                         and np.ndim(ds.labels) == 2)
        if ds.labels is None or (np.ndim(ds.labels) != 3
                                 and not sparse_labels):
            raise ValueError(
                "Truncated BPTT requires per-timestep labels: [b, t, c] "
                "one-hot or [b, t] integer class ids "
                "(reference doTruncatedBPTT semantics)"
            )
        if not self._collect_stats:
            # Fast path: the entire chunk loop is one jitted scan — ONE
            # dispatch per sequence instead of one per chunk (PERF.md §4).
            step_fn = self._get_jit("train_step_tbptt_scan")
            (self.params_tree, self.state, self.opt_state, loss,
             self._clock) = step_fn(
                self.params_tree, self.state, self.opt_state,
                jnp.asarray(ds.features), jnp.asarray(ds.labels),
                None if ds.features_mask is None else jnp.asarray(ds.features_mask),
                None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
                self._device_clock(), eb,
            )
            self._score = loss
            self._finish_tbptt(saved_state)
            return
        # Stats path: per-chunk dispatch (keeps the last chunk's per-layer
        # stats observable, matching the pre-scan behavior).
        for ci in range(n_chunks):
            sl = slice(ci * fwd, min((ci + 1) * fwd, t))
            chunk = DataSet(
                ds.features[:, sl],
                ds.labels[:, sl],
                ds.features_mask[:, sl] if ds.features_mask is not None else None,
                ds.labels_mask[:, sl] if ds.labels_mask is not None else None,
            )
            collect = self._collect_stats
            step_fn = self._get_jit("train_step_tbptt",
                                    advance=ci == n_chunks - 1, collect=collect)
            out = step_fn(
                self.params_tree, self.state, self.opt_state,
                jnp.asarray(chunk.features),
                jnp.asarray(chunk.labels),
                None if chunk.features_mask is None else jnp.asarray(chunk.features_mask),
                None if chunk.labels_mask is None else jnp.asarray(chunk.labels_mask),
                self._device_clock(), eb,
            )
            if collect:
                (self.params_tree, self.state, self.opt_state, loss, stats,
                 self._clock) = out
                self.last_training_stats = stats
            else:
                self.params_tree, self.state, self.opt_state, loss, self._clock = out
            self._score = loss  # device scalar; sync deferred to score_value
        self._finish_tbptt(saved_state)

    def _finish_tbptt(self, saved_state):
        # Reset rnn carries after the sequence; keep persistent (BN) state.
        self.state = {
            lk: {k: v for k, v in s.items() if k in dict(self._declared_state()).get(lk, ())}
            for lk, s in self.state.items()
        }
        self.state = {lk: s for lk, s in self.state.items() if s}
        # Restore any BN stats that were present before if lost (safety).
        for lk, s in saved_state.items():
            self.state.setdefault(lk, s)
        self.iteration += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration)

    def _declared_state(self):
        return {
            lk: tuple(layer.state_shapes())
            for lk, layer in zip(self.layer_keys, self.layers)
        }

    # -------------------------------------------------------------- predict

    def output(self, x, train: bool = False, features_mask=None,
               params=None) -> np.ndarray:
        """Inference forward (reference: `output()` `:1519-1601`).
        `params` substitutes another params tree of the same structure
        (e.g. an adapter-merged serving tree — `nn/lora.py`) for this
        net's own; params are jit arguments, so the swap re-uses the
        compiled program."""
        fn = self._get_jit("output", train=train)
        out, _ = fn(self.params_tree if params is None else params,
                    self.state, jnp.asarray(x),
                    None if features_mask is None else jnp.asarray(features_mask),
                    self._next_rng() if train else jax.random.PRNGKey(0))
        return np.asarray(out)

    def feed_forward(self, x, train: bool = False, features_mask=None) -> List[np.ndarray]:
        """All layer activations (reference: `feedForward()` `:655-760`).
        Note: for output layers the listed activation is the pre-activation."""
        fn = self._get_jit("feedforward", train=train)
        acts, _ = fn(self.params_tree, self.state, jnp.asarray(x),
                     None if features_mask is None else jnp.asarray(features_mask),
                     self._next_rng() if train else jax.random.PRNGKey(0))
        return [np.asarray(a) for a in acts]

    def predict(self, x) -> np.ndarray:
        return np.argmax(self.output(x), axis=-1)

    def score(self, data: Union[DataSet, tuple], labels=None) -> float:
        """Loss on a dataset without updating (reference: `score(DataSet)`)."""
        ds = _as_dataset(data, labels)
        fn = self._get_jit("score")
        return float(fn(
            self.params_tree, self.state,
            jnp.asarray(ds.features), jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
        ))

    # ----------------------------------------------------------------- rnn

    def rnn_time_step(self, x) -> np.ndarray:
        """Stateful single/multi-step inference (reference: `rnnTimeStep:2230`).
        Accepts [b, f] (one step) or [b, t, f]; hidden state persists across calls."""
        from deeplearning4j_tpu.nn import rnn_state as rnn_mod

        x = np.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        self._rnn_pos = rnn_mod.check_decode_budget(
            getattr(self, "_rnn_pos", 0), x.shape[1],
            rnn_mod.decode_capacity(self.layers))
        fn = self._get_jit("output", train=False, keep_rnn_state=True)
        state = rnn_mod.merge_rnn_state(self.state, self._rnn_state)
        out, new_state = fn(self.params_tree, state, jnp.asarray(x), None,
                            jax.random.PRNGKey(0))
        self._rnn_state = rnn_mod.split_rnn_state(new_state,
                                                  self._declared_state())
        out = np.asarray(out)
        return out[:, 0] if squeeze and out.ndim == 3 else out

    def rnn_clear_previous_state(self):
        self._rnn_state = {}
        self._rnn_pos = 0

    # ------------------------------------------------------------ eval misc

    def evaluate(self, iterator, top_n: int = 1):
        """Classification evaluation (reference: `evaluate(DataSetIterator)`
        `:2406-2506`)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        ev = Evaluation(top_n=top_n)
        maybe_reset(iterator)
        if isinstance(iterator, DataSet):
            iterator = [iterator]
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            ev.eval(ds.labels, out, mask=ds.labels_mask)
        return ev

    # ------------------------------------------------------------- params io

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        # Listeners that consume gradient/update stats (StatsListener) flip
        # the train step to the stats-collecting variant.
        self._collect_stats = any(
            getattr(l, "requires_training_stats", False) for l in listeners)
        return self

    def num_params(self) -> int:
        return int(sum(params_mod.num_params(l) for l in self.layers))

    def _param_orders(self):
        return {
            lk: list(layer.param_shapes())
            for lk, layer in zip(self.layer_keys, self.layers)
        }

    def params(self) -> np.ndarray:
        """Flattened 1-D param view (reference: `Model.params()`)."""
        return params_mod.flatten_params(self.params_tree, self.layer_keys, self._param_orders())

    def set_params(self, flat: np.ndarray):
        self.params_tree = params_mod.unflatten_params(
            np.asarray(flat), self.params_tree, self.layer_keys, self._param_orders()
        )
        if (self.dtype_policy.low_precision_params and self.opt_state
                and "_master" in self.opt_state):
            # Keep the f32 master in lockstep with an externally-set view.
            self.opt_state["_master"] = _cast_floating(
                self.params_tree, jnp.float32)

    def updater_state_flat(self) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(self.opt_state)
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate([np.asarray(l).reshape(-1) for l in leaves])

    def set_updater_state_flat(self, flat: np.ndarray):
        leaves, treedef = jax.tree_util.tree_flatten(self.opt_state)
        out, pos = [], 0
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(jnp.asarray(np.asarray(flat[pos:pos + n]).reshape(l.shape), l.dtype))
            pos += n
        self.opt_state = jax.tree_util.tree_unflatten(treedef, out)

    def clone(self) -> "MultiLayerNetwork":
        """Deep copy. Device buffers are COPIED (jnp.copy), not aliased: the
        source net's train step donates its buffers, which would delete a
        shared array out from under the clone."""
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self._initialized:
            net.init(params=jax.tree_util.tree_map(jnp.copy, self.params_tree))
            net.state = jax.tree_util.tree_map(jnp.copy, self.state)
            net.opt_state = jax.tree_util.tree_map(jnp.copy, self.opt_state)
            net.iteration = self.iteration
            net.epoch = self.epoch
        return net

    def summary(self) -> str:
        lines = ["=" * 70]
        lines.append(f"{'Layer':<28}{'Type':<24}{'Params':>10}")
        lines.append("-" * 70)
        for lk, layer in zip(self.layer_keys, self.layers):
            lines.append(f"{lk:<28}{type(layer).__name__:<24}{params_mod.num_params(layer):>10}")
        lines.append("-" * 70)
        lines.append(f"Total params: {self.num_params()}")
        lines.append("=" * 70)
        return "\n".join(lines)
