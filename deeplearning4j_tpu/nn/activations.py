"""Activation function registry.

TPU-native equivalent of ND4J's `IActivation` SPI (referenced from every layer's
`activation(...)` builder setting; see reference `nn/conf/layers/*` and SURVEY.md
§2.4). Implemented as pure jax functions so XLA fuses them into the surrounding
matmul — there is no per-op dispatch as in the reference's op executioner.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import Activation

ActivationFn = Callable[[jnp.ndarray], jnp.ndarray]


def _rational_tanh(x):
    # Rational approximation of tanh (reference: ND4J RationalTanh):
    # f(x) = 1.7159 * tanh_approx(2x/3), tanh_approx(y) = sign(y) * (1 - 1/(1+|y|+y^2+1.41645*y^4))
    y = 2.0 * x / 3.0
    a = jnp.abs(y)
    approx = jnp.sign(y) * (1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * (y ** 4)))
    return 1.7159 * approx


def _hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


_REGISTRY: dict[str, ActivationFn] = {
    Activation.SIGMOID.value: jax.nn.sigmoid,
    Activation.TANH.value: jnp.tanh,
    Activation.SOFTMAX.value: lambda x: jax.nn.softmax(x, axis=-1),
    Activation.IDENTITY.value: lambda x: x,
    Activation.RELU.value: jax.nn.relu,
    Activation.LEAKYRELU.value: lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    Activation.ELU.value: jax.nn.elu,
    Activation.CUBE.value: lambda x: x ** 3,
    Activation.SOFTPLUS.value: jax.nn.softplus,
    Activation.SOFTSIGN.value: jax.nn.soft_sign,
    Activation.RATIONALTANH.value: _rational_tanh,
    Activation.RECTIFIEDTANH.value: lambda x: jnp.maximum(jnp.tanh(x), 0.0),
    Activation.HARDSIGMOID.value: _hard_sigmoid,
    Activation.HARDTANH.value: jax.nn.hard_tanh,
    Activation.SELU.value: jax.nn.selu,
    Activation.GELU.value: jax.nn.gelu,
    Activation.SWISH.value: jax.nn.swish,
}


def resolve(activation: Union[str, Activation, ActivationFn, None]) -> ActivationFn:
    """Resolve an activation spec (enum/string/callable) to a jax function."""
    if activation is None:
        return _REGISTRY[Activation.IDENTITY.value]
    if callable(activation) and not isinstance(activation, (str, Activation)):
        return activation
    key = activation.value if isinstance(activation, Activation) else str(activation).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation: {activation!r}. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def register(name: str, fn: ActivationFn) -> None:
    """Register a custom activation (reference: custom `IActivation` subtype support)."""
    _REGISTRY[name.lower()] = fn
