"""Shared stateful-inference bookkeeping for both engines.

`rnn_time_step` (reference: `MultiLayerNetwork.rnnTimeStep:2230`,
`ComputationGraph.rnnTimeStep:1386`) carries UNDECLARED layer state (LSTM
hidden carries, attention KV caches, positional cursors) across calls.
The merge/split rules and the decode-capacity guard live here once so the
two engines cannot drift.
"""

from __future__ import annotations

from typing import Dict, Optional


def merge_rnn_state(base_state: Dict, rnn_state: Dict) -> Dict:
    """Overlay carried rnn state on the persistent (declared) state."""
    state = dict(base_state)
    for key, s in rnn_state.items():
        merged = dict(state.get(key, {}))
        merged.update(s)
        state[key] = merged
    return state


def split_rnn_state(new_state: Dict, declared: Dict) -> Dict:
    """Keep only the UNDECLARED entries (the rnn carries) of a forward's
    returned state — declared entries (BN stats) stay in engine.state."""
    out = {
        key: {k: v for k, v in s.items()
              if k not in declared.get(key, ())}
        for key, s in new_state.items()
    }
    return {key: s for key, s in out.items() if s}


def decode_capacity(layers) -> Optional[int]:
    """Smallest decode_cache_length across attention layers (None when no
    layer carries a KV cache) — the hard step budget for one stateful
    sequence."""
    caps = [l.decode_cache_length for l in layers
            if getattr(l, "decode_cache_length", None)]
    return min(caps) if caps else None


def check_decode_budget(pos: int, t: int, capacity: Optional[int]) -> int:
    """Host-side guard: the in-jit cache write clamps silently past
    capacity, so the ENGINES refuse first. Returns the new position."""
    if capacity is not None and pos + t > capacity:
        raise ValueError(
            f"stateful decode overflow: position {pos} + {t} new steps "
            f"exceeds the decode cache capacity {capacity}; call "
            "rnn_clear_previous_state() to start a new sequence")
    return pos + t
