"""Active parallelism context: how a DSL-built model reaches the mesh.

The reference's only parallelism is data-parallel parameter averaging wired
through wrapper objects (`parallelism/ParallelWrapper.java:322`); its config
DSL never needs to know about devices. On TPU the interesting axes —
sequence/context (`parallel/sequence.py`), expert (`parallel/expert.py`),
tensor (`parallel/mesh.py`) — change how a LAYER's forward is computed, so
layer implementations need to see the mesh at trace time. This module is
that bridge: a process-wide `ParallelContext` naming the mesh and the role
of each axis. Engines/wrappers install it (e.g. `ParallelWrapper(...,
seq_axis="seq")`) around their jitted-step tracing; layer impls
(`nn/layers/attention.py`, `nn/layers/moe.py`) consult it and pick the
sharded collective path when the relevant axis exists. The context is
read at TRACE time only (it selects which program to build — never a
traced value), so each engine folds `cache_key()` into its jit-cache key:
the same net can train sharded and unsharded in one process without stale
programs.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Optional

from jax.sharding import Mesh


@dataclass(frozen=True)
class ParallelContext:
    """Names the mesh axes by role. Any axis may be absent (None)."""

    mesh: Mesh
    data_axis: Optional[str] = "data"
    model_axis: Optional[str] = None
    seq_axis: Optional[str] = None
    expert_axis: Optional[str] = None
    pipe_axis: Optional[str] = None

    def __post_init__(self):
        for role in ("data_axis", "model_axis", "seq_axis", "expert_axis",
                     "pipe_axis"):
            name = getattr(self, role)
            if name is not None and name not in self.mesh.shape:
                raise ValueError(
                    f"{role}={name!r} is not an axis of the mesh "
                    f"(axes: {tuple(self.mesh.shape)})")

    def axis_size(self, role: str) -> int:
        """Mesh size of the axis filling `role` ('seq', 'expert', ...); 1 if
        the role is unassigned."""
        name = getattr(self, role + "_axis")
        return int(self.mesh.shape[name]) if name is not None else 1

    def cache_key(self):
        """Hashable description for engine jit-cache keys. The Mesh object
        itself is part of the key (it hashes by device identity), so two
        same-topology meshes over DIFFERENT devices never share a traced
        program whose sharding constraints are bound to the wrong devices."""
        return (
            self.mesh,
            self.data_axis, self.model_axis, self.seq_axis,
            self.expert_axis, self.pipe_axis,
        )


_state = threading.local()


def current_context() -> Optional[ParallelContext]:
    return getattr(_state, "ctx", None)


@contextmanager
def parallel_context(ctx: Optional[ParallelContext]):
    """Install `ctx` as the active parallelism context for the block."""
    prev = current_context()
    _state.ctx = ctx
    try:
        yield ctx
    finally:
        _state.ctx = prev


def context_cache_key():
    """The active context's cache key (None when no context is active) —
    engines mix this into their jit-cache keys."""
    ctx = current_context()
    return None if ctx is None else ctx.cache_key()
