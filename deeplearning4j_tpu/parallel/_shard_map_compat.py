"""shard_map import/kwarg compatibility across jax versions.

Newer jax exposes ``jax.shard_map`` whose replication-check knob is spelled
``check_vma=``; older releases (<= 0.4.x) only have
``jax.experimental.shard_map.shard_map`` where the same knob is
``check_rep=``.  Call sites in this package use the new-style spelling; on
old jax we translate the kwarg.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.5
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)
