"""Sequence/context parallelism: ring attention and Ulysses-style
all-to-all attention over a mesh axis.

The reference framework predates attention — its only long-sequence
mechanism is truncated BPTT (`MultiLayerNetwork.java:1207`,
`MultiLayerConfiguration.java:66-68`) — so SURVEY.md §5 sets tBPTT+masking
as the parity bar and names "ring-attention/context-parallel via shard_map
collective-permute over ICI" as the TPU-native extension for sequence-length
scaling. This module is that extension:

- `ring_attention(...)`: exact attention over a sequence axis sharded across
  mesh devices. Each device holds a [B, T/p, H, Dh] block of q/k/v; k/v
  blocks rotate around the ring via `lax.ppermute` while a flash-style
  online-softmax accumulator (running max + running sum) folds each block
  in, so no device ever materializes the [T, T] score matrix and per-device
  memory is O(T/p). Compute overlaps the ICI transfer because each
  ppermute'd block is consumed by the next scan step.
- `ulysses_attention(...)`: the all-to-all variant — redistribute
  [seq-sharded, all heads] -> [all seq, head-sharded] with
  `lax.all_to_all`, run ordinary full attention per head group, and
  redistribute back. Cheaper collectives for moderate T; requires
  n_heads % mesh_axis == 0.

Both are differentiable (scan + ppermute/all_to_all have transposes), jit
under `shard_map`, and are exact — equivalence against dense single-device
attention is tested on the 8-device virtual CPU mesh in
`tests/test_sequence_parallel.py`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from deeplearning4j_tpu.parallel._shard_map_compat import shard_map

_NEG = -1e30  # finite mask value: keeps exp() well-defined for masked rows


def _block_update(carry, q, k, v, kpos, qpos, causal, scale):
    """Fold one k/v block into the online-softmax accumulator.

    q: [B, H, Tq, D]; k, v: [B, H, Tk, D];
    carry = (acc [B, H, Tq, D], m [B, H, Tq], l [B, H, Tq]).
    """
    acc, m, l = carry
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s = jnp.where(kpos[None, :] > qpos[:, None], _NEG, s)
    blk_max = jnp.max(s, axis=-1)
    new_m = jnp.maximum(m, blk_max)
    corr = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m[..., None])
    if causal:
        # Rows whose every key so far is masked: new_m == _NEG makes
        # p == exp(0); zero those contributions explicitly.
        p = jnp.where(new_m[..., None] <= _NEG / 2, 0.0, p)
    l = l * corr + jnp.sum(p, axis=-1)
    acc = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return acc, new_m, l


def _ring_local(q, k, v, *, axis_name: str, n_blocks: int, causal: bool,
                scale: float):
    """Per-device body (runs inside shard_map). q/k/v: [B, T_loc, H, D]."""
    me = jax.lax.axis_index(axis_name)
    orig_dtype = q.dtype
    # [B, H, T, D] layout for the attention inner loops; accumulate in at
    # least fp32 (fp64 stays fp64 so x64 tests are exact).
    acc_dtype = jnp.promote_types(orig_dtype, jnp.float32)
    q, k, v = (jnp.swapaxes(a, 1, 2).astype(acc_dtype) for a in (q, k, v))
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qpos = me * Tq + jnp.arange(Tq)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    acc = jnp.zeros((B, H, Tq, D), acc_dtype)
    m = jnp.full((B, H, Tq), _NEG, acc_dtype)
    l = jnp.zeros((B, H, Tq), acc_dtype)

    def step(carry, s):
        k, v, acc, m, l = carry
        src = (me - s) % n_blocks  # ring step s holds src's original block
        kpos = src * Tk + jnp.arange(Tk)
        acc, m, l = _block_update((acc, m, l), q, k, v, kpos, qpos, causal,
                                  scale)
        k, v = jax.lax.ppermute((k, v), axis_name, perm)
        return (k, v, acc, m, l), None

    (k, v, acc, m, l), _ = jax.lax.scan(
        step, (k, v, acc, m, l), jnp.arange(n_blocks))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


def ring_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                   batch_axis: Optional[str] = "data", causal: bool = True,
                   scale: Optional[float] = None):
    """Exact multi-head attention with the SEQUENCE dim sharded over
    `mesh.shape[seq_axis]` devices (and optionally batch over `batch_axis`).

    q, k, v: [B, T, H, Dh] global arrays (or already-sharded). Returns
    [B, T, H, Dh] with the same sharding. Set `causal=False` for full
    (encoder) attention.
    """
    n = int(mesh.shape[seq_axis])
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    b_ax = batch_axis if batch_axis in mesh.shape else None
    spec = P(b_ax, seq_axis, None, None)
    fn = shard_map(
        functools.partial(_ring_local, axis_name=seq_axis, n_blocks=n,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def _dense_attn(q, k, v, causal, scale):
    """Single-device reference attention (also the Ulysses per-shard body).
    q/k/v: [B, H, T, D] fp32."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = s.shape[-1]
        s = jnp.where(jnp.triu(jnp.ones((T, T), bool), 1)[None, None], _NEG, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-device body: seq-sharded [B, T/p, H, D] -> all_to_all ->
    head-sharded [B, T, H/p, D] -> dense attention -> all_to_all back."""
    orig_dtype = q.dtype
    acc_dtype = jnp.promote_types(orig_dtype, jnp.float32)

    def to_heads(a):  # [B, T/p, H, D] -> [B, H/p, T, D]
        a = jax.lax.all_to_all(a, axis_name, split_axis=2, concat_axis=1,
                               tiled=True)
        return jnp.swapaxes(a, 1, 2).astype(acc_dtype)

    o = _dense_attn(to_heads(q), to_heads(k), to_heads(v), causal, scale)
    o = jnp.swapaxes(o, 1, 2).astype(orig_dtype)  # [B, T, H/p, D]
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, *, seq_axis: str = "seq",
                      batch_axis: Optional[str] = "data",
                      causal: bool = True, scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style sequence parallelism: one all-to-all turns
    sequence sharding into head sharding, each device runs full-sequence
    attention for its head group, and a second all-to-all restores sequence
    sharding. Requires n_heads divisible by the mesh axis size."""
    n = int(mesh.shape[seq_axis])
    H = q.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses_attention needs n_heads ({H}) divisible by mesh axis "
            f"'{seq_axis}' ({n}); use ring_attention otherwise")
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    b_ax = batch_axis if batch_axis in mesh.shape else None
    spec = P(b_ax, seq_axis, None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=seq_axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def attention(q, k, v, *, causal: bool = True,
              scale: Optional[float] = None, impl: str = "auto"):
    """Single-device multi-head attention, q/k/v [B, T, H, Dh] — the
    framework's default attention entry point.

    impl="auto" uses the Pallas flash kernel (`ops/flash_attention.py`:
    1.2-3.1x XLA dense on a v5e, O(T·D) memory; falls back to dense
    internally when T isn't a block multiple); impl="dense" forces the XLA
    path (also the test oracle). For sequence-sharded attention use
    `ring_attention` / `ulysses_attention`."""
    if impl == "dense":
        return dense_attention(q, k, v, causal=causal, scale=scale)
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal, scale)


def dense_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None):
    """Single-device reference: q/k/v [B, T, H, Dh] -> [B, T, H, Dh]."""
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    acc_dtype = jnp.promote_types(q.dtype, jnp.float32)
    q_, k_, v_ = (jnp.swapaxes(a, 1, 2).astype(acc_dtype)
                  for a in (q, k, v))
    o = _dense_attn(q_, k_, v_, causal, scale)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)
