"""ParallelWrapper: data-parallel training over a device mesh.

API-level equivalent of the reference's
`deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java` — but where
the reference spawns N replica threads, round-robins minibatches, barriers, and
averages parameters every `averagingFrequency` iterations (`:322,353,179`), here
the SAME jitted train step simply runs with the batch sharded over the mesh's
"data" axis: XLA GSPMD emits the gradient all-reduce over ICI inside the step.
There is no averaging frequency because gradients synchronize every step (the
k=1 case the reference can't afford over its transports), no trainer threads,
and no updater-state divergence to repair (`:198-225`).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets import staging as _staging
from deeplearning4j_tpu.datasets.iterators import (
    MultiSuperbatch,
    Superbatch,
    batch_signature,
    maybe_reset,
    transfer_cast,
)
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.context import parallel_context
from deeplearning4j_tpu import observability as _obs

_M_BATCHES = _obs.metrics.counter(
    "dl4j_parallel_batches_total",
    "Batches sharded and dispatched through ParallelWrapper.fit")
_M_INPUT_WAIT = _obs.metrics.histogram(
    "dl4j_input_wait_seconds",
    "Host seconds blocked in iterator-next waiting for the next batch "
    "(input starvation; the device is idle while this accrues)",
    label_names=("source",)).labels(source="parallel")
_M_SHARD_SECONDS = _obs.metrics.counter(
    "dl4j_parallel_shard_dispatch_seconds_total",
    "Host seconds spent padding + device_put-sharding batches over the mesh "
    "(the host-side proxy for data distribution cost; in-step collective "
    "wait is inside XLA and not host-visible — see PERF.md)")
_M_DEVICES = _obs.metrics.gauge(
    "dl4j_parallel_devices", "Mesh size of the active ParallelWrapper")


class ParallelWrapper:
    """Data-parallel fit() driver (see module docstring).

    `workers`/`averaging_frequency`/`prefetch_buffer` are accepted for
    reference API parity; `workers` maps to the mesh size, averaging is
    per-step by construction.
    """

    def __init__(self, net, mesh=None, workers: Optional[int] = None,
                 averaging_frequency: int = 1, prefetch_buffer: int = 2,
                 report_score_after_averaging: bool = True,
                 model_axis: Optional[str] = None,
                 seq_axis: Optional[str] = None,
                 expert_axis: Optional[str] = None):
        self.net = net
        self.prefetch_buffer = max(1, int(prefetch_buffer or 2))
        if mesh is None:
            devices = jax.devices()[:workers] if workers else jax.devices()
            mesh = mesh_mod.create_mesh(devices=devices)
        self.mesh = mesh
        self.data_axis = mesh.axis_names[0]
        self.n_devices = int(np.prod(mesh.devices.shape))
        if not net._initialized:
            net.init()
        mesh_mod.shard_params(net, mesh, model_axis=model_axis,
                              expert_axis=expert_axis)
        # Axis roles beyond "data" activate the corresponding layer paths
        # (ring attention over seq_axis, expert-parallel MoE) at trace time
        # via the ParallelContext installed around every dispatch.
        from deeplearning4j_tpu.parallel.context import ParallelContext

        self.context = ParallelContext(
            mesh=mesh, data_axis=self.data_axis, model_axis=model_axis,
            seq_axis=seq_axis, expert_axis=expert_axis)
        _M_DEVICES.set(self.n_devices)

    def _pad_dataset(self, ds: DataSet) -> DataSet:
        """Pad the batch dim up to a multiple of the mesh size (XLA needs the
        sharded dim divisible). Padded rows are masked out of the loss via a
        zeroed labels mask, so a ragged final batch trains identically to the
        unpadded batch (padded rows contribute zero to the summed loss, the
        score divisor counts only real rows, and CenterLoss center updates
        are mask-weighted). Known limitation: BatchNormalization batch
        statistics in train mode are computed over the padded batch (the
        duplicated last row slightly skews mean/var for a ragged batch);
        exact for every batch divisible by the mesh."""
        b = np.asarray(ds.features).shape[0]
        rem = b % self.n_devices
        if rem == 0:
            return ds
        pad = self.n_devices - rem
        labels = None if ds.labels is None else np.asarray(ds.labels)
        lmask = ds.labels_mask
        if labels is not None:
            lmask = _full_labels_mask(labels, lmask,
                                      sequence=self._seq_output())
        return DataSet(
            _pad_rows(np.asarray(ds.features), pad),
            _pad_rows(labels, pad),
            _pad_rows(ds.features_mask, pad, fill_last=False),
            _pad_rows(lmask, pad, fill_last=False),
        )

    def _pad_mds(self, mds: MultiDataSet) -> MultiDataSet:
        """MultiDataSet variant of `_pad_dataset` for ComputationGraph."""
        b = mds.num_examples()
        rem = b % self.n_devices
        if rem == 0:
            return mds
        pad = self.n_devices - rem
        labels = [np.asarray(l) for l in mds.labels]
        lmasks = list(mds.labels_masks) if mds.labels_masks is not None else [None] * len(labels)
        seq = self._seq_output()
        lmasks = [_full_labels_mask(l, m, sequence=seq)
                  for l, m in zip(labels, lmasks)]
        fmasks = mds.features_masks
        return MultiDataSet(
            features=[_pad_rows(np.asarray(f), pad) for f in mds.features],
            labels=[_pad_rows(l, pad) for l in labels],
            features_masks=None if fmasks is None
            else [_pad_rows(m, pad, fill_last=False) for m in fmasks],
            labels_masks=[_pad_rows(m, pad, fill_last=False) for m in lmasks],
        )

    def _seq_output(self) -> bool:
        """Whether the net's output layer(s) emit per-timestep labels —
        disambiguates 2-D INTEGER labels ([b, t] sparse ids vs [b, c]
        integer one-hot) when padding."""
        layers = getattr(self.net, "layers", None)
        if layers is not None:
            return type(layers[-1]).__name__ == "RnnOutputLayer"
        lv = getattr(self.net, "layer_vertices", {})
        return any(type(v.layer).__name__ == "RnnOutputLayer"
                   for v in lv.values())

    def _shard(self, a):
        if a is None:
            return None
        return jax.device_put(
            a, mesh_mod.data_sharding(self.mesh, np.ndim(a), self.data_axis)
        )

    def _prepare(self, ds, is_graph: bool):
        """Pad one host batch to a mesh-size-multiple batch dim, then apply
        the net's DtypePolicy `transfer_dtype` cast host-side so every
        per-device shard crosses the link in the reduced representation
        (same knob as the local SuperbatchIterator staging path)."""
        if is_graph:
            mds = MultiDataSet.from_dataset(ds) if isinstance(ds, DataSet) else ds
            padded = self._pad_mds(mds)
        else:
            if isinstance(ds, MultiDataSet):
                raise TypeError("MultiDataSet input requires a ComputationGraph net")
            padded = self._pad_dataset(ds)
        pol = getattr(self.net, "dtype_policy", None)
        tdt = getattr(pol, "transfer_dtype", None)
        return padded if tdt is None else transfer_cast(padded, tdt)

    def _shard_batch(self, padded, is_graph: bool):
        """device_put one padded batch with the batch dim over the mesh."""
        if is_graph:
            return MultiDataSet(
                features=[self._shard(np.asarray(f)) for f in padded.features],
                labels=[self._shard(np.asarray(l)) for l in padded.labels],
                features_masks=None if padded.features_masks is None
                else [self._shard(m) for m in padded.features_masks],
                labels_masks=None if padded.labels_masks is None
                else [self._shard(m) for m in padded.labels_masks],
            )
        return DataSet(
            self._shard(np.asarray(padded.features)),
            self._shard(None if padded.labels is None else np.asarray(padded.labels)),
            self._shard(padded.features_mask),
            self._shard(padded.labels_mask),
        )

    def _shard_super(self, parts):
        """np.stack K same-shape parts to [K, B, ...] and device_put with
        the BATCH axis (dim 1) sharded over the mesh — one transfer per
        part for the whole K-block."""
        if parts[0] is None:
            return None
        stacked = np.stack([np.asarray(p) for p in parts])
        return jax.device_put(stacked, mesh_mod.superbatch_sharding(
            self.mesh, stacked.ndim, self.data_axis))

    def _stack_shard(self, pending, is_graph: bool):
        """Stack K padded same-signature batches into a sharded superbatch."""
        k = len(pending)
        if is_graph:
            first = pending[0]
            feats = [self._shard_super([p.features[i] for p in pending])
                     for i in range(len(first.features))]
            labs = [self._shard_super([p.labels[i] for p in pending])
                    for i in range(len(first.labels))]
            fmasks = None if first.features_masks is None else [
                self._shard_super([p.features_masks[i] for p in pending])
                for i in range(len(first.features_masks))]
            lmasks = None if first.labels_masks is None else [
                self._shard_super([p.labels_masks[i] for p in pending])
                for i in range(len(first.labels_masks))]
            return MultiSuperbatch(feats, labs, fmasks, lmasks, k=k)
        return Superbatch(
            self._shard_super([p.features for p in pending]),
            self._shard_super([p.labels for p in pending]),
            self._shard_super([p.features_mask for p in pending]),
            self._shard_super([p.labels_mask for p in pending]),
            k=k,
        )

    def _grouped(self, iterator, k: int, is_graph: bool):
        """Yield lists of padded, transfer-cast, same-signature host
        batches: singletons when the superstep knob is off, else up to K
        per group (a signature change flushes early — heterogeneous
        shapes form per-signature blocks). Runs on the stager thread when
        staging is enabled, so pad+cast host work overlaps compute."""
        pending: list = []
        sig = None
        for ds in iterator:
            t0 = time.perf_counter()
            padded = self._prepare(ds, is_graph)
            _M_SHARD_SECONDS.inc(time.perf_counter() - t0)
            if k < 2:
                yield [padded]
                continue
            s = batch_signature(padded)
            if pending and s != sig:
                yield pending
                pending = []
            sig = s
            pending.append(padded)
            if len(pending) >= k:
                yield pending
                pending = []
        if pending:
            yield pending

    def _stage_group(self, group, is_graph: bool):
        """Shard one padded group over the mesh: a singleton becomes a
        batch-sharded DataSet/MultiDataSet, K batches a `[K, B, ...]`
        superbatch sharded on the batch axis. The DeviceStager's
        `stage_fn` — per-shard puts issue on the stager thread, ahead of
        dispatch."""
        t0 = time.perf_counter()
        if len(group) == 1:
            sharded = self._shard_batch(group[0], is_graph)
        else:
            sharded = self._stack_shard(group, is_graph)
        _M_SHARD_SECONDS.inc(time.perf_counter() - t0)
        return sharded

    def fit(self, iterator):
        """One pass over the iterator, each batch sharded across the mesh.

        Accepts the same inputs as the wrapped engine's `fit`: DataSet /
        iterator of DataSets for `MultiLayerNetwork`, plus MultiDataSet for
        `ComputationGraph` (the reference ParallelWrapper supports both,
        `ParallelWrapper.java:322` and the MDS variant `:151`).

        When the engine's `superstep_k` knob is active, consecutive
        same-signature padded batches are stacked into `[K, B, ...]`
        superbatches sharded on the BATCH axis (dim 1), so sharded training
        amortizes dispatch the same way local training does (PERF.md §13);
        the engine gate (`_superstep_k`) also covers the stats-listener /
        tBPTT / solver fallbacks here.

        Multi-batch epochs pad/cast/shard on a background `DeviceStager`
        (`prefetch_buffer` deep — the reference knob, now real), so the
        next sharded batch crosses the link while the current dispatch
        runs; single-batch fits (the elastic per-step path) shard
        synchronously, as does `DL4J_TPU_STAGING=0`."""
        net = self.net
        is_graph = type(net).__name__ == "ComputationGraph"
        maybe_reset(iterator)
        single = isinstance(iterator, (DataSet, MultiDataSet)) or (
            isinstance(iterator, (list, tuple)) and len(iterator) <= 1)
        if isinstance(iterator, (DataSet, MultiDataSet)):
            iterator = [iterator]
        k = net._superstep_k() if hasattr(net, "_superstep_k") else 0
        groups = self._grouped(iterator, k, is_graph)

        def stage(group):
            return self._stage_group(group, is_graph)

        if single or not _staging.staging_enabled():
            src = map(stage, groups)
        else:
            src = _staging.DeviceStager(
                groups, stage_fn=stage, net=net, engine="parallel",
                depth=self.prefetch_buffer)
        try:
            while True:
                t_wait = time.perf_counter()
                try:
                    sharded = next(src)
                except StopIteration:
                    break
                wait = time.perf_counter() - t_wait
                _M_INPUT_WAIT.observe(wait)
                # K batches feed one stacked dispatch: the flight record's
                # input_wait is the wait behind that dispatch.
                net._last_input_wait = wait
                _M_BATCHES.inc(int(getattr(sharded, "k", 1)))
                with _obs.tracer.span("parallel.batch", cat="parallel",
                                      devices=self.n_devices,
                                      data_axis=self.data_axis,
                                      k=int(getattr(sharded, "k", 1))):
                    with parallel_context(getattr(self, "context", None)):
                        net._fit_dispatch(sharded)
        finally:
            _staging.close_stager(src)
        return net

    def evaluate(self, iterator, top_n: int = 1):
        """Mesh-sharded evaluation (reference: the Spark module's
        distributed `evaluate`); see `parallel/evaluation.py`."""
        from deeplearning4j_tpu.parallel.evaluation import sharded_evaluate

        return sharded_evaluate(self.net, iterator, mesh=self.mesh,
                                top_n=top_n)

    def warmup(self, data=None, kinds=None, background: bool = False,
               batch_size: int = 32):
        """Pre-compile the SHARDED programs `fit()` will dispatch: the
        example batch (synthetic when `data` is None) is padded and
        device_put over this wrapper's mesh exactly like a training batch,
        so the warmed programs carry the right input shardings and mesh
        context. When the superstep knob is active the `[K, B, ...]`
        superbatch program is warmed too. See
        `compilation.warmup.warmup_net` for the return contract."""
        from deeplearning4j_tpu.compilation import warmup as warmup_mod

        net = self.net
        is_graph = type(net).__name__ == "ComputationGraph"
        if data is None:
            data = warmup_mod.synthetic_dataset(net, batch_size)
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        k = net._superstep_k() if hasattr(net, "_superstep_k") else 0
        items = []
        for ds in data:
            padded = self._prepare(ds, is_graph)
            items.append(self._shard_batch(padded, is_graph))
            has_labels = (padded.labels is not None)
            if k > 1 and kinds is None and has_labels:
                items.append(self._stack_shard([padded] * k, is_graph))
        return warmup_mod.warmup_net(net, items, kinds=kinds,
                                     background=background,
                                     batch_size=batch_size,
                                     context=self.context)

    def push_host_state(self, params_tree=None, opt_state=None, state=None):
        """Install host-side trees (numpy / jnp leaves) into the wrapped
        net and re-apply THIS wrapper's placement rules — the write-back
        half of host-mediated parameter averaging (`parallel/elastic.py`
        averages over the coordinator, then pushes the mean back through
        the same `shard_params` rules the constructor applied, so the
        next dispatch sees correctly-placed params, not host arrays).
        Only the trees passed are replaced; `None` leaves the net's
        current tree untouched."""
        net = self.net
        if params_tree is not None:
            net.params_tree = params_tree
        if opt_state is not None:
            net.opt_state = opt_state
        if state is not None:
            net.state = state
        ctx = getattr(self, "context", None)
        mesh_mod.shard_params(
            net, self.mesh,
            model_axis=None if ctx is None else ctx.model_axis,
            expert_axis=None if ctx is None else ctx.expert_axis)
        return net

    # ------------------------------------------------------- checkpointing

    def checkpoint_manager(self, directory: str, **kwargs):
        """A `CheckpointManager` bound to THIS wrapper's mesh and axis
        roles: saves shard per-device over the mesh, restores elastically
        onto it — including a checkpoint written by a different mesh shape
        (the elastic-resume path: save on 8 chips, resume on 4, or on CPU).
        """
        from deeplearning4j_tpu.checkpoint import CheckpointManager

        return CheckpointManager(directory, context=self.context, **kwargs)

    def save_checkpoint(self, directory: str, step=None) -> str:
        """Committed sharded checkpoint of the wrapped net (synchronous;
        use `checkpoint_manager()` for async saves + retention)."""
        return self.checkpoint_manager(directory, keep_last=0,
                                       async_save=False).save(self.net, step)

    def restore_checkpoint(self, directory: str, step=None):
        """Restore the latest (or named) committed step INTO the wrapped
        net, placed per this wrapper's mesh, whatever shape saved it."""
        ctx = self.context
        net = self.checkpoint_manager(directory).restore(step=step,
                                                         net=self.net)
        if ctx.expert_axis is not None:
            # The elastic restore places per param_shardings (replicated /
            # model-sharded); MoE expert tables additionally shard over the
            # expert axis — re-apply the full placement rules.
            mesh_mod.shard_params(net, self.mesh, model_axis=ctx.model_axis,
                                  expert_axis=ctx.expert_axis)
        self.net = net
        return net


def _pad_rows(a, pad: int, fill_last: bool = True):
    """Append `pad` rows: copies of the last row (features/labels — keeps
    values finite and typical) or zeros (masks — padded rows masked out)."""
    if a is None:
        return None
    a = np.asarray(a)
    tail = np.repeat(a[-1:], pad, axis=0) if fill_last else np.zeros(
        (pad,) + a.shape[1:], a.dtype)
    return np.concatenate([a, tail], axis=0)


def _full_labels_mask(labels: np.ndarray, lmask, sequence: bool = False):
    """An explicit all-ones labels mask matching the labels' batch/time shape
    (so the pad can zero the appended rows). `sequence` disambiguates 2-D
    integer labels: per-timestep [b, t] ids need a [b, t] mask, while
    integer-dtype one-hot [b, c] needs the per-example [b] mask — the
    label array alone can't tell them apart, so the caller decides from
    the net's output-layer type."""
    if lmask is not None:
        return np.asarray(lmask)
    if (labels.ndim == 2 and sequence
            and np.issubdtype(labels.dtype, np.integer)):
        shape = labels.shape  # sparse [b, t] class ids: per-timestep mask
    else:
        shape = (labels.shape[0],) if labels.ndim == 2 else labels.shape[:2]
    return np.ones(shape, np.float32)
