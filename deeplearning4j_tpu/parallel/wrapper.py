"""ParallelWrapper: data-parallel training over a device mesh.

API-level equivalent of the reference's
`deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java` — but where
the reference spawns N replica threads, round-robins minibatches, barriers, and
averages parameters every `averagingFrequency` iterations (`:322,353,179`), here
the SAME jitted train step simply runs with the batch sharded over the mesh's
"data" axis: XLA GSPMD emits the gradient all-reduce over ICI inside the step.
There is no averaging frequency because gradients synchronize every step (the
k=1 case the reference can't afford over its transports), no trainer threads,
and no updater-state divergence to repair (`:198-225`).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.parallel import mesh as mesh_mod


class ParallelWrapper:
    """Data-parallel fit() driver (see module docstring).

    `workers`/`averaging_frequency`/`prefetch_buffer` are accepted for
    reference API parity; `workers` maps to the mesh size, averaging is
    per-step by construction.
    """

    def __init__(self, net, mesh=None, workers: Optional[int] = None,
                 averaging_frequency: int = 1, prefetch_buffer: int = 2,
                 report_score_after_averaging: bool = True):
        self.net = net
        if mesh is None:
            devices = jax.devices()[:workers] if workers else jax.devices()
            mesh = mesh_mod.create_mesh(devices=devices)
        self.mesh = mesh
        self.data_axis = mesh.axis_names[0]
        self.n_devices = int(np.prod(mesh.devices.shape))
        if not net._initialized:
            net.init()
        mesh_mod.shard_params(net, mesh)

    def _pad_dataset(self, ds: DataSet) -> DataSet:
        """Pad the batch dim up to a multiple of the mesh size (XLA needs the
        sharded dim divisible). Padded rows are masked out of the loss via a
        zeroed labels mask, so a ragged final batch trains identically to the
        unpadded batch (the loss normalizes by the unmasked count)."""
        x = np.asarray(ds.features)
        b = x.shape[0]
        rem = b % self.n_devices
        if rem == 0:
            return ds
        pad = self.n_devices - rem

        def pad_rows(a, fill_last=True):
            if a is None:
                return None
            a = np.asarray(a)
            tail = np.repeat(a[-1:], pad, axis=0) if fill_last else np.zeros(
                (pad,) + a.shape[1:], a.dtype)
            return np.concatenate([a, tail], axis=0)

        labels = pad_rows(None if ds.labels is None else np.asarray(ds.labels))
        lmask = ds.labels_mask
        if labels is not None:
            if lmask is None:
                lmask_shape = (b,) if labels.ndim == 2 else (b, labels.shape[1])
                lmask = np.ones(lmask_shape, x.dtype)
            lmask = pad_rows(lmask, fill_last=False)  # zeros on padded rows
        return DataSet(
            pad_rows(x),
            labels,
            pad_rows(ds.features_mask, fill_last=False),
            lmask,
        )

    def _shard(self, a):
        if a is None:
            return None
        return jax.device_put(
            a, mesh_mod.data_sharding(self.mesh, np.ndim(a), self.data_axis)
        )

    def fit(self, iterator):
        """One pass over the iterator, each batch sharded across the mesh."""
        net = self.net
        if hasattr(iterator, "reset"):
            try:
                iterator.reset()
            except Exception:
                pass
        if isinstance(iterator, DataSet):
            iterator = [iterator]
        for ds in iterator:
            padded = self._pad_dataset(ds)
            sharded = DataSet(
                self._shard(np.asarray(padded.features)),
                self._shard(None if padded.labels is None else np.asarray(padded.labels)),
                self._shard(padded.features_mask),
                self._shard(padded.labels_mask),
            )
            net._fit_one(sharded)
        return net
