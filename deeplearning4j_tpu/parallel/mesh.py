"""Device mesh construction and sharding rules.

TPU-native replacement for the reference's three parameter-averaging
transports (SURVEY.md §2.3/§5): in-process `ParallelWrapper`
(`parallelism/ParallelWrapper.java:322`), Spark `ParameterAveragingTrainingMaster`,
and the Aeron parameter server. Here a single `jax.sharding.Mesh` + sharding
annotations make XLA emit per-step gradient all-reduce over ICI inside the
jitted train step — gradient (not parameter) averaging every step, which
strictly dominates the reference's every-k-iterations averaging.

Axes:
- "data": batch-dim data parallelism (the reference's only parallelism mode);
- "model": tensor parallelism over large weight matrices' output dim
  (no reference equivalent — the TPU-first extension).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import observability as _obs

#: Every ≥`min_shard_size` 2-D leaf the sharding rules left fully
#: replicated. A big matrix silently falling through the divisibility
#: gates (odd head count, misaligned vocab) costs full-copy HBM on every
#: chip — this counter makes that visible on /metrics instead of only in
#: an OOM three layers later. Incremented by `shard_params`; use
#: `describe_shardings` to see WHICH leaves.
M_REPLICATED_LEAVES = _obs.metrics.counter(
    "dl4j_params_replicated_leaves",
    "Large (>=min_shard_size) 2-D param leaves left fully replicated by "
    "param_shardings rules")


def create_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = ("data",),
    devices=None,
) -> Mesh:
    """Build a mesh over the available devices. Default: 1-D data-parallel
    mesh over all devices."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def local_mesh(shape: Optional[Tuple[int, ...]] = None,
               axis_names: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over THIS process's addressable devices only — the elastic
    trainer's per-worker mesh: each surviving worker trains on its local
    slice and synchronizes through the host-side coordinator, so the mesh
    never spans processes and a host loss never invalidates it."""
    return create_mesh(shape, axis_names=axis_names,
                       devices=jax.local_devices())


def data_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) axis; replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def superbatch_sharding(mesh: Mesh, ndim: int,
                        axis: str = "data") -> NamedSharding:
    """Sharding for a `[K, B, ...]` stacked superstep block: the batch axis
    (dim 1) shards over `axis`, the K step axis and feature dims replicate —
    each scan iteration then sees the same per-device batch split that
    `data_sharding` gives a single dispatched batch."""
    return NamedSharding(mesh, P(None, axis, *([None] * (ndim - 2))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def own_on_device(x):
    """An XLA-owned copy of an already-placed array (sharding preserved).

    `device_put` / `make_array_from_callback` zero-copy suitably-aligned
    host numpy buffers on the CPU backend, so a leaf placed from a
    TRANSIENT numpy array (a checkpoint-restore scratch buffer, the
    elastic averaging result) can end up aliasing memory the host
    allocator reclaims once the numpy object dies. That alias is harmless
    until the train step DONATES the leaf: XLA then reuses the aliased
    allocation in place for the updated parameter, and the live training
    state is sitting in freed host memory — the next unrelated host
    allocation silently stomps the weights. (Observed on CPU CI as
    elastic restore -> fit -> params corrupted some reads later; small
    leaves survived because sub-alignment-threshold arrays are copied,
    not aliased.) An eager on-device copy's output buffer comes from the
    XLA pool, decoupling the leaf from whatever host memory placed it.
    Use at every host->device boundary that feeds donated training state.
    """
    import jax.numpy as jnp

    return jnp.copy(x)


def batch_shardings(mesh: Mesh, tree, axis: str = "data"):
    """Sharding pytree for a batch structure: leading dim on `axis`."""
    return jax.tree_util.tree_map(
        lambda a: data_sharding(mesh, np.ndim(a), axis) if a is not None else None,
        tree,
        is_leaf=lambda a: a is None or hasattr(a, "ndim"),
    )


def _layer_confs(net) -> Dict[str, object]:
    """Param-tree top-level key -> layer conf, for either engine (layer key
    for MultiLayerNetwork, vertex name for ComputationGraph)."""
    found: Dict[str, object] = {}
    layers = getattr(net, "layers", None)
    if layers is not None:
        for lk, layer in zip(net.layer_keys, layers):
            found[lk] = layer
    for name, v in (getattr(net, "layer_vertices", None) or {}).items():
        found[name] = v.layer
    return found


#: Layer conf class names whose params stay replicated on purpose: small
#: per-feature vectors (norms) and token tables (embeddings — the decode
#:  path gathers one row per token, so splitting the vocab dim buys an
#: all-gather per step for ~nothing at serving batch sizes).
_REPLICATED_LAYER_TYPES = frozenset({
    "EmbeddingLayer", "BatchNormalization", "LocalResponseNormalization",
    "ActivationLayer", "DropoutLayer",
})


def _layer_param_specs(conf, axis_size: int,
                       model_axis: str) -> Optional[Dict[str, P]]:
    """Megatron-style per-param PartitionSpecs for one layer conf, or None
    when this layer type has no head-aware rule (caller falls back to the
    generic divisibility rule). A returned dict may still map a param to
    P() — that's an INTENTIONAL replication, not a fallback."""
    kind = type(conf).__name__
    if kind == "SelfAttentionLayer":
        # Head-aligned: column-splitting Wq/Wk/Wv's last dim by the axis
        # size keeps whole heads per shard only when n_heads divides, and
        # the attention kernel reshapes to [B, T, H, Dh] — a non-aligned
        # split would slice through a head. Wo is row-parallel (its input
        # is the head-sharded concat); XLA all-reduces the partial sums.
        if getattr(conf, "n_heads", 0) % axis_size:
            return None
        return {
            "Wq": P(None, model_axis), "qB": P(model_axis),
            "Wk": P(None, model_axis),
            "Wv": P(None, model_axis), "vB": P(model_axis),
            "Wo": P(model_axis, None), "oB": P(),
        }
    if kind in _REPLICATED_LAYER_TYPES:
        return {pn: P() for pn in conf.param_shapes()}
    if kind == "DenseLayer":
        n_in = getattr(conf, "n_in", 0)
        n_out = getattr(conf, "n_out", 0)
        if n_out >= n_in and n_out % axis_size == 0:
            # Expanding matmul (an MLP up-projection): column-parallel,
            # bias shards with the output features.
            return {"W": P(None, model_axis), "b": P(model_axis)}
        if n_in % axis_size == 0:
            # Contracting matmul (MLP down-projection): row-parallel over
            # the already-sharded input features; the bias is added after
            # the all-reduce, so it replicates.
            return {"W": P(model_axis, None), "b": P()}
        return None
    return None


def param_shardings(params, mesh: Mesh, model_axis: Optional[str] = None,
                    min_shard_size: int = 2048, net=None):
    """Sharding pytree for params: replicated by default; with `model_axis`,
    2-D weight matrices whose output dim divides the axis size (and is big
    enough to be worth sharding) split along their last dim (Megatron-style
    column parallel — XLA inserts the matching collectives).

    With `net`, the rules become layer-aware: attention QKV/output
    projections partition on heads (column/row-parallel, gated on
    `n_heads % axis_size == 0`), DenseLayer matmuls split column-wise when
    expanding and row-wise when contracting, and embeddings/norms stay
    replicated — the layout PERF.md §28 documents. Layers without a
    specific rule fall back to the generic last-dim divisibility rule."""
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(model_axis, 1)
    by_layer: Dict[str, Dict[str, P]] = {}
    if net is not None and model_axis is not None and axis_size > 1:
        for key, conf in _layer_confs(net).items():
            specs = _layer_param_specs(conf, axis_size, model_axis)
            if specs is not None:
                by_layer[key] = specs

    def generic(a):
        if (
            model_axis is not None
            and axis_size > 1
            and hasattr(a, "ndim")
            and a.ndim >= 2
            and a.shape[-1] % axis_size == 0
            and int(np.prod(a.shape)) >= min_shard_size
        ):
            return NamedSharding(mesh, P(*([None] * (a.ndim - 1)), model_axis))
        return NamedSharding(mesh, P())

    def rule(path, a):
        for i, k in enumerate(path):
            specs = by_layer.get(getattr(k, "key", None))
            if specs is None:
                continue
            # Updater state mirrors the param dict, so the param name is
            # somewhere below the layer key even when slots nest deeper.
            for k2 in path[i + 1:]:
                spec = specs.get(getattr(k2, "key", None))
                if spec is not None:
                    return NamedSharding(mesh, spec)
            break
        return generic(a)

    return jax.tree_util.tree_map_with_path(rule, params)


def describe_shardings(net, mesh: Mesh, model_axis: Optional[str] = None,
                       min_shard_size: int = 2048) -> List[dict]:
    """Per-leaf layout report for `shard_params(net, mesh, ...)` — what
    WOULD be placed where. Each row: ``{path, shape, bytes, spec,
    replicated, large_replicated}``; `large_replicated` marks the leaves
    `dl4j_params_replicated_leaves` counts (≥ min_shard_size elements,
    ndim ≥ 2, fully replicated) — the "is 90% of my HBM secretly on every
    chip" question answered in one call."""
    ps = param_shardings(net.params_tree, mesh, model_axis,
                         min_shard_size=min_shard_size, net=net)
    rows: List[dict] = []
    flat, _ = jax.tree_util.tree_flatten_with_path(net.params_tree)
    flat_s = jax.tree_util.tree_leaves(
        ps, is_leaf=lambda s: isinstance(s, NamedSharding))
    for (path, a), s in zip(flat, flat_s):
        spec = s.spec if isinstance(s, NamedSharding) else P()
        replicated = all(dim is None for dim in spec)
        rows.append({
            "path": jax.tree_util.keystr(path),
            "shape": tuple(getattr(a, "shape", ())),
            "bytes": int(getattr(a, "nbytes", 0)),
            "spec": str(spec),
            "replicated": replicated,
            "large_replicated": bool(
                replicated and getattr(a, "ndim", 0) >= 2
                and int(np.prod(getattr(a, "shape", (0,)))) >= min_shard_size),
        })
    return rows


def axis_sharding(mesh: Mesh, ndim: int, dim: int,
                  axis: Optional[str]) -> NamedSharding:
    """Partition one dimension over `axis`, replicate the rest (the
    single construction seam layer/stepper code goes through — tpulint
    JX020 keeps NamedSharding construction inside parallel/)."""
    spec = [None] * ndim
    if axis is not None:
        spec[dim] = axis
    return NamedSharding(mesh, P(*spec))


def kv_page_sharding(mesh: Mesh, ndim: int,
                     model_axis: Optional[str]) -> NamedSharding:
    """Paged KV storage `[pages, page_size, H, Dh]`: partition the head
    dim (2) over the model axis — the same split the attention QKV
    column-parallel rules give q/k/v, so the paged scatter + decode
    attention run shard-local with zero KV collectives. Page tables,
    refcounts and cursors stay replicated/host-side."""
    return axis_sharding(mesh, ndim, 2, model_axis)


def _moe_layers(net) -> Dict[str, object]:
    """Param-tree keys of MoELayer configs in either engine (layer key for
    MultiLayerNetwork, vertex name for ComputationGraph)."""
    found: Dict[str, object] = {}
    layers = getattr(net, "layers", None)
    if layers is not None:
        for lk, layer in zip(net.layer_keys, layers):
            if type(layer).__name__ == "MoELayer":
                found[lk] = layer
    for name, v in (getattr(net, "layer_vertices", None) or {}).items():
        if type(v.layer).__name__ == "MoELayer":
            found[name] = v.layer
    return found


def shard_params(net, mesh: Mesh, model_axis: Optional[str] = None,
                 expert_axis: Optional[str] = None, put=None):
    """Place a network's params/opt_state/state on the mesh in-place.

    `put(leaf, sharding)` is the placement primitive: `jax.device_put` by
    default (single-process — all mesh devices addressable); multi-process
    callers pass `parallel/distributed.py`'s global-array builder. One
    routine, one set of sharding rules for both worlds.

    With `expert_axis`, every MoELayer's per-expert tables (leading [E]
    axis) shard over that axis — the expert-parallel placement
    `nn/layers/moe.py`'s sharding constraints then keep through the step."""
    raw_put = jax.device_put if put is None else put

    def put(a, s):
        placed = raw_put(a, s)
        if isinstance(a, np.ndarray):
            # Host-sourced leaf (elastic averaging write-back, host-side
            # restores): the placement may zero-copy the caller's numpy
            # buffer, which the donated train step must never alias — see
            # `own_on_device`. Device-sourced leaves skip the copy (the
            # common ctor path re-places arrays XLA already owns).
            placed = own_on_device(placed)
        return placed

    ps = param_shardings(net.params_tree, mesh, model_axis, net=net)
    for row in describe_shardings(net, mesh, model_axis):
        if row["large_replicated"]:
            M_REPLICATED_LEAVES.inc()
    moe = _moe_layers(net) if expert_axis in mesh.shape else {}
    for lk, layer in moe.items():
        for pn in ("w1", "b_1", "w2", "b_2"):
            a = net.params_tree[lk][pn]
            ps[lk][pn] = NamedSharding(
                mesh, P(expert_axis, *([None] * (a.ndim - 1))))
    net.params_tree = jax.tree_util.tree_map(put, net.params_tree, ps)
    if net.opt_state is not None:
        os_shard = param_shardings(net.opt_state, mesh, model_axis, net=net)
        expert_param_names = {"w1", "b_1", "w2", "b_2"}
        for lk in moe:
            # Updater state mirrors the param dict (tree_map(zeros_like)),
            # so the PATH carries the param name — shard by name, exactly
            # like the params branch above (a shape heuristic would
            # mis-shard gate_w state when n_in == n_experts).
            flat, treedef = jax.tree_util.tree_flatten_with_path(
                net.opt_state[lk])
            flat_s = jax.tree_util.tree_leaves(os_shard[lk])
            new_s = []
            for (path, a), s in zip(flat, flat_s):
                names = {getattr(k, "key", None) for k in path}
                if names & expert_param_names and hasattr(a, "ndim"):
                    s = NamedSharding(
                        mesh, P(expert_axis, *([None] * (a.ndim - 1))))
                new_s.append(s)
            os_shard[lk] = jax.tree_util.tree_unflatten(treedef, new_s)
        net.opt_state = jax.tree_util.tree_map(
            lambda a, s: put(a, s) if hasattr(a, "shape") else a,
            net.opt_state, os_shard)
    if net.state:
        repl = NamedSharding(mesh, P())
        net.state = jax.tree_util.tree_map(lambda a: put(a, repl), net.state)
    return net
