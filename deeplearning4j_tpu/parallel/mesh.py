"""Device mesh construction and sharding rules.

TPU-native replacement for the reference's three parameter-averaging
transports (SURVEY.md §2.3/§5): in-process `ParallelWrapper`
(`parallelism/ParallelWrapper.java:322`), Spark `ParameterAveragingTrainingMaster`,
and the Aeron parameter server. Here a single `jax.sharding.Mesh` + sharding
annotations make XLA emit per-step gradient all-reduce over ICI inside the
jitted train step — gradient (not parameter) averaging every step, which
strictly dominates the reference's every-k-iterations averaging.

Axes:
- "data": batch-dim data parallelism (the reference's only parallelism mode);
- "model": tensor parallelism over large weight matrices' output dim
  (no reference equivalent — the TPU-first extension).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = ("data",),
    devices=None,
) -> Mesh:
    """Build a mesh over the available devices. Default: 1-D data-parallel
    mesh over all devices."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    arr = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) axis; replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_shardings(mesh: Mesh, tree, axis: str = "data"):
    """Sharding pytree for a batch structure: leading dim on `axis`."""
    return jax.tree_util.tree_map(
        lambda a: data_sharding(mesh, np.ndim(a), axis) if a is not None else None,
        tree,
        is_leaf=lambda a: a is None or hasattr(a, "ndim"),
    )


def param_shardings(params, mesh: Mesh, model_axis: Optional[str] = None,
                    min_shard_size: int = 2048):
    """Sharding pytree for params: replicated by default; with `model_axis`,
    2-D weight matrices whose output dim divides the axis size (and is big
    enough to be worth sharding) split along their last dim (Megatron-style
    column parallel — XLA inserts the matching collectives)."""
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get(model_axis, 1)

    def rule(a):
        if (
            model_axis is not None
            and axis_size > 1
            and hasattr(a, "ndim")
            and a.ndim >= 2
            and a.shape[-1] % axis_size == 0
            and int(np.prod(a.shape)) >= min_shard_size
        ):
            return NamedSharding(mesh, P(*([None] * (a.ndim - 1)), model_axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def shard_params(net, mesh: Mesh, model_axis: Optional[str] = None, put=None):
    """Place a network's params/opt_state/state on the mesh in-place.

    `put(leaf, sharding)` is the placement primitive: `jax.device_put` by
    default (single-process — all mesh devices addressable); multi-process
    callers pass `parallel/distributed.py`'s global-array builder. One
    routine, one set of sharding rules for both worlds."""
    if put is None:
        put = jax.device_put
    ps = param_shardings(net.params_tree, mesh, model_axis)
    net.params_tree = jax.tree_util.tree_map(put, net.params_tree, ps)
    if net.opt_state is not None:
        os_shard = param_shardings(net.opt_state, mesh, model_axis)
        net.opt_state = jax.tree_util.tree_map(
            lambda a, s: put(a, s) if hasattr(a, "shape") else a,
            net.opt_state, os_shard)
    if net.state:
        repl = NamedSharding(mesh, P())
        net.state = jax.tree_util.tree_map(lambda a: put(a, repl), net.state)
    return net
