"""Expert parallelism: a mixture-of-experts FFN sharded over a mesh axis.

The reference has no MoE (it predates the architecture); this completes
the framework's parallelism matrix (dp/tp/sp/pp/ep — the driver's
multi-chip dryrun exercises all five). The design is the Mesh-TensorFlow /
GShard einsum formulation, TPU-first: routing builds a dense
[tokens, experts, capacity] dispatch tensor, the per-expert FFN runs as
batched einsums over a [E, C, D] tensor whose EXPERT axis is sharded over
the mesh — XLA's GSPMD inserts the all-to-alls that move each token to its
expert's device and back; nothing is hand-scheduled. Over-capacity tokens
are dropped (output zero) exactly as in GShard; capacity_factor sizes the
buffer.

Everything is jit-compatible (static shapes, no data-dependent control
flow) and differentiable — the router's combine weights carry the gradient
through the top-k selection.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(rng_key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32):
    """Per-expert two-layer FFN + router. Returns a params dict with every
    expert table carrying a leading [E, ...] axis (shard it over the
    expert mesh axis with `shard_moe_params`)."""
    k1, k2, k3 = jax.random.split(rng_key, 3)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_hidden) ** 0.5
    return {
        "gate_w": jax.random.normal(k1, (d_model, n_experts), dtype) * s1,
        "w1": jax.random.normal(k2, (n_experts, d_model, d_hidden),
                                dtype) * s1,
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "w2": jax.random.normal(k3, (n_experts, d_hidden, d_model),
                                dtype) * s2,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def shard_moe_params(params, mesh: Mesh, expert_axis: str = "expert"):
    """Place each per-expert table with its leading axis on `expert_axis`;
    the router replicates."""
    def put(name, a):
        if name == "gate_w":
            return jax.device_put(a, NamedSharding(mesh, P()))
        spec = P(expert_axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))
    return {k: put(k, v) for k, v in params.items()}


def moe_ffn(params, x, *, capacity_factor: float = 1.25,
            mesh: Optional[Mesh] = None, expert_axis: str = "expert"):
    """Top-1 routed MoE FFN. x: [N, D] tokens -> [N, D].

    With `mesh`, the [E, C, D] expert batch is sharding-constrained to the
    expert axis so GSPMD all-to-alls tokens to their expert's device; the
    math is identical with or without a mesh (exact-equivalence tested)."""
    N, D = x.shape
    E = params["gate_w"].shape[1]
    C = max(1, int(capacity_factor * N / E))
    # Accumulate in at least fp32 (fp64 stays fp64 so x64 tests are exact).
    acc = jnp.promote_types(x.dtype, jnp.float32)

    logits = x @ params["gate_w"]                       # [N, E]
    probs = jax.nn.softmax(logits.astype(acc), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)             # [N]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, E, dtype=acc)           # [N, E]
    # Position of each token within its expert's capacity buffer.
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # [N, E]
    pos_tok = jnp.sum(pos, axis=-1)                             # [N]
    keep = pos_tok < C
    # int cast for one_hot (it rejects float indices going forward);
    # over-capacity tokens are already zeroed by the keep mask.
    dispatch = (onehot * keep[:, None])[:, :, None] * jax.nn.one_hot(
        pos_tok.astype(jnp.int32), C, dtype=acc)[:, None, :]    # [N, E, C]
    combine = dispatch * gate[:, None, None]

    expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                           x.astype(acc))                       # [E, C, D]
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(expert_axis, None, None)))
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in,
                               params["w1"].astype(acc))
                    + params["b1"][:, None, :])
    out_e = (jnp.einsum("ech,ehd->ecd", h,
                        params["w2"].astype(acc))
             + params["b2"][:, None, :])
    if mesh is not None:
        out_e = jax.lax.with_sharding_constraint(
            out_e, NamedSharding(mesh, P(expert_axis, None, None)))
    y = jnp.einsum("nec,ecd->nd", combine, out_e)
    return y.astype(x.dtype)


def dense_moe_reference(params, x, *, capacity_factor: float = 1.25):
    """Per-token reference: run every token through ITS expert's FFN
    directly (same capacity-dropping rule), for equivalence tests."""
    import numpy as np

    x64 = np.asarray(x, np.float64)
    gate_w = np.asarray(params["gate_w"], np.float64)
    N, D = x64.shape
    E = gate_w.shape[1]
    C = max(1, int(capacity_factor * N / E))
    logits = x64 @ gate_w
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs = e / e.sum(axis=1, keepdims=True)
    idx = probs.argmax(axis=1)
    out = np.zeros_like(x64)
    counts = {j: 0 for j in range(E)}
    for n in range(N):
        j = int(idx[n])
        if counts[j] >= C:
            continue  # dropped
        counts[j] += 1
        w1 = np.asarray(params["w1"][j], np.float64)
        b1 = np.asarray(params["b1"][j], np.float64)
        w2 = np.asarray(params["w2"][j], np.float64)
        b2 = np.asarray(params["b2"][j], np.float64)
        h = np.maximum(x64[n] @ w1 + b1, 0.0)
        out[n] = (h @ w2 + b2) * probs[n, j]
    return out
