"""Expert parallelism: a mixture-of-experts FFN sharded over a mesh axis.

The reference has no MoE (it predates the architecture); this completes
the framework's parallelism matrix (dp/tp/sp/pp/ep — the driver's
multi-chip dryrun exercises all five). The design is the Mesh-TensorFlow /
GShard einsum formulation, TPU-first: routing builds a dense
[tokens, experts, capacity] dispatch tensor, the per-expert FFN runs as
batched einsums over a [E, C, D] tensor whose EXPERT axis is sharded over
the mesh — XLA's GSPMD inserts the all-to-alls that move each token to its
expert's device and back; nothing is hand-scheduled. Over-capacity tokens
are dropped (output zero) exactly as in GShard; capacity_factor sizes the
buffer.

Everything is jit-compatible (static shapes, no data-dependent control
flow) and differentiable — the router's combine weights carry the gradient
through the top-k selection.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(rng_key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32):
    """Per-expert two-layer FFN + router. Returns a params dict with every
    expert table carrying a leading [E, ...] axis (shard it over the
    expert mesh axis with `shard_moe_params`)."""
    k1, k2, k3 = jax.random.split(rng_key, 3)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_hidden) ** 0.5
    return {
        "gate_w": jax.random.normal(k1, (d_model, n_experts), dtype) * s1,
        "w1": jax.random.normal(k2, (n_experts, d_model, d_hidden),
                                dtype) * s1,
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "w2": jax.random.normal(k3, (n_experts, d_hidden, d_model),
                                dtype) * s2,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def shard_moe_params(params, mesh: Mesh, expert_axis: str = "expert"):
    """Place each per-expert table with its leading axis on `expert_axis`;
    the router replicates."""
    def put(name, a):
        if name == "gate_w":
            return jax.device_put(a, NamedSharding(mesh, P()))
        spec = P(expert_axis, *([None] * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(mesh, spec))
    return {k: put(k, v) for k, v in params.items()}


def _capacity_dispatch(onehot, C, acc, *, base_count=None):
    """[N, E] assignment one-hot -> [N, E, C] dispatch tensor.

    Position of each token within its expert's capacity buffer is its rank
    among same-expert tokens (first-come order); `base_count` [E] offsets the
    ranks (top-2 second choices queue behind every first choice, GShard
    semantics)."""
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # [N, E]
    if base_count is not None:
        pos = pos + base_count[None, :] * onehot
    pos_tok = jnp.sum(pos, axis=-1)                             # [N]
    keep = pos_tok < C
    # int cast for one_hot (it rejects float indices going forward);
    # over-capacity tokens are already zeroed by the keep mask.
    return (onehot * keep[:, None])[:, :, None] * jax.nn.one_hot(
        pos_tok.astype(jnp.int32), C, dtype=acc)[:, None, :]    # [N, E, C]


def moe_ffn(params, x, *, capacity_factor: float = 1.25,
            mesh: Optional[Mesh] = None, expert_axis: str = "expert",
            top_k: int = 1, rng=None, jitter_eps: float = 0.0,
            return_aux: bool = False):
    """Top-1 / top-2 routed MoE FFN. x: [N, D] tokens -> [N, D_out].

    GShard routing semantics (the module's design donor):
    - `top_k=2`: each token is dispatched to its two highest-probability
      experts; the two gate values are renormalized to sum to 1; second
      choices queue behind ALL first choices in each expert's capacity
      buffer, so under pressure first choices win buffer slots.
    - load-balance auxiliary loss `E * sum_e(fraction_tokens_e * mean_prob_e)`
      over FIRST-choice assignments (GShard eq. (4) / Switch Transformer
      eq. (4)); minimized at 1.0 for a perfectly uniform router. Returned
      when `return_aux=True` as `(y, aux_loss)`; callers scale it into
      their training loss.
    - router jitter: with `rng` and `jitter_eps > 0`, router inputs are
      multiplied by uniform noise in [1-eps, 1+eps] (training-time
      exploration; pass rng=None at eval).

    With `mesh`, the [E, C, D] expert batch is sharding-constrained to the
    expert axis so GSPMD all-to-alls tokens to their expert's device; the
    math is identical with or without a mesh (exact-equivalence tested)."""
    N, D = x.shape
    E = params["gate_w"].shape[1]
    C = max(1, int(capacity_factor * top_k * N / E))
    # Accumulate in at least fp32 (fp64 stays fp64 so x64 tests are exact).
    acc = jnp.promote_types(x.dtype, jnp.float32)

    x_router = x.astype(acc)
    if rng is not None and jitter_eps > 0.0:
        x_router = x_router * jax.random.uniform(
            rng, x.shape, acc, 1.0 - jitter_eps, 1.0 + jitter_eps)
    logits = x_router @ params["gate_w"].astype(acc)            # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(logits, axis=-1)            # [N] first choice
    gate1 = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
    onehot1 = jax.nn.one_hot(expert_idx, E, dtype=acc)          # [N, E]

    # Load-balance aux loss from FIRST-choice fractions (GShard eq. 4).
    frac_tokens = jnp.mean(onehot1, axis=0)                     # [E]
    mean_prob = jnp.mean(probs, axis=0)                         # [E]
    aux_loss = E * jnp.sum(frac_tokens * mean_prob)

    if top_k == 1:
        dispatch = _capacity_dispatch(onehot1, C, acc)
        combine = dispatch * gate1[:, None, None]
    elif top_k == 2:
        # Second choice = highest remaining LOGIT (not prob): a saturated
        # softmax zeroes the non-first-choice probs exactly, and an argmax
        # over those zeros would re-select the first-choice expert.
        logits2 = jnp.where(onehot1 > 0, -jnp.inf, logits)
        idx2 = jnp.argmax(logits2, axis=-1)
        gate2 = jnp.take_along_axis(probs, idx2[:, None], axis=1)[:, 0]
        onehot2 = jax.nn.one_hot(idx2, E, dtype=acc)
        denom = gate1 + gate2 + 1e-9
        g1, g2 = gate1 / denom, gate2 / denom
        d1 = _capacity_dispatch(onehot1, C, acc)
        count1 = jnp.sum(onehot1, axis=0)                       # [E]
        d2 = _capacity_dispatch(onehot2, C, acc, base_count=count1)
        dispatch = d1 + d2
        combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]
    else:
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")

    expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                           x.astype(acc))                       # [E, C, D]
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(expert_axis, None, None)))
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", expert_in,
                               params["w1"].astype(acc))
                    + params["b1"][:, None, :])
    out_e = (jnp.einsum("ech,ehd->ecd", h,
                        params["w2"].astype(acc))
             + params["b2"][:, None, :])
    if mesh is not None:
        out_e = jax.lax.with_sharding_constraint(
            out_e, NamedSharding(mesh, P(expert_axis, None, None)))
    y = jnp.einsum("nec,ecd->nd", combine, out_e)
    y = y.astype(x.dtype)
    if return_aux:
        return y, aux_loss
    return y


def dense_moe_reference(params, x, *, capacity_factor: float = 1.25,
                        top_k: int = 1):
    """Per-token reference: run every token through ITS expert(s)' FFN
    directly (same capacity/queueing rules as `moe_ffn`), for equivalence
    tests. Second choices queue behind every first choice (GShard)."""
    import numpy as np

    x64 = np.asarray(x, np.float64)
    gate_w = np.asarray(params["gate_w"], np.float64)
    N, D = x64.shape
    E = gate_w.shape[1]
    C = max(1, int(capacity_factor * top_k * N / E))
    logits = x64 @ gate_w
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs = e / e.sum(axis=1, keepdims=True)
    idx = logits.argmax(axis=1)
    d_out = np.asarray(params["w2"]).shape[-1]
    out = np.zeros((N, d_out), np.float64)

    def expert_out(j, v):
        h = np.maximum(v @ np.asarray(params["w1"][j], np.float64)
                       + np.asarray(params["b1"][j], np.float64), 0.0)
        return h @ np.asarray(params["w2"][j], np.float64) + np.asarray(
            params["b2"][j], np.float64)

    counts = {j: 0 for j in range(E)}
    if top_k == 1:
        for n in range(N):
            j = int(idx[n])
            if counts[j] >= C:
                continue  # dropped
            counts[j] += 1
            out[n] = expert_out(j, x64[n]) * probs[n, j]
        return out
    # top-2: first choices claim buffer slots for ALL tokens first; second
    # choice is the highest remaining LOGIT (matches moe_ffn's tie-robust
    # selection under saturated softmax).
    logits2 = logits.copy()
    logits2[np.arange(N), idx] = -np.inf
    idx2 = logits2.argmax(axis=1)
    g1 = probs[np.arange(N), idx]
    g2 = probs[np.arange(N), idx2]
    denom = g1 + g2 + 1e-9
    for n in range(N):
        j = int(idx[n])
        if counts[j] < C:
            counts[j] += 1
            out[n] += expert_out(j, x64[n]) * (g1[n] / denom[n])
    for n in range(N):
        j = int(idx2[n])
        if counts[j] < C:
            counts[j] += 1
            out[n] += expert_out(j, x64[n]) * (g2[n] / denom[n])
    return out
