"""Mesh-sharded evaluation.

TPU-native replacement for the reference's distributed evaluation
(`dl4j-spark/.../impl/multilayer/evaluation/EvaluateFlatMapFunction.java` +
`IEvaluation.merge`): where Spark evaluates per-partition Evaluation objects
and tree-merges them, here each batch is data-sharded over the mesh and the
confusion-matrix / top-N counts are computed IN-JIT on device — GSPMD
parallelizes the forward across the data axis and all that crosses the
host link per batch is a [C, C] count matrix and two scalars (instead of
the full [B, C] prediction array `MultiLayerNetwork.evaluate` fetches).

`Evaluation.merge()` remains the cross-process aggregation path (same as
the reference); this module removes the per-host bottleneck.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel import wrapper as wrapper_mod


@partial(jax.jit, static_argnums=(3, 4, 5))
def _batch_counts(out, y, lmask, num_classes, top_n, sparse=False):
    """Confusion counts + top-N correct + total for one batch, on device.

    out: [b, c] or [b, t, c]; y one-hot like out, or — with `sparse` —
    integer class ids [b]/[b, t]; lmask: [b]/[b, t] weights or None.
    Matches `Evaluation.eval` semantics: masked rows dropped, argmax
    decisions, top-N by the N largest predictions."""
    C = num_classes
    if out.ndim == 3:
        t_shape = out.shape[:2]
        w = (jnp.ones(t_shape) if lmask is None else lmask).reshape(-1)
        y = y.reshape(-1) if sparse else y.reshape(-1, C)
        out = out.reshape(-1, C)
    else:
        w = jnp.ones(out.shape[0]) if lmask is None else lmask.reshape(-1)
        if sparse:
            y = y.reshape(-1)
    # Host-path semantics (`Evaluation.eval`): any mask > 0 counts the row
    # fully — masks are keep/drop flags here, not fractional weights.
    w = (w > 0).astype(jnp.float64 if jax.config.jax_enable_x64
                       else jnp.float32)
    actual = y.astype(jnp.int32) if sparse else jnp.argmax(y, axis=-1)
    pred = jnp.argmax(out, axis=-1)
    conf = jax.ops.segment_sum(w, actual * C + pred,
                               num_segments=C * C).reshape(C, C)
    if top_n > 1:
        _, top = jax.lax.top_k(out, top_n)
        tn_correct = jnp.sum(w * jnp.any(top == actual[:, None], axis=-1))
    else:
        tn_correct = jnp.sum(w * (actual == pred))
    return conf, tn_correct, jnp.sum(w)


def _pad_to(a, target_rows):
    if a is None or a.shape[0] == target_rows:
        return a
    return wrapper_mod._pad_rows(np.asarray(a), target_rows - a.shape[0],
                                 fill_last=False)


def sharded_evaluate(net, iterator, mesh=None, top_n: int = 1,
                     num_classes: Optional[int] = None) -> Evaluation:
    """Evaluate `net` over `iterator` with every batch sharded across the
    mesh's data axis. Returns a standard `Evaluation` (merge-able across
    processes like the reference's `IEvaluation.merge`)."""
    if mesh is None:
        mesh = mesh_mod.create_mesh()
    if not net._initialized:
        net.init()
    mesh_mod.shard_params(net, mesh)
    n_dev = int(mesh.shape[mesh.axis_names[0]])

    out_fn = net._get_jit("output", train=False)
    is_graph = type(net).__name__ == "ComputationGraph"

    ev = Evaluation(top_n=top_n)
    if hasattr(iterator, "reset"):
        try:
            iterator.reset()
        except Exception:
            pass
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet

    if isinstance(iterator, (DataSet, MultiDataSet)):
        iterator = [iterator]
    for ds in iterator:
        if isinstance(ds, MultiDataSet):
            if len(ds.features) > 1 or len(ds.labels) > 1:
                raise ValueError(
                    "sharded_evaluate supports single-input/single-output "
                    f"graphs only (got {len(ds.features)} inputs, "
                    f"{len(ds.labels)} outputs); evaluate multi-IO graphs "
                    "with net.evaluate")
            feats, labels = ds.features[0], ds.labels[0]
            fmask = None if ds.features_masks is None else ds.features_masks[0]
            lmask = None if ds.labels_masks is None else ds.labels_masks[0]
        else:
            feats, labels = ds.features, ds.labels
            fmask, lmask = ds.features_mask, ds.labels_mask
        b = feats.shape[0]
        padded = -(-b // n_dev) * n_dev
        if padded != b:
            # Padded rows are excluded via a zeroed labels mask.
            if lmask is None:
                has_time = np.ndim(labels) == 3 or (
                    np.ndim(labels) == 2
                    and np.issubdtype(np.asarray(labels).dtype, np.integer))
                lmask = np.ones(np.shape(labels)[:2], "float32") \
                    if has_time else np.ones((b,), "float32")
            feats, labels = _pad_to(feats, padded), _pad_to(labels, padded)
            fmask, lmask = _pad_to(fmask, padded), _pad_to(lmask, padded)
        sh = lambda a: None if a is None else jax.device_put(
            np.asarray(a), mesh_mod.data_sharding(mesh, np.ndim(a)))
        x, y = sh(feats), jnp.asarray(np.asarray(labels))
        fm, lm = sh(fmask), None if lmask is None else jnp.asarray(np.asarray(lmask))
        if is_graph:
            outs, _ = out_fn(net.params_tree, net.state, [x],
                             None if fm is None else [fm], None)
            out = outs[0]
        else:
            out, _ = out_fn(net.params_tree, net.state, x, fm, None)
        sparse = (jnp.issubdtype(y.dtype, jnp.integer)
                  and y.ndim == out.ndim - 1)
        C = num_classes or ev.num_classes or int(
            out.shape[-1] if sparse else y.shape[-1])
        conf, tn_c, total = _batch_counts(out, y, lm, C, top_n, sparse)
        ev.add_counts(np.asarray(conf), float(tn_c), float(total))
    return ev
