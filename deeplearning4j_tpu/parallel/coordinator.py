"""Host-side cluster coordinator for elastic training.

The transport half of `parallel/elastic.py` — the role the reference
stack splits between the Spark driver (membership, averaging barriers;
`ParameterAveragingTrainingMaster.java`) and the Aeron parameter server
(parameter shipping). One small TCP service, JSON-line protocol, two
jobs:

1. **Membership, by generation.** Workers `join`; the live set at any
   moment is a *generation* (monotonic int). Heartbeats refresh a
   member's lease; a reaper evicts members whose lease lapsed
   (`lost_after`) and bumps the generation. Every blocked collective
   call observes the bump and returns ``regen`` — so a lost host turns
   into a clean, observable "cluster changed, re-form" signal on every
   survivor within one lease, never a hang.
2. **Step collectives.** `allreduce` (mean of equally-weighted host
   arrays — parameter averaging; accumulated in float64) and `barrier`,
   keyed by (generation, step, name). Results are cached per key, so a
   worker whose response packet was lost retries idempotently and gets
   the SAME mean (no double-counting: a re-contribution from the same
   worker replaces, never adds).

Why host-side TCP and not XLA collectives: the elastic path must keep
working while the device cluster is broken — that is its whole job — and
on CPU CI there is no cross-process XLA backend at all. The SPMD
transport (`DistributedTrainer`) remains the fast path on real pods;
`ElasticTrainer(sync="auto")` picks per platform.

Fault-injection hooks: `inject_hang(seconds)` makes the server accept
connections but delay every response until the hang elapses — clients
must survive via timeout + backoff-retry (`util/retry.py`), and the
reaper treats the hang window as leased time so the coordinator's own
outage never *causes* evictions.

Wire format: one JSON object per line, one request per connection.
Arrays travel as ``{shape, dtype, b64}`` (raw little-endian bytes,
base64) — fine for the parameter sizes this averaging tier targets;
giant models use the SPMD path where weights never leave the devices.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.observability import elastic as _ev
from deeplearning4j_tpu.observability import propagate as _prop
from deeplearning4j_tpu.util.retry import Backoff, RetryError


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


HEARTBEAT_S = _env_float("DL4J_TPU_ELASTIC_HEARTBEAT_S", 5.0)
LOST_AFTER_S = _env_float("DL4J_TPU_ELASTIC_LOST_AFTER_S", 3 * HEARTBEAT_S)
RPC_TIMEOUT_S = _env_float("DL4J_TPU_ELASTIC_RPC_TIMEOUT_S", 10.0)
BARRIER_TIMEOUT_S = _env_float("DL4J_TPU_ELASTIC_BARRIER_TIMEOUT_S", 60.0)
JOIN_GRACE_S = _env_float("DL4J_TPU_ELASTIC_JOIN_GRACE_S", 30.0)


# The coordinator's own exposition (satellite of the observability
# plane): fleet membership by role, lease-age distribution at heartbeat
# refresh, and the generation — the three numbers that tell an operator
# whether the cluster is stable without reading logs. Families are
# process-global; each Coordinator refreshes them via a scrape-time
# collector gated on its own liveness (the newest live coordinator wins,
# which is the common one-coordinator-per-process case).
_M_MEMBERS = _obs.metrics.gauge(
    "dl4j_coordinator_members",
    "Live coordinator members by declared role",
    label_names=("role",))
_M_LEASE_AGE = _obs.metrics.histogram(
    "dl4j_coordinator_lease_age_seconds",
    "Member lease age observed at each heartbeat refresh (a distribution "
    "creeping toward lost_after_s means heartbeats barely outrun the "
    "reaper)")
_M_GENERATION = _obs.metrics.counter(
    "dl4j_coordinator_generation",
    "Current membership generation (bumps on every join/leave/eviction)")


class ClusterChanged(Exception):
    """Membership changed under a blocked collective — re-join and
    recover (the elastic supervisor's restart trigger)."""


class CoordinatorError(RuntimeError):
    """The coordinator answered with an error document (an exception
    caught server-side in `_dispatch`). Usually transient — membership
    shifted under the op — so the elastic supervisor treats it as
    recoverable, same as `ClusterChanged`."""


def parse_address(address: str,
                  default_host: str = "127.0.0.1") -> tuple:
    """``host:port`` -> ``(host, port)``. A bare ``host`` (no colon)
    means port 0 — ephemeral when binding; when connecting, the socket
    layer reports it instead of a parse-time ValueError."""
    host, sep, port = address.rpartition(":")
    if not sep:
        return address or default_host, 0
    return host or default_host, int(port or 0)


# ------------------------------------------------------------- wire codecs

def encode_tree(tree: Dict[str, np.ndarray]) -> Dict[str, Any]:
    out = {}
    for k, a in tree.items():
        a = np.ascontiguousarray(a)
        out[k] = {"shape": list(a.shape), "dtype": a.dtype.str,
                  "b64": base64.b64encode(a.tobytes()).decode("ascii")}
    return out


def decode_tree(doc: Dict[str, Any]) -> Dict[str, np.ndarray]:
    out = {}
    for k, d in doc.items():
        a = np.frombuffer(base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"]))
        out[k] = a.reshape(d["shape"]).copy()
    return out


# ---------------------------------------------------------------- server


class Coordinator:
    """The in-process coordinator service. Start with `start()`; workers
    connect to `address`. All mutable state lives behind `_cond` (one
    Condition doubles as the lock and the wakeup channel for blocked
    collectives)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 lost_after_s: float = LOST_AFTER_S,
                 metrics_port: Optional[int] = 0):
        self._cond = threading.Condition()
        self._members: Dict[str, float] = {}  # worker_id -> last_seen
        self._roles: Dict[str, str] = {}      # worker_id -> declared role
        self._generation = 0
        self._hang_until = 0.0
        self._contribs: Dict[tuple, Dict[str, Dict[str, np.ndarray]]] = {}
        self._results: Dict[tuple, Dict[str, Any]] = {}
        self._barriers: Dict[tuple, set] = {}
        self._closed = False
        self.lost_after_s = float(lost_after_s)
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline()
                    if not line:
                        return
                    req = json.loads(line.decode("utf-8"))
                    resp = outer._dispatch(req)
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode("utf-8"))
                except (OSError, ValueError):
                    pass  # client went away / torn request: it will retry

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.address = "%s:%d" % self._server.server_address[:2]
        self._threads: List[threading.Thread] = []
        self._metrics_port = metrics_port
        self._metrics_server = None
        self.metrics_url: Optional[str] = None
        self._metric_roles: set = set()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Coordinator":
        t = threading.Thread(target=self._server.serve_forever,
                             name="dl4j-coordinator", daemon=True)
        t.start()
        r = threading.Thread(target=self._reap_loop,
                             name="dl4j-coordinator-reaper", daemon=True)
        r.start()
        self._threads = [t, r]
        _obs.metrics.register_collector(self._collect_metrics)
        if self._metrics_port is not None:
            self._start_metrics_http(self._metrics_port)
        return self

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._server.shutdown()
        self._server.server_close()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server.server_close()
            self._metrics_server = None

    def _collect_metrics(self, reg) -> None:
        """Scrape-time refresh of the coordinator families (registered
        from `start()`; exits fast once this coordinator is closed)."""
        if self._closed:
            return
        with self._cond:
            roles = [self._roles.get(w, "trainer") for w in self._members]
            gen = self._generation
        counts: Dict[str, int] = {}
        for r in roles:
            counts[r] = counts.get(r, 0) + 1
        # Zero out roles whose last member left, so a stale series never
        # reports a phantom member.
        for role in self._metric_roles | set(counts):
            _M_MEMBERS.labels(role=role).set(float(counts.get(role, 0)))
        self._metric_roles |= set(counts)
        _M_GENERATION.set(float(gen))

    def _start_metrics_http(self, port: int) -> None:
        """The coordinator's own HTTP exposition (`/metrics`,
        `/api/trace`): the JSON-line RPC port is not scrapeable by
        Prometheus or the federation aggregator, this is. The URL is
        advertised in every `status` response (`metrics_url`)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        class MetricsHandler(BaseHTTPRequestHandler):
            # Keep-alive (see serving/http.py): the aggregator holds one
            # persistent connection instead of a dial per poll.
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path == "/metrics":
                    q = parse_qs(url.query)
                    fmt = (q.get("format") or ["prometheus"])[0]
                    names = (q["names"][0].split(",") if q.get("names")
                             else None)
                    body, ctype = _obs.prometheus_payload(fmt, names=names)
                    self._send(body, ctype)
                elif url.path == "/api/trace":
                    q = parse_qs(url.query)
                    since = (int(q["since"][0]) if q.get("since")
                             else None)
                    self._send(
                        json.dumps(
                            _obs.tracer.export_chrome(since=since)
                        ).encode(),
                        "application/json")
                elif url.path == "/health":
                    self._send(b'{"status": "ok"}', "application/json")
                else:
                    self._send(b'{"error": "not found"}',
                               "application/json", 404)

        class MetricsServer(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        host = self._server.server_address[0]
        self._metrics_server = MetricsServer((host, int(port)),
                                             MetricsHandler)
        mhost, mport = self._metrics_server.server_address[:2]
        self.metrics_url = f"http://{mhost}:{mport}"
        threading.Thread(target=self._metrics_server.serve_forever,
                         name="dl4j-coordinator-metrics",
                         daemon=True).start()

    # ------------------------------------------------------------- faults

    def inject_hang(self, seconds: float) -> None:
        """Stop responding for `seconds` (connections accept, responses
        stall). The reaper credits the hang window to every member's
        lease — a coordinator outage must not masquerade as host loss."""
        with self._cond:
            self._hang_until = max(self._hang_until,
                                   time.monotonic() + float(seconds))

    # ------------------------------------------------------------ internals

    def _reap_loop(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                floor = self._hang_until  # hang time counts as leased
                dead = [w for w, seen in self._members.items()
                        if now - max(seen, floor) > self.lost_after_s]
                if dead:
                    for w in dead:
                        del self._members[w]
                        self._roles.pop(w, None)
                    self._bump_generation()
            for w in dead:
                _ev.record_event("host_lost", worker=w,
                                 lost_after_s=self.lost_after_s)
            time.sleep(min(0.1, self.lost_after_s / 4))

    def _bump_generation(self) -> None:
        """Advance the generation and purge collective state keyed to
        superseded generations — every waiter on those keys unblocks with
        ``regen`` and nobody will ever complete them, so keeping their
        contribution trees (parameter-sized!) and barrier sets would leak
        unboundedly across a long elastic run. Call with `_cond` held."""
        self._generation += 1
        gen = self._generation
        for d in (self._contribs, self._barriers):
            for key in [k for k in d if k[0] != gen]:
                d.pop(key)
        self._cond.notify_all()

    def _ranked(self) -> List[str]:
        return sorted(self._members)

    def _member_doc(self) -> Dict[str, Any]:
        return {"gen": self._generation, "members": self._ranked(),
                "world": len(self._members)}

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        # The hang gate: every op (status included — a hung coordinator
        # answers nothing) stalls until the injected outage elapses.
        while True:
            with self._cond:
                remaining = self._hang_until - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 0.05))
        op = req.get("op")
        # Remote-parent trace context: clients attach their thread-current
        # context as a `trace` field, so coordinator ops nest under the
        # caller's span in the federated timeline.
        tctx = _prop.parse(req.pop(_prop.TRACE_FIELD, None))
        fn = getattr(self, "_op_" + str(op), None)
        if fn is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        try:
            if tctx is not None:
                with _obs.tracer.span(f"coordinator.{op}",
                                      cat="coordinator", parent_ctx=tctx,
                                      worker=req.get("worker")):
                    return fn(req)
            return fn(req)
        except Exception as e:  # surface, don't kill the handler thread
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # ------------------------------------------------------------- ops

    def _op_join(self, req) -> Dict[str, Any]:
        """Add the worker; when `expected` is given, block until that many
        members are present (or `grace_s` runs out — the cluster then
        forms on whoever showed up, elastically). `role` tags the member
        for `status` consumers (trainer vs serving replica; a re-join with
        a new role updates it in place — the serving fleet drives its
        warming/draining lifecycle through exactly that)."""
        worker = str(req["worker"])
        expected = req.get("expected")
        grace = float(req.get("grace_s", JOIN_GRACE_S))
        deadline = time.monotonic() + grace
        with self._cond:
            if "role" in req and req["role"] is not None:
                self._roles[worker] = str(req["role"])
            else:
                self._roles.setdefault(worker, "trainer")
            if worker not in self._members:
                self._members[worker] = time.monotonic()
                self._bump_generation()
            else:
                self._members[worker] = time.monotonic()
            if expected:
                while (len(self._members) < int(expected)
                       and not self._closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.25))
                    # The joiner heartbeats only AFTER join returns, so
                    # its lease must stay fresh while IT is the one
                    # blocked here — with JOIN_GRACE_S > LOST_AFTER_S the
                    # reaper would otherwise evict the waiting worker and
                    # the rank lookup below would blow up.
                    if worker not in self._members:
                        self._members[worker] = time.monotonic()
                        self._bump_generation()
                    else:
                        self._members[worker] = time.monotonic()
            doc = self._member_doc()
        doc.update(ok=True, rank=doc["members"].index(worker))
        return doc

    def _op_heartbeat(self, req) -> Dict[str, Any]:
        worker = str(req["worker"])
        with self._cond:
            known = worker in self._members
            if known:
                now = time.monotonic()
                _M_LEASE_AGE.observe(
                    max(0.0, now - max(self._members[worker],
                                       self._hang_until)))
                self._members[worker] = now
            doc = self._member_doc()
        doc.update(ok=True, known=known,
                   regen=int(req.get("gen", -1)) != doc["gen"])
        return doc

    def _op_leave(self, req) -> Dict[str, Any]:
        worker = str(req["worker"])
        with self._cond:
            if worker in self._members:
                del self._members[worker]
                self._roles.pop(worker, None)
                self._bump_generation()
            doc = self._member_doc()
        doc.update(ok=True)
        return doc

    def _op_status(self, req) -> Dict[str, Any]:
        """Membership plus per-member lease age and role: the serving
        router reads staleness here BEFORE the reaper evicts (a replica
        whose lease is most of the way to `lost_after_s` stops getting new
        requests), and humans get the same table via the CLI."""
        with self._cond:
            doc = self._member_doc()
            now = time.monotonic()
            floor = self._hang_until
            doc["detail"] = {
                w: {"role": self._roles.get(w, "trainer"),
                    "lease_age_s": round(max(0.0, now - max(seen, floor)), 4)}
                for w, seen in self._members.items()}
            doc["lost_after_s"] = self.lost_after_s
        if self.metrics_url is not None:
            doc["metrics_url"] = self.metrics_url
        doc.update(ok=True)
        return doc

    def _op_barrier(self, req) -> Dict[str, Any]:
        worker, gen = str(req["worker"]), int(req["gen"])
        key = (gen, int(req.get("step", -1)), str(req.get("name", "")))
        timeout = float(req.get("timeout_s", BARRIER_TIMEOUT_S))
        deadline = time.monotonic() + timeout
        with self._cond:
            if gen != self._generation:
                return {"ok": False, "regen": True, "gen": self._generation}
            self._barriers.setdefault(key, set()).add(worker)
            self._trim_barriers()
            self._cond.notify_all()
            while True:
                if self._generation != gen:
                    return {"ok": False, "regen": True,
                            "gen": self._generation}
                if self._barriers.get(key, set()) >= set(self._ranked()):
                    return {"ok": True, "gen": gen}
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return {"ok": False, "timeout": True}
                self._cond.wait(min(remaining, 0.25))

    def _op_allreduce(self, req) -> Dict[str, Any]:
        """Mean over one contribution per CURRENT member. Blocks until the
        key's contributor set covers the generation's member set; any
        membership change unblocks everyone with `regen`."""
        worker, gen = str(req["worker"]), int(req["gen"])
        key = (gen, int(req.get("step", -1)), str(req.get("name", "")))
        timeout = float(req.get("timeout_s", BARRIER_TIMEOUT_S))
        deadline = time.monotonic() + timeout
        tree = decode_tree(req.get("data", {}))
        with self._cond:
            if gen != self._generation:
                return {"ok": False, "regen": True, "gen": self._generation}
            done = self._results.get(key)
            if done is None:
                # replace-not-add: a retried contribution is idempotent
                self._contribs.setdefault(key, {})[worker] = tree
                self._cond.notify_all()
            while True:
                done = self._results.get(key)
                if done is not None:
                    return {"ok": True, "gen": gen, "data": done}
                if self._generation != gen:
                    return {"ok": False, "regen": True,
                            "gen": self._generation}
                contribs = self._contribs.get(key, {})
                if set(contribs) >= set(self._ranked()) and contribs:
                    self._results[key] = self._mean(contribs)
                    self._contribs.pop(key, None)
                    self._trim_results()
                    self._cond.notify_all()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return {"ok": False, "timeout": True}
                self._cond.wait(min(remaining, 0.25))

    def _mean(self, contribs: Dict[str, Dict[str, np.ndarray]]
              ) -> Dict[str, Any]:
        trees = list(contribs.values())
        out: Dict[str, np.ndarray] = {}
        for k in trees[0]:
            acc = np.zeros(trees[0][k].shape, np.float64)
            for t in trees:
                acc += np.asarray(t[k], np.float64)
            out[k] = (acc / len(trees)).astype(trees[0][k].dtype)
        return encode_tree(out)

    def _trim_results(self, keep: int = 8) -> None:
        # Results are only re-read by laggards of the same step; a short
        # tail bounds memory on long runs.
        while len(self._results) > keep:
            self._results.pop(next(iter(self._results)))

    def _trim_barriers(self, keep: int = 8) -> None:
        # Completed barrier sets are never popped by the waiters (each
        # blocked peer still needs to observe completeness), so bound
        # them the same way: drop oldest-inserted first — live keys are
        # the newest, and a per-step run has at most one or two in flight.
        while len(self._barriers) > keep:
            self._barriers.pop(next(iter(self._barriers)))


# ---------------------------------------------------------------- client


class CoordinatorClient:
    """One worker's connection to the coordinator. Every RPC is one
    short-lived TCP connection retried under `util/retry.py`'s backoff
    (the coordinator may be hung, restarting, or not yet listening);
    retries surface as `dl4j_elastic_events_total{event=coordinator_retry}`.
    """

    def __init__(self, address: str, worker_id: str,
                 rpc_timeout_s: float = RPC_TIMEOUT_S,
                 backoff: Optional[Backoff] = None,
                 role: str = "trainer"):
        self.host, self.port = parse_address(address)
        self.worker_id = str(worker_id)
        self.role = str(role)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.backoff = backoff or Backoff(base_s=0.05, max_s=2.0, tries=8)
        self.gen = -1
        self.rank = 0
        self.world = 1
        self._hb_stop = threading.Event()
        self._hb_regen = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # --------------------------------------------------------------- rpc

    def _rpc_once(self, doc: Dict[str, Any],
                  timeout_s: Optional[float] = None) -> Dict[str, Any]:
        ctx = _prop.current()
        if ctx is not None:
            # The RPC-document twin of the X-DL4J-Trace header: the
            # coordinator parents its op span under the caller's context.
            doc = dict(doc)
            doc[_prop.TRACE_FIELD] = ctx.to_header()
        with socket.create_connection(
                (self.host, self.port),
                timeout=timeout_s or self.rpc_timeout_s) as s:
            f = s.makefile("rwb")
            f.write((json.dumps(doc) + "\n").encode("utf-8"))
            f.flush()
            line = f.readline()
        if not line:
            raise ConnectionError("coordinator closed the connection")
        resp = json.loads(line.decode("utf-8"))
        if resp.get("error"):
            raise CoordinatorError(f"coordinator error: {resp['error']}")
        return resp

    def _rpc(self, doc: Dict[str, Any], timeout_s: Optional[float] = None,
             tries: Optional[int] = None,
             max_elapsed_s: Optional[float] = None) -> Dict[str, Any]:
        bo = Backoff(base_s=self.backoff.base_s, max_s=self.backoff.max_s,
                     tries=tries or self.backoff.tries,
                     max_elapsed_s=(max_elapsed_s
                                    if max_elapsed_s is not None
                                    else self.backoff.max_elapsed_s))

        def on_retry(attempt, exc):
            _ev.record_event("coordinator_retry", op=doc.get("op"),
                             attempt=attempt, error=type(exc).__name__)

        return bo.run(lambda: self._rpc_once(doc, timeout_s),
                      retry_on=(OSError, socket.timeout),
                      on_retry=on_retry,
                      describe=f"coordinator rpc {doc.get('op')}")

    # --------------------------------------------------------- membership

    def join(self, expected: Optional[int] = None,
             grace_s: float = JOIN_GRACE_S,
             deadline_s: Optional[float] = None,
             role: Optional[str] = None) -> Dict[str, Any]:
        """Join (or re-join) the cluster; blocks server-side until the
        expected world forms or the grace lapses. Clears any pending
        regen flag — after a successful join we ARE the new generation.
        The retry envelope is capped at the caller's budget (`deadline_s`
        or the grace) — a coordinator that stays down can no longer push
        the join past its caller's timeout by one extra backoff step."""
        if role is not None:
            self.role = str(role)
        budget = (deadline_s or grace_s) + self.rpc_timeout_s
        doc = self._rpc({"op": "join", "worker": self.worker_id,
                         "expected": expected, "grace_s": grace_s,
                         "role": self.role},
                        timeout_s=budget,
                        tries=max(self.backoff.tries, 8),
                        max_elapsed_s=budget)
        self.gen, self.rank = int(doc["gen"]), int(doc["rank"])
        self.world = int(doc["world"])
        self._hb_regen.clear()
        return doc

    def leave(self) -> None:
        try:
            self._rpc({"op": "leave", "worker": self.worker_id}, tries=2)
        except (RetryError, RuntimeError):
            pass  # leaving best-effort: the reaper will get it anyway

    def heartbeat(self) -> Dict[str, Any]:
        doc = self._rpc({"op": "heartbeat", "worker": self.worker_id,
                         "gen": self.gen})
        if doc.get("regen") or not doc.get("known", True):
            self._hb_regen.set()
        return doc

    def status(self) -> Dict[str, Any]:
        """The coordinator's membership table with per-member role and
        lease age (`detail`): ``{gen, members, world, lost_after_s,
        detail: {worker: {role, lease_age_s}}}``. Read-only — usable
        without having joined (the serving router polls it)."""
        doc = self._rpc({"op": "status"})
        doc.setdefault("detail", {})
        return doc

    def start_heartbeats(self, interval_s: float = HEARTBEAT_S) -> None:
        """Background lease refresh. Sets the regen flag (checked by the
        trainer between steps) instead of raising into a foreign thread."""
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()

        def loop():
            while not self._hb_stop.wait(interval_s):
                try:
                    self.heartbeat()
                except (RetryError, RuntimeError, OSError):
                    self._hb_regen.set()

        self._hb_thread = threading.Thread(
            target=loop, name=f"dl4j-heartbeat-{self.worker_id}", daemon=True)
        self._hb_thread.start()

    def stop_heartbeats(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None

    @property
    def cluster_changed(self) -> bool:
        return self._hb_regen.is_set()

    def check(self) -> None:
        if self._hb_regen.is_set():
            raise ClusterChanged(
                f"worker {self.worker_id}: generation moved past {self.gen}")

    # -------------------------------------------------------- collectives

    def _collective(self, doc: Dict[str, Any],
                    timeout_s: float) -> Dict[str, Any]:
        doc.update(worker=self.worker_id, gen=self.gen, timeout_s=timeout_s)
        resp = self._rpc(doc, timeout_s=timeout_s + self.rpc_timeout_s)
        if resp.get("regen"):
            self._hb_regen.set()
            raise ClusterChanged(
                f"{doc['op']} {doc.get('name')}: cluster re-formed "
                f"(gen {self.gen} -> {resp.get('gen')})")
        if resp.get("timeout"):
            raise ClusterChanged(
                f"{doc['op']} {doc.get('name')}: collective timed out "
                f"(lost host not yet evicted?)")
        return resp

    def barrier(self, name: str, step: int = -1,
                timeout_s: float = BARRIER_TIMEOUT_S) -> None:
        self._collective({"op": "barrier", "name": name, "step": int(step)},
                         timeout_s)

    def allreduce_mean(self, name: str, step: int,
                       tree: Dict[str, np.ndarray],
                       timeout_s: float = BARRIER_TIMEOUT_S
                       ) -> Dict[str, np.ndarray]:
        resp = self._collective(
            {"op": "allreduce", "name": name, "step": int(step),
             "data": encode_tree(tree)}, timeout_s)
        return decode_tree(resp["data"])


# ------------------------------------------------------------------- cli


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m deeplearning4j_tpu.parallel.coordinator HOST:PORT`` —
    print the membership table (role + lease age per member), the human
    view of the same `status` op the serving router polls."""
    import argparse

    ap = argparse.ArgumentParser(
        description="inspect a running coordinator's membership")
    ap.add_argument("address", help="coordinator host:port")
    ap.add_argument("--timeout-s", type=float, default=5.0)
    args = ap.parse_args(argv)
    client = CoordinatorClient(args.address, worker_id="cli-status",
                               rpc_timeout_s=args.timeout_s)
    try:
        doc = client.status()
    except RetryError as e:
        print(f"coordinator unreachable at {args.address}: {e}")
        return 1
    print(f"generation {doc['gen']}  world {doc['world']}  "
          f"lost_after {doc.get('lost_after_s', '?')}s")
    detail = doc.get("detail", {})
    for w in doc.get("members", []):
        d = detail.get(w, {})
        print(f"  {w:40s} role={d.get('role', '?'):18s} "
              f"lease_age={d.get('lease_age_s', '?')}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
