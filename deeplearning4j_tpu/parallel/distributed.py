"""Multi-host / multi-process distributed training.

The cluster half of the reference's scale-out story, redesigned TPU-first:
where the reference ships parameters through Spark tree-aggregation
(`ParameterAveragingTrainingMaster.java:344-744` — broadcast params, fit
partitions, average every split) or an Aeron parameter server, here EVERY
process runs the SAME jitted SPMD program over one global
`jax.sharding.Mesh` spanning all hosts (SURVEY.md §7 row 5: "multi-host =
same program via jax.distributed"). Gradient all-reduce is emitted by XLA
inside the step — over ICI within a slice, DCN across slices — so there is
no master, no parameter shipping, and no averaging frequency.

Topology notes (the scaling-book recipe): `jax.devices()` orders devices
process-contiguously, so a `(data, model)` mesh built from it keeps the
model axis inside each process's slice — tensor-parallel collectives ride
ICI while only data-parallel gradient reduction crosses DCN.

Process-local data feeding mirrors the Spark partition model: each process
contributes its own slice of every global batch
(`DistributedTrainer.fit`), assembled into a global array without any
cross-host copy of the data itself.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.util.retry import with_retries


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               tries: int = 5,
               **kwargs) -> None:
    """Join (or form) the multi-process cluster — `jax.distributed.
    initialize` under backoff retries (`util/retry.py`): process 0 binds
    the coordinator service, every other process dials it, and nothing
    guarantees who starts first — a dial that beats the bind must retry,
    not crash the worker. With no arguments, cluster-environment
    autodetection applies (TPU pods populate everything; standalone
    clusters use the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID env vars). Call before any jax device use."""
    with_retries(
        lambda: jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id, **kwargs),
        tries=tries, retry_on=(RuntimeError, OSError),
        describe="jax.distributed.initialize")


def multiprocess_spmd_supported(platform: Optional[str] = None) -> bool:
    """Whether the backend can run CROSS-PROCESS SPMD computations.

    `jax.distributed.initialize` itself succeeds on any platform (the
    coordinator/KV service is backend-agnostic), but XLA:CPU then rejects
    the first multi-process collective with "Multiprocess computations
    aren't implemented on the CPU backend" — so the honest capability gate
    is the backend platform, not a handshake probe. The two-process tests
    and `ElasticTrainer(sync="auto")` consult this to pick the host-side
    coordinator transport (or a clean skip, with this reason) on CPU."""
    platform = platform or jax.default_backend()
    return platform not in ("cpu",)


def force_host_device_count(n: int) -> None:
    """Make the CPU backend expose `n` virtual devices — the worker-
    subprocess analog of conftest's XLA_FLAGS plumbing. MUST run before
    jax initializes its backends (os.environ edit; an already-initialized
    backend won't re-read it). Replaces any existing
    --xla_force_host_platform_device_count flag rather than appending a
    duplicate (XLA takes the first occurrence)."""
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+\s*", "",
                   flags).strip()
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={int(n)}".strip())


def shutdown() -> None:
    jax.distributed.shutdown()


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_mesh(shape: Optional[Tuple[int, ...]] = None,
                axis_names: Sequence[str] = ("data",)) -> Mesh:
    """Mesh over ALL processes' devices (same result on every process —
    required for the SPMD program to agree)."""
    return mesh_mod.create_mesh(shape, axis_names=axis_names,
                                devices=jax.devices())


def put_global(sharding: NamedSharding, host_array: np.ndarray) -> jax.Array:
    """Build a global array from a host copy every process holds (params,
    replicated state). Works for replicated AND sharded specs, single- and
    multi-process: each process materializes only its addressable shards."""
    host_array = np.asarray(host_array)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx])


def local_shard_to_global(mesh: Mesh, local: np.ndarray,
                          axis: str = "data") -> jax.Array:
    """Assemble a global batch from per-process slices: this process
    contributes `local` as its rows of the global leading dim (the Spark
    'partition' analog — data never crosses hosts)."""
    sharding = mesh_mod.data_sharding(mesh, np.ndim(local), axis)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def replicate_params_global(net, mesh: Mesh,
                            model_axis: Optional[str] = None) -> None:
    """Place the engine's params/state/opt-state onto the global mesh —
    `mesh_mod.shard_params` with the multi-process placement primitive
    (device_put requires all devices addressable; `put_global` does not).
    Same sharding rules as single-process by construction."""
    mesh_mod.shard_params(
        net, mesh, model_axis=model_axis,
        put=lambda a, s: put_global(s, np.asarray(a)))


class DistributedTrainer(ParallelWrapper):
    """Multi-process data-parallel fit: every process constructs this with
    the same net/config and feeds its LOCAL slice of each batch; the
    engines' jitted step then runs as one SPMD program over the global
    mesh. Single-process (process_count == 1) degenerates exactly to
    `ParallelWrapper`.

    Equivalence contract (mirrors the reference's
    `TestCompareParameterAveragingSparkVsSingleMachine`): with the same
    seed and the concatenation of all processes' local batches equal to
    the single-machine batch stream, the resulting parameters match
    single-machine training — tested in
    `tests/test_distributed.py` via a real 2-process run.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 model_axis: Optional[str] = None):
        if mesh is None:
            mesh = global_mesh()
        self.net = net
        self.mesh = mesh
        self.data_axis = mesh.axis_names[0]
        # Padding granularity: this process's share of the data axis.
        data_size = mesh.devices.shape[0]
        self.n_devices = max(data_size // jax.process_count(), 1)
        if not net._initialized:
            net.init()
        replicate_params_global(net, mesh, model_axis=model_axis)
        from deeplearning4j_tpu.parallel.context import ParallelContext

        # The inherited fit() installs this around every dispatch (layer
        # impls consult it for the sharded attention/MoE paths).
        self.context = ParallelContext(
            mesh=mesh, data_axis=self.data_axis, model_axis=model_axis)
        self._shape_checked = False

    def _shard(self, a):
        if a is None:
            return None
        if not self._shape_checked and jax.process_count() > 1:
            # Unequal local batches make each process infer a DIFFERENT
            # global shape -> mismatched SPMD programs -> silent collective
            # deadlock. One tiny allgather on the first batch turns that
            # into a fast, diagnosable failure.
            from jax.experimental import multihost_utils
            rows = np.asarray(a).shape[0]
            all_rows = np.asarray(
                multihost_utils.process_allgather(np.int64(rows)))
            if not (all_rows == all_rows[0]).all():
                raise ValueError(
                    "DistributedTrainer requires every process to feed the "
                    f"same local batch size; got {all_rows.tolist()} rows "
                    "across processes")
            self._shape_checked = True
        return local_shard_to_global(self.mesh, a, self.data_axis)
