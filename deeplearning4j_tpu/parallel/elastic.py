"""ElasticTrainer: preemption-aware, host-loss-tolerant training supervisor.

ROADMAP open item 3 ("as big as the hardware allows") is not a bigger
mesh — it is surviving the mesh shrinking under you. On TPU pods
preemption is the COMMON case, and the reference stack's answer (Spark
speculative re-execution around `ParameterAveragingTrainingMaster`) was
an entire cluster substrate. Here the supervisor is one class wired from
parts this repo already ships:

- **join** — `CoordinatorClient.join` under exponential-backoff + jitter
  (`util/retry.py`): a restarted 256-host pod must not synchronize its
  reconnect stampede, and a coordinator that is *slow* must not be
  treated as *dead*.
- **preemption** — SIGTERM sets a flag; at the next step boundary the
  trainer writes an immediate committed checkpoint, emits ONE flight
  bundle (chaining with the flight recorder's own handler: if that
  already dumped for this signal, the trainer skips its duplicate —
  `recorder.last_dump_reason`), leaves the cluster cleanly, and returns
  status ``"preempted"``.
- **host loss** — heartbeat leases + step collectives: a vanished peer
  stalls the step allreduce until the coordinator's reaper evicts it and
  bumps the generation; every survivor unblocks with `ClusterChanged`.
- **recovery** — re-join on the surviving set, rebuild placement, restore
  the newest committed sharded checkpoint ANY worker wrote (corrupt
  newest falls back to the previous committed step — PR 1's
  restore-onto-any-mesh-shape path, finally exercised for its stated
  purpose), fast-forward the data stream to the restored step, keep
  training. Bounded by `DL4J_TPU_ELASTIC_MAX_RESTARTS`.

Parameter synchronization (``sync=``):

- ``"spmd"``        — the `DistributedTrainer` path: XLA collectives
  inside the jitted step (real pods; requires a cross-process backend).
- ``"coordinator"`` — host-mediated per-step parameter averaging through
  the coordinator's float64 allreduce. Averaging parameters every step
  after identical local updates IS gradient averaging (the updates are
  affine in the gradient for SGD-family updaters), so this reproduces
  the reference's `ParameterAveragingTrainingMaster` semantics with
  k=1 — and it keeps working when the device cluster can't span
  processes (CPU CI, degraded pods), which is exactly when elastic
  recovery gets exercised.
- ``"auto"``        — "spmd" when `jax.process_count() > 1`, else
  "coordinator" when a coordinator is configured, else local-only.

Fault injection (`util/faultinject.py`) is evaluated at the top of every
step, so chaos tests schedule kills, preemptions, coordinator hangs and
checkpoint truncations deterministically — recovery is a tested code
path, not a hope.

Knobs: ``DL4J_TPU_ELASTIC_HEARTBEAT_S``, ``DL4J_TPU_ELASTIC_LOST_AFTER_S``,
``DL4J_TPU_ELASTIC_MAX_RESTARTS``, ``DL4J_TPU_ELASTIC_JOIN_GRACE_S``,
``DL4J_TPU_ELASTIC_BARRIER_TIMEOUT_S``, ``DL4J_TPU_ELASTIC_RPC_TIMEOUT_S``,
plus the `util/retry.py` backoff envelope (PERF.md §18).
"""

from __future__ import annotations

import os
import signal
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.checkpoint.array_store import (
    CheckpointCorruptError, CheckpointError)
from deeplearning4j_tpu.datasets.iterators import maybe_reset
from deeplearning4j_tpu.observability import elastic as _ev
from deeplearning4j_tpu.parallel.coordinator import (
    BARRIER_TIMEOUT_S, HEARTBEAT_S, JOIN_GRACE_S, ClusterChanged,
    Coordinator, CoordinatorClient, CoordinatorError, parse_address)
from deeplearning4j_tpu.util.faultinject import (
    Fault, FaultPlan, truncate_newest_chunk)
from deeplearning4j_tpu.util.retry import RetryError


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


MAX_RESTARTS = _env_int("DL4J_TPU_ELASTIC_MAX_RESTARTS", 5)


@dataclass
class ElasticResult:
    """What `ElasticTrainer.run` hands back to the job script."""
    status: str                       # "finished" | "preempted"
    step: int                         # net.iteration at exit
    restarts: int = 0
    recoveries_s: List[float] = field(default_factory=list)
    checkpoint: Optional[str] = None  # the preemption checkpoint, if any


class ElasticTrainer:
    """Supervises a `ParallelWrapper` (or `DistributedTrainer`) end to
    end: join, train, detect faults, recover, repeat. See module
    docstring for the recovery model.

    `data` for `run()` is either a callable ``data_fn(step, rank, world)
    -> DataSet`` (random-access — the elastic-native form: a shrunken
    cluster re-partitions by the NEW rank/world) or a DataSet iterator
    (fast-forwarded past the restored step on recovery; the stream must
    already be this worker's share).
    """

    def __init__(self, wrapper,
                 coordinator_address: Optional[str] = None,
                 worker_id: Optional[str] = None,
                 expected_world: Optional[int] = None,
                 checkpoint_root: Optional[str] = None,
                 save_every: int = 0,
                 sync: str = "auto",
                 host_coordinator: bool = False,
                 heartbeat_s: float = HEARTBEAT_S,
                 join_grace_s: float = JOIN_GRACE_S,
                 collective_timeout_s: float = BARRIER_TIMEOUT_S,
                 max_restarts: int = MAX_RESTARTS,
                 fault_plan: Optional[FaultPlan] = None,
                 lost_after_s: Optional[float] = None):
        self.wrapper = wrapper
        self.worker_id = str(worker_id if worker_id is not None
                             else f"worker-{os.getpid()}")
        self.expected_world = expected_world
        self.checkpoint_root = checkpoint_root
        self.save_every = int(save_every)
        self.heartbeat_s = float(heartbeat_s)
        self.join_grace_s = float(join_grace_s)
        self.collective_timeout_s = float(collective_timeout_s)
        self.max_restarts = int(max_restarts)
        self.fault_plan = (fault_plan if fault_plan is not None
                           else FaultPlan.from_env())
        self.coordinator: Optional[Coordinator] = None
        self.client: Optional[CoordinatorClient] = None
        if host_coordinator:
            host, port = parse_address(coordinator_address or "127.0.0.1:0")
            self.coordinator = Coordinator(
                host, port,
                lost_after_s=(lost_after_s if lost_after_s is not None
                              else 3 * self.heartbeat_s)).start()
            coordinator_address = self.coordinator.address
        self.coordinator_address = coordinator_address
        if coordinator_address:
            self.client = CoordinatorClient(coordinator_address,
                                            self.worker_id)
        import jax
        if sync == "auto":
            sync = ("spmd" if jax.process_count() > 1 else
                    "coordinator" if self.client is not None else "local")
        self.sync = sync
        self.manager = None
        if checkpoint_root:
            self._ckpt_dir = os.path.join(checkpoint_root,
                                          f"worker-{self.worker_id}")
            self.manager = wrapper.checkpoint_manager(
                self._ckpt_dir, save_every=self.save_every)
        self._preempted = threading.Event()
        self._prev_sigterm: Any = None
        self._recovery_t0: Optional[float] = None
        self._stream_pos = 0  # batches drawn from an iterator `data`

    # ------------------------------------------------------------- signals

    def _install_signal(self) -> None:
        """Own SIGTERM for the duration of run(). If the flight recorder's
        lazy installer runs AFTER us (first recorded step happens inside
        run), flight layers its bundle-dumping handler on top and chains
        to this one: a preemption yields flight's bundle + our flag, in
        that order. If flight installed FIRST (an earlier fit in this
        process), we must NOT chain into its handler — its own chain ends
        in a SIG_DFL re-raise that kills the process mid-checkpoint — so
        we take over its one duty (the signal bundle) and swallow the
        signal; `_graceful_preempt` then skips the duplicate dump via
        `last_dump_reason`."""
        if threading.current_thread() is not threading.main_thread():
            return

        def handler(signum, frame):
            self._preempted.set()
            prev = self._prev_sigterm
            try:
                from deeplearning4j_tpu.observability import flight
            except Exception:
                flight = None
            if flight is not None and prev is flight.signal_handler:
                try:
                    flight.dump(reason=f"signal:{signal.Signals(signum).name}",
                                force=True)
                except Exception:
                    pass
            elif callable(prev):
                # chain a pre-existing user handler (not SIG_DFL/IGN:
                # default would kill us mid-checkpoint)
                prev(signum, frame)

        try:
            self._prev_sigterm = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, handler)
        except (ValueError, OSError):
            self._prev_sigterm = None

    def _restore_signal(self) -> None:
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None

    # -------------------------------------------------------------- faults

    def _fault_handlers(self) -> Dict[str, Callable[[Fault], None]]:
        def hang(fault: Fault) -> None:
            if self.coordinator is not None:
                self.coordinator.inject_hang(
                    float(fault.args.get("seconds", 2.0)))

        def truncate(fault: Fault) -> None:
            if self.manager is None:
                return
            self.manager.flush()
            steps = self.manager.all_steps()
            if steps:
                truncate_newest_chunk(
                    self.manager.step_path(steps[-1]),
                    int(fault.args.get("bytes", 64)))

        return {"hang_coordinator": hang, "truncate_chunk": truncate}

    # ----------------------------------------------------------- lifecycle

    def _join(self, rejoin: bool = False) -> None:
        """First join waits (up to the grace) for the full expected world;
        a RE-join after a fault forms the cluster on whoever is alive NOW
        — waiting the grace out for a host that is dead would turn every
        recovery into a `join_grace_s` stall. Survivors that re-join
        moments later bump the generation, which surfaces as one more
        (cheap) restart on the early re-joiners until the set settles."""
        if self.client is None:
            return
        doc = self.client.join(
            expected=None if rejoin else self.expected_world,
            grace_s=self.join_grace_s)
        self.client.start_heartbeats(self.heartbeat_s)
        _ev.record_event("join", worker=self.worker_id, gen=doc["gen"],
                         world=doc["world"], rank=doc["rank"])

    def _restore_latest(self) -> Optional[int]:
        """Newest committed step across EVERY worker's checkpoint subdir
        (post-averaging checkpoints are identical across workers, so any
        worker's copy continues the run). Corrupt candidates warn, count
        `restore_fallback`, and the walk moves to the next-newest copy."""
        if not self.checkpoint_root or not os.path.isdir(self.checkpoint_root):
            return None
        if self.manager is not None:
            self.manager.flush()
        pairs: List[tuple] = []
        for sub in sorted(os.listdir(self.checkpoint_root)):
            subdir = os.path.join(self.checkpoint_root, sub)
            if not os.path.isdir(subdir):
                continue
            mgr = self.wrapper.checkpoint_manager(subdir)
            for step in mgr.candidate_steps():
                pairs.append((step, subdir))
        pairs.sort(key=lambda p: (-p[0], p[1]))
        for step, subdir in pairs:
            try:
                net = self.wrapper.checkpoint_manager(subdir).restore(
                    step=step, net=self.wrapper.net)
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"checkpoint step {step} in {subdir} failed corruption "
                    f"checks ({e}); trying next-newest copy",
                    RuntimeWarning, stacklevel=2)
                _ev.record_event("restore_fallback", step=int(step),
                                 dir=subdir, error=f"{type(e).__name__}: {e}")
                continue
            except CheckpointError:
                continue
            self.wrapper.net = net
            _ev.record_event("restore", step=int(net.iteration), dir=subdir)
            return int(net.iteration)
        return None

    def _position_stream(self, data, target: int):
        """Position a shared iterator `data` at batch `target`. A
        resettable iterator replays from scratch (the fast-forward
        contract: same batch stream as an uninterrupted run). A
        non-resettable one cannot rewind — and on a restart it is
        already `_stream_pos` batches in, so skip only the delta to the
        target instead of discarding `target` MORE batches from the
        current position (which would silently lose training data on
        every recovery). When the stream is already past the target the
        gap is unreplayable: warn rather than drop data silently."""
        if maybe_reset(data):
            self._stream_pos = 0
        elif self._stream_pos > target:
            warnings.warn(
                f"elastic restart: data iterator is not resettable and is "
                f"already {self._stream_pos - target} batches past restored "
                f"step {target}; continuing from the live stream position. "
                f"Use a resettable iterator or a data_fn(step, rank, world) "
                f"callable for replay-exact recovery.",
                RuntimeWarning, stacklevel=2)
        it = iter(data)
        while self._stream_pos < target:
            try:
                next(it)
            except StopIteration:
                break
            self._stream_pos += 1
        return it

    # ------------------------------------------------------------ training

    def _average(self, step: int) -> None:
        """Per-step parameter averaging over the coordinator: flatten the
        float leaves of params + updater state to host arrays, allreduce
        the mean (float64 accumulate), push the result back through the
        wrapper's placement rules. Non-float leaves (int step counters,
        quantized weights) stay local — they are identical across workers
        by construction."""
        import jax

        net = self.wrapper.net
        payload: Dict[str, np.ndarray] = {}
        p_leaves, p_def = jax.tree_util.tree_flatten(net.params_tree)
        o_leaves, o_def = ([], None)
        if net.opt_state is not None:
            o_leaves, o_def = jax.tree_util.tree_flatten(net.opt_state)

        def collect(prefix, leaves):
            for i, leaf in enumerate(leaves):
                a = np.asarray(leaf)
                if np.issubdtype(a.dtype, np.floating):
                    payload[f"{prefix}{i}"] = a

        collect("p", p_leaves)
        collect("o", o_leaves)
        mean = self.client.allreduce_mean(
            "params", step, payload, timeout_s=self.collective_timeout_s)

        def merge(prefix, leaves):
            return [mean[f"{prefix}{i}"] if f"{prefix}{i}" in mean else leaf
                    for i, leaf in enumerate(leaves)]

        new_params = jax.tree_util.tree_unflatten(p_def, merge("p", p_leaves))
        new_opt = (jax.tree_util.tree_unflatten(o_def, merge("o", o_leaves))
                   if o_def is not None else None)
        self.wrapper.push_host_state(params_tree=new_params,
                                     opt_state=new_opt)

    def _graceful_preempt(self, result: ElasticResult) -> ElasticResult:
        """The preemption drill: commit a checkpoint NOW, one flight
        bundle, leave the cluster, hand back control."""
        net = self.wrapper.net
        _ev.record_event("preempt", worker=self.worker_id,
                         step=int(net.iteration))
        if self.manager is not None:
            result.checkpoint = self.manager.save(net)
            self.manager.flush()  # committed before we report clean exit
        try:
            # `observability.flight` is the recorder INSTANCE (re-export).
            from deeplearning4j_tpu.observability import flight

            reason = flight.last_dump_reason
            if not (reason or "").startswith("signal:"):
                flight.dump(reason="preempt")
        except Exception:
            pass
        self._leave()
        result.status = "preempted"
        result.step = int(net.iteration)
        return result

    def _leave(self) -> None:
        if self.client is not None:
            self.client.stop_heartbeats()
            self.client.leave()

    def _train(self, data, steps: int, result: ElasticResult) -> str:
        net = self.wrapper.net
        handlers = self._fault_handlers()
        rank = self.client.rank if self.client is not None else 0
        world = self.client.world if self.client is not None else 1
        stream = None
        if not callable(data):
            stream = self._position_stream(data, int(net.iteration))
        while net.iteration < int(steps):
            step = int(net.iteration)
            if self.client is not None:
                self.client.check()  # heartbeat thread saw a regen?
            self.fault_plan.maybe_fire(step, rank, handlers)
            if self._preempted.is_set():
                self._graceful_preempt(result)
                return "preempted"
            if callable(data):
                ds = data(step, rank, world)
            else:
                ds = next(stream, None)
                if ds is not None:
                    self._stream_pos += 1
            if ds is None:
                break
            self.wrapper.fit(ds)
            if self.sync == "coordinator" and world > 1:
                self._average(step)
            if self._recovery_t0 is not None:
                # first full step after a restart: training has RESUMED
                seconds = time.monotonic() - self._recovery_t0
                self._recovery_t0 = None
                _ev.observe_recovery(seconds)
                result.recoveries_s.append(seconds)
            if self._preempted.is_set():
                self._graceful_preempt(result)
                return "preempted"
            if self.manager is not None:
                self.manager.maybe_save(net)
        if self.manager is not None:
            self.manager.flush()
        return "finished"

    # ----------------------------------------------------------------- run

    def run(self, data, steps: int) -> ElasticResult:
        """Train to `steps` total iterations, surviving preemptions, lost
        hosts, hung coordinators and corrupt checkpoints along the way.
        Returns an `ElasticResult`; raises only when the restart budget
        is exhausted or the cluster cannot be re-formed."""
        result = ElasticResult(status="finished",
                               step=int(self.wrapper.net.iteration))
        self._install_signal()
        try:
            restarts = 0
            while True:
                try:
                    self._join(rejoin=restarts > 0)
                    # Also on the FIRST attempt: a restarted process (the
                    # preempt-then-relaunch flow) resumes from the newest
                    # committed step instead of training from scratch.
                    self._restore_latest()
                    status = self._train(data, int(steps), result)
                    result.status = status
                    result.step = int(self.wrapper.net.iteration)
                    result.restarts = restarts
                    if status == "finished":
                        self._leave()
                    return result
                except (ClusterChanged, CoordinatorError, RetryError) as e:
                    self._recovery_t0 = time.monotonic()
                    restarts += 1
                    _ev.RESTARTS.inc()
                    _ev.record_event("restart", worker=self.worker_id,
                                     attempt=restarts, cause=type(e).__name__)
                    if restarts > self.max_restarts:
                        raise
                    if self.client is not None:
                        self.client.stop_heartbeats()
        finally:
            self._restore_signal()
            if self.client is not None:
                self.client.stop_heartbeats()
            if self.coordinator is not None and not self._linger_coordinator():
                self.coordinator.close()

    def _linger_coordinator(self) -> bool:
        """Keep the in-process coordinator alive after run() while other
        members are still registered — the hosting worker may finish (or
        be preempted) first, and closing the service under the survivors
        would turn one fault into a cluster-wide outage."""
        if self.coordinator is None:
            return False
        with self.coordinator._cond:
            others = [w for w in self.coordinator._members
                      if w != self.worker_id]
        return bool(others)
