"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A brand-new framework with the capabilities of deeplearning4j (reference:
JelliSindhu/deeplearning4j), designed TPU-first: layer/graph configurations are
JSON-serializable builder-produced dataclasses; networks compile to pure jitted
apply/train functions over parameter pytrees; optimizers are composable gradient
transformations fused into the jitted step; data parallelism is per-step gradient
all-reduce over a `jax.sharding.Mesh` (pjit/shard_map) instead of the reference's
parameter-averaging transports (ParallelWrapper / Spark / Aeron PS).

Top-level re-exports cover the most common user-facing API.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf.enums import (  # noqa: F401
    Activation,
    BackpropType,
    ConvolutionMode,
    GradientNormalization,
    LossFunction,
    OptimizationAlgorithm,
    PoolingType,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.neural_net import (  # noqa: F401
    ComputationGraphConfiguration,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401
