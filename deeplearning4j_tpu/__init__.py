"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A brand-new framework with the capabilities of deeplearning4j (reference:
JelliSindhu/deeplearning4j), designed TPU-first: layer/graph configurations are
JSON-serializable builder-produced dataclasses; networks compile to pure jitted
apply/train functions over parameter pytrees; optimizers are composable gradient
transformations fused into the jitted step; data parallelism is per-step gradient
all-reduce over a `jax.sharding.Mesh` (pjit/shard_map) instead of the reference's
parameter-averaging transports (ParallelWrapper / Spark / Aeron PS).

Top-level re-exports cover the most common user-facing API.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf.enums import (  # noqa: F401
    Activation,
    BackpropType,
    ConvolutionMode,
    GradientNormalization,
    LossFunction,
    OptimizationAlgorithm,
    PoolingType,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf.neural_net import (  # noqa: F401
    ComputationGraphConfiguration,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401

# Wire jax's persistent compilation cache at import (opt-out via
# DL4J_TPU_COMPILE_CACHE=0): init-time helper ops compile before the first
# _get_jit would lazily configure it, and a warm process should replay
# those from disk too, not just the big training programs.
from deeplearning4j_tpu.compilation import (  # noqa: F401,E402
    configure_persistent_cache as _configure_persistent_cache)

_configure_persistent_cache()
