"""Fleet front-end: failure-aware routing over N serving replicas.

The `FleetRouter` owns no models and runs no compute — it owns the
*routing table* and the *failure policy*:

- **membership is the coordinator's** (`parallel/coordinator.py`): a poll
  thread reads `status` (members + per-member role + lease age) at
  sub-lease cadence, so replica health is the SAME heartbeat lease that
  detects a lost trainer. Role strings are the lifecycle
  (``replica`` routable / ``replica:warming`` / ``replica:draining``);
  a live replica that vanishes from the table was lease-reaped — counted
  dead, its traffic rerouted.
- **load is the replicas' own SLO gauges**: each poll scrapes every live
  replica's `/metrics` (explicit timeout — JX012) and sums
  `dl4j_serving_model_queue_depth` + `dl4j_serving_decode_slots_busy`
  into one score; `_pick` takes the least-loaded live replica, with the
  router's own outstanding-request count added so traffic doesn't dogpile
  between scrapes.
- **failover runs under the request's deadline**: each request is a
  `util/retry.Backoff` envelope with ``max_elapsed_s`` = the caller's
  budget; a failed attempt excludes that replica and retries the next
  pick. Retry is classified, never blind: replica 503s (shed / draining
  / warming) were **never admitted** and always retry; connection-refused
  never reached the socket and always retries; but a request that FAILED
  AFTER ADMISSION (timeout / reset / 5xx) retries only when idempotent —
  a partial generation is surfaced as `PartialFailureError`, not silently
  re-sampled. 4xx pass through verbatim (client bugs don't failover).
- **saturation is shed, not queued**: no pickable replica means an
  immediate `ServerOverloadedError` (503 + Retry-After at the HTTP
  front), counted ``shed`` — deliberately distinct from ``failed``
  (budget exhausted by real failures) in
  `dl4j_router_requests_total{outcome}`.

A failed replica is also locally quarantined for a few seconds so a hung
process (heartbeats alive, service dead — lease expiry will NOT evict
it) stops receiving fresh traffic after its first timeout.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Set
from urllib.parse import parse_qs, urlparse

import numpy as np

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.analysis.locktrace import named_condition, named_lock
from deeplearning4j_tpu.observability import fleet as _fev
from deeplearning4j_tpu.observability import propagate as _prop
from deeplearning4j_tpu.parallel.coordinator import CoordinatorClient
from deeplearning4j_tpu.serving import metrics as _m
from deeplearning4j_tpu.serving.errors import (
    ServerOverloadedError,
    ServingError,
)
from deeplearning4j_tpu.util.retry import Backoff, RetryError

ROLE_LIVE = "replica"
ROLE_WARMING = "replica:warming"
ROLE_DRAINING = "replica:draining"

_STATE_BY_ROLE = {ROLE_LIVE: "live", ROLE_WARMING: "warming",
                  ROLE_DRAINING: "draining"}

# Tensor-parallel shard-group member (serving/fleet.py shard_role):
# replica:shard<i>/<n>[:warming|:draining]. The shard topology rides the
# role string — the coordinator's only per-member metadata plane.
_SHARD_ROLE_RE = re.compile(
    r"^replica:shard(\d+)/(\d+)(?::(warming|draining))?$")


def parse_replica_role(role: str):
    """Role string -> (state, shard_index, shard_count), or None for
    non-replica roles (trainers/routers share the coordinator).
    Unsharded replicas come back as (state, None, 1)."""
    state = _STATE_BY_ROLE.get(role)
    if state is not None:
        return state, None, 1
    m = _SHARD_ROLE_RE.match(role or "")
    if m is None:
        return None
    return (m.group(3) or "live"), int(m.group(1)), int(m.group(2))


class PartialFailureError(ServingError):
    """A non-idempotent request (generation samples tokens) failed AFTER
    the replica admitted it. The router refuses to blind-retry — the
    caller decides whether re-sampling is acceptable."""

    status = 502


class UpstreamError(ServingError):
    """A replica answered with a non-retryable client error (4xx); the
    router propagates status + body verbatim instead of failing over —
    a malformed payload fails identically on every replica."""

    def __init__(self, status: int, body: dict):
        super().__init__(body.get("error", f"upstream {status}"))
        self.status = int(status)
        self.body = dict(body)

    def payload(self) -> dict:
        return self.body


class _Failover(Exception):
    """Internal: this attempt failed in a way that is safe to retry on a
    different replica (inside the deadline budget)."""


# ------------------------------------------------------------- http utils


def post_json(url: str, payload: dict, timeout_s: float,
              headers: Optional[Dict[str, str]] = None) -> dict:
    """POST JSON -> parsed JSON body, with an EXPLICIT socket timeout on
    every call (JX012: an unbounded request path turns one hung replica
    into a hung fleet). The thread-current trace context is forwarded on
    the X-DL4J-Trace header automatically (JX013), so every hop made
    through this helper stays on the request's cross-process timeline."""
    all_headers = _prop.trace_headers(headers)
    all_headers.setdefault("Content-Type", "application/json")
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers=all_headers, method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def get_text(url: str, timeout_s: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8")


def _error_body(e: urllib.error.HTTPError) -> dict:
    try:
        return json.loads(e.read().decode("utf-8"))
    except Exception:
        return {"error": f"HTTP {e.code}"}


def _unwrap(e: BaseException) -> BaseException:
    """urllib wraps connect-phase failures in URLError(reason=...); the
    classification below needs the underlying OSError/timeout."""
    if isinstance(e, urllib.error.URLError) \
            and not isinstance(e, urllib.error.HTTPError) \
            and isinstance(e.reason, BaseException):
        return e.reason
    return e


def sum_metric_snapshot(doc: dict, names) -> float:
    """Sum every series value of the named families out of a
    `/metrics?format=json` snapshot (the narrow-scrape fast path: the
    replica serialized ONLY the requested families, so neither side's
    cost scales with how many families the process hosts)."""
    total = 0.0
    for name in names:
        fam = doc.get(name)
        if not isinstance(fam, dict):
            continue
        for series in fam.get("series", ()):
            try:
                total += float(series.get("value", 0.0))
            except (TypeError, ValueError):
                pass
    return total


def sum_metric_families(text: str, names) -> float:
    """Sum every sample of the named families out of a Prometheus text
    exposition (labels ignored — the router wants one load score)."""
    total = 0.0
    names = tuple(names)
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        family = metric.split("{", 1)[0]
        if family in names:
            try:
                total += float(value)
            except ValueError:
                pass
    return total


# ----------------------------------------------------------------- router


@dataclass
class ReplicaInfo:
    """One routing-table row (router-local view of one replica)."""

    worker_id: str
    name: str
    url: str
    state: str            # live | warming | draining
    lease_age_s: float
    seen_at: float        # monotonic time of the poll that produced this
    load: float = 0.0     # scraped queue depth + busy decode slots
    scrape_ok: bool = True
    # Tensor-parallel shard-group membership (None/1/None = unsharded).
    # A group routes as ONE unit through its shard-0 entry member, and
    # only while EVERY member is live with a fresh lease.
    shard_index: Optional[int] = None
    shard_count: int = 1
    group: Optional[str] = None

    def row(self) -> Dict[str, Any]:
        return {"worker_id": self.worker_id, "name": self.name,
                "url": self.url, "state": self.state,
                "lease_age_s": self.lease_age_s, "load": self.load,
                "scrape_ok": self.scrape_ok,
                "shard_index": self.shard_index,
                "shard_count": self.shard_count, "group": self.group}


class FleetRouter:
    """Least-loaded routing + deadline-budgeted failover over the fleet.

    In-process API (`predict` / `generate`) plus an optional HTTP front
    mirroring the replica surface (`/predict`, `/generate`, `/metrics`,
    `/health`, `/fleet`) so external clients talk to ONE address while
    replicas come, go, die and roll underneath.
    """

    def __init__(self, coordinator_address: str, *,
                 poll_interval_s: float = 0.25,
                 scrape_timeout_s: float = 1.0,
                 request_timeout_s: float = 30.0,
                 attempt_timeout_s: Optional[float] = None,
                 failover_tries: int = 4,
                 quarantine_s: float = 2.0,
                 stale_lease_fraction: float = 0.75,
                 host: str = "127.0.0.1", port: int = 0,
                 http: bool = True,
                 slo_objectives=None,
                 slo_window_scale: float = 1.0):
        self.coordinator_address = str(coordinator_address)
        self.poll_interval_s = float(poll_interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        # Per-attempt cap < deadline is what makes a HUNG replica (lease
        # alive, service dead) cost one bounded attempt, not the whole
        # request budget.
        self.attempt_timeout_s = attempt_timeout_s
        self.failover_tries = int(failover_tries)
        self.quarantine_s = float(quarantine_s)
        self.stale_lease_fraction = float(stale_lease_fraction)
        self.host = host
        self.port = int(port)
        self.http = bool(http)
        self._client = CoordinatorClient(
            self.coordinator_address, worker_id="fleet-router",
            role="router",
            # The poll loop already retries every poll_interval_s; per-RPC
            # retries would only stall it (and the shed-path refresh).
            backoff=Backoff(base_s=0.05, max_s=0.1, tries=1))
        self._lock = named_lock("serving.router.table")
        self._table: Dict[str, ReplicaInfo] = {}
        # Outstanding requests per worker_id. Lives OUTSIDE the per-poll
        # ReplicaInfo snapshots: a request that spans a table rebuild must
        # decrement the same counter it incremented, or the leak skews
        # _pick's load score forever.
        self._inflight: Dict[str, int] = {}
        self._quarantine: Dict[str, float] = {}
        # Single-flight shed refresh: one leader does the coordinator RPC
        # with NO lock held; followers wait on the condition for the
        # generation bump (holding a lock across the RPC was JX018 — it
        # serialized every about-to-shed request behind network I/O).
        self._refresh_cond = named_condition("serving.router.refresh")
        self._refreshing = False
        self._refresh_gen = 0
        self._lost_after_s = 15.0
        self._dead_total = 0
        self._rr = 0
        self._counts: Dict[str, int] = {"ok": 0, "failover": 0, "shed": 0,
                                        "failed": 0}
        self._latencies: deque = deque(maxlen=1024)
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._aggregator = None
        self._slo_objectives = slo_objectives
        self.slo_window_scale = float(slo_window_scale)
        self._slo_engine = None
        self._slo_lock = named_lock("serving.router.slo")

    # ----------------------------------------------------------- federation

    def aggregator(self):
        """The fleet-wide observability aggregator, built lazily on the
        router's own coordinator membership (`observability/federation`).
        Backs the HTTP front's `/fleet/metrics` and `/api/trace`."""
        if self._aggregator is None:
            from deeplearning4j_tpu.observability import federation as _fed

            self._aggregator = _fed.FleetAggregator(
                self.coordinator_address,
                scrape_timeout_s=self.scrape_timeout_s,
                local_worker_id=f"fleet-router@{self.host}:{self.port}")
        return self._aggregator

    def slo_engine(self):
        """The fleet burn-rate engine (`observability/slo.py`), built
        lazily. Its `on_page` hook POSTs `/admin/flight-dump` to each
        offending replica, so a paging burn freezes forensic bundles
        while the incident is live — the replica-side per-reason rate
        limit is what makes one sustained breach yield one bundle."""
        if self._slo_engine is None:
            from deeplearning4j_tpu.observability import slo as _slo

            with self._slo_lock:
                if self._slo_engine is None:
                    self._slo_engine = _slo.BurnRateEngine(
                        objectives=self._slo_objectives,
                        window_scale=self.slo_window_scale,
                        on_page=self._on_slo_page)
        return self._slo_engine

    def _on_slo_page(self, objective: str, worker_ids: List[str]) -> None:
        with self._lock:
            urls = {wid: info.url for wid, info in self._table.items()}
        for wid in worker_ids:
            url = urls.get(wid)
            if url is None:
                # Not in the routing table (e.g. just evicted): the
                # worker-id convention still carries the address.
                if "@" not in wid:
                    continue
                url = f"http://{wid.rsplit('@', 1)[1]}"
            try:
                post_json(url + "/admin/flight-dump",
                          {"reason": f"slo:{objective}"},
                          timeout_s=self.scrape_timeout_s)
                _fev.record_event("slo_page", objective=objective,
                                  replica=wid)
            except Exception:
                pass  # forensics must never take down the alert path

    def fleet_slo(self) -> Dict[str, Any]:
        """`GET /fleet/slo`: scrape the federated exposition, fold it
        into the burn-rate engine, return the current alert state."""
        text = self.aggregator().federate_metrics()
        return self.slo_engine().report(text)

    def fleet_tenants(self) -> Dict[str, Any]:
        """`GET /v1/tenants` federated: each live replica's per-tenant
        ledger rollups merged by (model, adapter) — numeric fields sum,
        and every merged row lists the workers it came from."""
        merged: Dict[tuple, Dict[str, Any]] = {}
        with self._lock:
            targets = [(info.worker_id, info.url)
                       for info in self._table.values()
                       if info.state == "live"]
        for wid, url in targets:
            try:
                doc = json.loads(get_text(
                    url + "/v1/tenants", timeout_s=self.scrape_timeout_s))
            except Exception:
                continue
            for row in doc.get("tenants", []):
                key = (row.get("model"), row.get("adapter"))
                agg = merged.setdefault(key, {
                    "model": key[0], "adapter": key[1], "workers": []})
                agg["workers"].append(wid)
                for k, v in row.items():
                    if isinstance(v, dict):
                        sub = agg.setdefault(k, {})
                        for sk, sv in v.items():
                            sub[sk] = sub.get(sk, 0) + sv
                    elif isinstance(v, (int, float)) and not isinstance(
                            v, bool):
                        agg[k] = agg.get(k, 0) + v
        rows = sorted(merged.values(),
                      key=lambda r: (r["model"] or "", r["adapter"] or ""))
        for row in rows:
            n = row.get("requests", 0)
            # The per-replica means don't sum; recompute from the sums.
            row["queue_wait_mean_s"] = (
                (row.get("queue_wait_s", 0.0) / n) if n else 0.0)
        return {"tenants": rows}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "FleetRouter":
        try:
            self.poll_once()
        except Exception:
            pass  # coordinator may still be coming up; the loop retries
        self._stop.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="dl4j-router-poll", daemon=True)
        self._poll_thread.start()
        for state in ("live", "warming", "draining", "dead"):
            _m.FLEET_REPLICAS.labels(state=state).set_function(
                (lambda s: lambda: float(self._count_state(s)))(state))
        if self.http:
            self._httpd = ThreadingHTTPServer(
                (self.host, self.port), _make_router_handler(self))
            self.port = self._httpd.server_address[1]
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever, name="dl4j-router-http",
                daemon=True)
            self._http_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=2.0)
            self._poll_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for state in ("live", "warming", "draining", "dead"):
            _m.FLEET_REPLICAS.labels(state=state).set_function(None)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ---------------------------------------------------------- membership

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:
                # Coordinator unreachable: keep the last table — replicas
                # may still be serving; the request path finds out.
                pass

    # The two SLO gauges one load score is computed from. The poll asks
    # the replica for ONLY these (narrow JSON snapshot) — scraping and
    # re-parsing the full exposition per poll made poll cost scale with
    # every metric family any subsystem ever registered.
    _LOAD_FAMILIES = ("dl4j_serving_model_queue_depth",
                      "dl4j_serving_decode_slots_busy")
    _LOAD_QUERY = "/metrics?format=json&names=" + ",".join(_LOAD_FAMILIES)

    def poll_once(self) -> None:
        """Rebuild the routing table from coordinator membership, then
        refresh each live replica's load score from its own /metrics."""
        live = self._refresh_membership()
        for info in live:
            try:
                info.load = self._scrape_load(info)
                info.scrape_ok = True
            except Exception:
                # Keep the stale score; the request path (timeout +
                # quarantine) is the authority on a broken replica.
                info.scrape_ok = False

    def _scrape_load(self, info: ReplicaInfo) -> float:
        text = get_text(info.url + self._LOAD_QUERY,
                        timeout_s=self.scrape_timeout_s)
        try:
            doc = json.loads(text)
        except ValueError:
            # A /metrics that ignored the query params (non-registry
            # endpoint): fall back to the full-exposition parse.
            return sum_metric_families(text, self._LOAD_FAMILIES)
        return sum_metric_snapshot(doc, self._LOAD_FAMILIES)

    def _refresh_membership(self) -> List[ReplicaInfo]:
        """One coordinator status RPC -> new routing table; returns the
        live rows (the poll loop's scrape candidates). Does no per-replica
        I/O, so the shed path can afford it on the request thread."""
        doc = self._client.status()
        detail = doc.get("detail", {})
        now = time.monotonic()
        rows: Dict[str, ReplicaInfo] = {}
        for wid in doc.get("members", []):
            role = detail.get(wid, {}).get("role", "trainer")
            parsed = parse_replica_role(role)
            if parsed is None:
                continue  # trainers/routers share the coordinator
            state, shard_index, shard_count = parsed
            name, _, addr = wid.partition("@")
            if not addr:
                continue
            group = (name.rsplit("#", 1)[0] if shard_index is not None
                     else None)
            rows[wid] = ReplicaInfo(
                worker_id=wid, name=name, url=f"http://{addr}",
                state=state,
                lease_age_s=float(
                    detail.get(wid, {}).get("lease_age_s", 0.0)),
                seen_at=now, shard_index=shard_index,
                shard_count=shard_count, group=group)
        with self._lock:
            self._lost_after_s = float(
                doc.get("lost_after_s", self._lost_after_s))
            for wid, old in self._table.items():
                if wid not in rows and old.state == "live":
                    # A voluntary `leave` removes the member while its lease
                    # is still fresh; the reaper only evicts once the lease
                    # runs past lost_after_s.  Use the last-observed
                    # effective age to tell a clean goodbye from a death —
                    # a fast drain can leave between two polls without ever
                    # being seen in the draining role.
                    age = old.lease_age_s + (now - old.seen_at)
                    if age >= 0.5 * self._lost_after_s:
                        self._dead_total += 1
                        _fev.record_event("replica_dead", replica=old.name,
                                          url=old.url)
                elif wid in rows:
                    rows[wid].load = old.load
            self._table = rows
            return [r for r in rows.values() if r.state == "live"]

    def _refresh_membership_shared(self) -> None:
        """Shed-path refresh: membership only, single-flight. Concurrent
        shedding requests share one coordinator RPC — a saturated fleet
        must not dogpile the coordinator (or re-scrape every replica's
        /metrics) once per about-to-shed request. The RPC runs with no
        lock held: the first caller becomes the leader, everyone who
        arrives while it is in flight waits on the condition for the
        generation bump and reuses the leader's table."""
        with self._refresh_cond:
            if self._refreshing:
                gen = self._refresh_gen
                self._refresh_cond.wait_for(
                    lambda: self._refresh_gen != gen,
                    timeout=max(1.0, 2.0 * self.scrape_timeout_s))
                return
            self._refreshing = True
        try:
            self._refresh_membership()
        finally:
            with self._refresh_cond:
                self._refresh_gen += 1
                self._refreshing = False
                self._refresh_cond.notify_all()

    def table(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(info.row(),
                         inflight=self._inflight.get(wid, 0))
                    for wid, info in self._table.items()]

    def _count_state(self, state: str) -> int:
        if state == "dead":
            with self._lock:
                return self._dead_total
        with self._lock:
            return sum(1 for r in self._table.values()
                       if r.state == state)

    def _healthy_groups(self, now: float, stale_cut: float) -> Set[str]:
        """Shard groups currently routable: EVERY member present (all
        shard indices 0..n-1), live, lease fresh. Health is the AND of
        the members' leases — one dead shard makes the whole group
        unroutable within one lease (the reaper evicts the dead member,
        completeness breaks). Caller holds self._lock."""
        members: Dict[str, List[ReplicaInfo]] = {}
        for r in self._table.values():
            if r.group is not None:
                members.setdefault(r.group, []).append(r)
        healthy: Set[str] = set()
        for group, rows in members.items():
            want = max(r.shard_count for r in rows)
            shards = {r.shard_index for r in rows}
            if (shards == set(range(want))
                    and all(r.state == "live"
                            and (r.lease_age_s + (now - r.seen_at))
                            <= stale_cut for r in rows)):
                healthy.add(group)
        return healthy

    def _pick(self, exclude: Set[str]) -> Optional[ReplicaInfo]:
        """Least-loaded routable unit: fresh lease, not quarantined, not
        already tried by this request. A unit is an unsharded live
        replica OR a complete shard group (picked through its shard-0
        entry member). None -> the fleet has no capacity for this
        request (shed)."""
        now = time.monotonic()
        with self._lock:
            stale_cut = self.stale_lease_fraction * self._lost_after_s
            healthy_groups = self._healthy_groups(now, stale_cut)
            candidates = [
                r for r in self._table.values()
                if r.state == "live" and r.worker_id not in exclude
                and self._quarantine.get(r.worker_id, 0.0) <= now
                and (r.lease_age_s + (now - r.seen_at)) <= stale_cut
                and (r.group is None
                     or (r.shard_index == 0
                         and r.group in healthy_groups))
            ]
            if not candidates:
                return None

            def score(r: ReplicaInfo) -> float:
                return r.load + self._inflight.get(r.worker_id, 0)

            best = min(score(r) for r in candidates)
            tied = sorted((r for r in candidates if score(r) == best),
                          key=lambda r: r.name)
            # Round-robin among equally-idle replicas: a sequential client
            # (inflight always 0 at pick time) must not pin one replica.
            self._rr += 1
            return tied[self._rr % len(tied)]

    def _quarantine_replica(self, info: ReplicaInfo) -> None:
        with self._lock:
            self._quarantine[info.worker_id] = (time.monotonic()
                                                + self.quarantine_s)

    # ------------------------------------------------------------- requests

    def predict(self, data, model: Optional[str] = None,
                timeout_s: Optional[float] = None) -> np.ndarray:
        payload: Dict[str, Any] = {"data": np.asarray(data).tolist()}
        if model is not None:
            payload["model"] = model
        out = self._request("predict", payload, timeout_s, idempotent=True)
        return np.asarray(out["predictions"])

    def generate(self, prompt_ids, n_steps: int,
                 model: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 **sampling) -> List[int]:
        payload: Dict[str, Any] = {
            "prompt_ids": [int(t) for t in prompt_ids],
            "n_steps": int(n_steps)}
        payload.update(sampling)
        if model is not None:
            payload["model"] = model
        out = self._request("generate", payload, timeout_s,
                            idempotent=False)
        return [int(t) for t in out["ids"]]

    def _request(self, route: str, payload: dict,
                 timeout_s: Optional[float], idempotent: bool) -> dict:
        # Mint the request's trace context: this span is the ROOT of the
        # cross-process tree, its (trace_id, span_id) travel to every
        # replica attempt on the X-DL4J-Trace header (post_json reads the
        # binding), and replica-side spans parent to it in the federated
        # timeline — across failover, across processes.
        ctx = _prop.mint()
        with _obs.tracer.span(f"router.{route}", cat="fleet",
                              span_ctx=ctx, route=route), _prop.bound(ctx):
            return self._request_inner(route, payload, timeout_s,
                                       idempotent)

    def _request_inner(self, route: str, payload: dict,
                       timeout_s: Optional[float], idempotent: bool) -> dict:
        budget = (self.request_timeout_s if timeout_s is None
                  else float(timeout_s))
        t0 = time.monotonic()
        deadline = t0 + budget
        tried_failed: Set[str] = set()
        tried_saturated: Set[str] = set()
        first_fail: List[Optional[float]] = [None]

        def note_failure(info: ReplicaInfo) -> None:
            tried_failed.add(info.worker_id)
            self._quarantine_replica(info)
            if first_fail[0] is None:
                first_fail[0] = time.monotonic()

        def once() -> dict:
            rep = self._pick(exclude=tried_failed | tried_saturated)
            if rep is None and time.monotonic() < deadline:
                # The table may be one poll interval stale (a replica that
                # just rejoined after a drain or reload is not visible
                # yet).  Refresh membership once before shedding — cheap
                # (one coordinator RPC, no per-replica /metrics scrape)
                # and single-flight, so saturated traffic can't dogpile.
                try:
                    self._refresh_membership_shared()
                except Exception:
                    pass
                rep = self._pick(exclude=tried_failed | tried_saturated)
            if rep is None:
                raise ServerOverloadedError(
                    f"fleet saturated: no live replica can take this "
                    f"{route} (tried {len(tried_failed | tried_saturated)})")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise _Failover("request deadline exhausted")
            attempt_budget = (remaining if self.attempt_timeout_s is None
                              else min(remaining, self.attempt_timeout_s))
            wid = rep.worker_id
            with self._lock:
                self._inflight[wid] = self._inflight.get(wid, 0) + 1
            try:
                # Each attempt is its own child span: a failover renders
                # as N attempt spans (the failed ones carry `error`)
                # under one router.<route> root.
                with _obs.tracer.span("router.attempt", cat="fleet",
                                      replica=rep.name):
                    return post_json(rep.url + "/" + route, payload,
                                     timeout_s=attempt_budget)
            except urllib.error.HTTPError as e:
                body = _error_body(e)
                if e.code == 503:
                    # Never admitted (shedding / draining / warming):
                    # always safe to try another replica.
                    tried_saturated.add(rep.worker_id)
                    raise _Failover(f"{rep.name}: 503 {body.get('error')}")
                if 400 <= e.code < 500:
                    raise UpstreamError(e.code, body)
                note_failure(rep)
                if idempotent:
                    raise _Failover(f"{rep.name}: HTTP {e.code}")
                # Carry the upstream reason: "HTTP 500" alone hides the
                # difference between a decode crash and a shard-group
                # member death, and the caller only gets one shot at it.
                raise PartialFailureError(
                    f"{route} failed on {rep.name} after admission "
                    f"(HTTP {e.code}: {body.get('error')}); "
                    "not retried: non-idempotent")
            except (OSError, TimeoutError) as e:
                cause = _unwrap(e)
                refused = isinstance(cause, ConnectionRefusedError)
                note_failure(rep)
                if idempotent or refused:
                    # Refused = the request never left the router; safe
                    # even for generation.
                    raise _Failover(
                        f"{rep.name}: {type(cause).__name__}: {cause}")
                raise PartialFailureError(
                    f"{route} on {rep.name} died after admission "
                    f"({type(cause).__name__}); a partial generation is "
                    f"never blind-retried")
            finally:
                with self._lock:
                    n = self._inflight.get(wid, 1) - 1
                    if n > 0:
                        self._inflight[wid] = n
                    else:
                        # Drop zeroed entries so counters for replicas
                        # that left the fleet don't accumulate.
                        self._inflight.pop(wid, None)

        bo = Backoff(base_s=0.02, max_s=0.25,
                     tries=max(2, self.failover_tries),
                     max_elapsed_s=budget)
        try:
            out = bo.run(once, retry_on=(_Failover,),
                         describe=f"router {route}")
        except ServerOverloadedError:
            self._count("shed")
            _fev.record_event("shed", route=route)
            raise
        except (PartialFailureError, UpstreamError, RetryError):
            self._count("failed")
            raise
        now = time.monotonic()
        if first_fail[0] is not None:
            seconds = now - first_fail[0]
            _m.ROUTER_FAILOVER_SECONDS.observe(seconds)
            _fev.record_event("failover", route=route,
                              seconds=round(seconds, 4))
            self._count("failover")
        else:
            self._count("ok")
        with self._lock:
            self._latencies.append(now - t0)
        return out

    def _count(self, outcome: str) -> None:
        _m.ROUTER_REQUESTS.labels(outcome=outcome).inc()
        with self._lock:
            self._counts[outcome] = self._counts.get(outcome, 0) + 1

    # ------------------------------------------------------------------ slo

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def load_stats(self) -> Dict[str, Any]:
        """The autoscaler's input: live capacity, aggregate load, request
        p99 over the recent window, and outcome counters."""
        with self._lock:
            live = [r for r in self._table.values() if r.state == "live"]
            total_load = sum(r.load + self._inflight.get(r.worker_id, 0)
                             for r in live)
            lat = sorted(self._latencies)
            counts = dict(self._counts)
            dead = self._dead_total
        p99 = lat[int(0.99 * (len(lat) - 1))] if lat else None
        return {"live": len(live), "dead": dead,
                "total_load": total_load, "p99_s": p99, "counts": counts}


# ------------------------------------------------------------- http front


def _make_router_handler(router: FleetRouter):
    """The router's own HTTP surface — the same request/metrics routes a
    replica exposes, so clients can't tell they moved behind a fleet."""

    class Handler(BaseHTTPRequestHandler):
        # Keep-alive (see serving/http.py): scrapers hold one persistent
        # connection instead of a dial per poll.
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _json(self, obj, code=200, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, e: Exception):
            if isinstance(e, ServingError):
                headers = ({"Retry-After": str(e.retry_after)}
                           if e.retry_after is not None else None)
                return self._json(e.payload(), e.status, headers=headers)
            if isinstance(e, RetryError):
                return self._json(
                    {"error": str(e), "attempts": e.attempts,
                     "elapsed_s": round(e.elapsed, 4)}, 502)
            if isinstance(e, (KeyError, ValueError, json.JSONDecodeError)):
                return self._json({"error": f"bad request: {e}"}, 400)
            return self._json({"error": str(e)}, 500)

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/health":
                stats = router.load_stats()
                self._json({"status": "ok", "live": stats["live"]})
            elif url.path == "/fleet":
                self._json({"replicas": router.table(),
                            "stats": router.load_stats()})
            elif url.path == "/metrics":
                q = parse_qs(url.query)
                fmt = (q.get("format") or ["prometheus"])[0]
                names = (q["names"][0].split(",") if q.get("names")
                         else None)
                body, ctype = _obs.prometheus_payload(fmt, names=names)
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif url.path == "/fleet/metrics":
                # Fleet-wide federation: every live member's families
                # merged under a worker_id label.
                try:
                    body = router.aggregator().federate_metrics().encode()
                except Exception as e:
                    return self._json(
                        {"error": f"{type(e).__name__}: {e}"}, 502)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif url.path == "/api/trace":
                # Merged fleet timeline (Perfetto-loadable): the router's
                # own span ring plus every member's.
                try:
                    self._json(router.aggregator().federate_trace())
                except Exception as e:
                    self._json({"error": f"{type(e).__name__}: {e}"}, 502)
            elif url.path == "/fleet/slo":
                # Burn-rate evaluation over the federated exposition; a
                # page-severity burn POSTs flight dumps to the offenders
                # as a side effect (rate-limited replica-side).
                try:
                    self._json(router.fleet_slo())
                except Exception as e:
                    self._json({"error": f"{type(e).__name__}: {e}"}, 502)
            elif url.path == "/v1/tenants":
                try:
                    self._json(router.fleet_tenants())
                except Exception as e:
                    self._json({"error": f"{type(e).__name__}: {e}"}, 502)
            else:
                self._json({"error": "not found",
                            "routes": ["/health", "/fleet", "/metrics",
                                       "/fleet/metrics", "/fleet/slo",
                                       "/v1/tenants", "/api/trace",
                                       "/predict", "/generate"]}, 404)

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length))

        def do_POST(self):
            if self.path not in ("/predict", "/generate"):
                return self._json({"error": "not found"}, 404)
            try:
                payload = self._payload()
                ms = payload.pop("timeout_ms", None)
                timeout_s = None if ms is None else float(ms) / 1000.0
                if self.path == "/predict":
                    preds = router.predict(payload["data"],
                                           model=payload.get("model"),
                                           timeout_s=timeout_s)
                    return self._json({"predictions": preds.tolist()})
                sampling = {k: payload[k] for k in
                            ("temperature", "top_k", "top_p", "seed",
                             "eos_id") if k in payload}
                ids = router.generate(payload["prompt_ids"],
                                      int(payload["n_steps"]),
                                      model=payload.get("model"),
                                      timeout_s=timeout_s, **sampling)
                return self._json({"ids": ids})
            except Exception as e:
                return self._error(e)

    return Handler
