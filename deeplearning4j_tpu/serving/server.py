"""The serving facade: `InferenceServer`, now a multi-model host.

The PR 5 single-model server (`deeplearning4j_tpu/serving.py`) became this
package; the constructor, `from_checkpoint`, `predict`, `wait_ready`,
`url`, `stop` and the HTTP surface (`/health`, `/healthz`, `/metrics`,
`/predict`) are unchanged for existing callers. What's new underneath:

- admission goes through a per-model `ShapeBucketBatcher` (bounded queue,
  bucket-ladder padding, deadline/cancellation drops) instead of one
  unbounded queue + one fixed compile shape;
- `add_model(name, net=..., path=...)` hosts several models in one
  process under a `ModelHost` HBM budget (LRU eviction + reload);
- LM engines with a KV-cached decode path get a continuous-batching
  `GenerationScheduler` (`generate()`, `POST /generate`);
- warmup drives EVERY batch bucket (and every prompt bucket + the decode
  step) through the `compilation/` AOT store, so mixed-shape traffic
  never compiles post-startup.
"""

from __future__ import annotations

import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.observability.ledger import ledger as _ledger
from deeplearning4j_tpu.serving import metrics as _m
from deeplearning4j_tpu.serving.batcher import (
    ShapeBucketBatcher,
    canonicalize_features,
)
from deeplearning4j_tpu.serving.errors import (
    InputValidationError,
    ModelNotReadyError,
    RequestTimeoutError,
    ServerOverloadedError,
    ServingError,
)
from deeplearning4j_tpu.serving.host import ModelHost
from deeplearning4j_tpu.serving.scheduler import GenerationScheduler

_UNSET = object()


class InferenceServer:
    """HTTP predict/generate server over trained engines (anything with
    `output(x)`; LM generation needs a ComputationGraph with a KV-cached
    attention decode path).

    `max_batch_size` bounds the LARGEST padded compile shape; requests pad
    to the smallest bucket in `batch_buckets` (powers of two up to
    `max_batch_size` by default). `max_delay_ms` is the coalescing window.
    With `warmup=True`, `start()` returns immediately and compiles every
    bucket on a background thread; poll `GET /healthz` or `wait_ready()`
    before sending traffic. `hbm_budget_bytes` turns on LRU eviction of
    cold checkpoint-backed models.
    """

    def __init__(self, net=None, port: int = 0, host: str = "127.0.0.1",
                 max_batch_size: int = 32, max_delay_ms: float = 5.0,
                 predict_timeout_s: Optional[float] = 300.0,
                 warmup: bool = False,
                 warmup_shape: Optional[Tuple[int, ...]] = None,
                 batch_buckets: Optional[Sequence[int]] = None,
                 queue_depth: int = 256,
                 hbm_budget_bytes: Optional[int] = None,
                 decode_slots: int = 4,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 generate_queue_depth: int = 64,
                 scheduler_mode: str = "continuous",
                 kv_cache: str = "dense",
                 kv_page_size: int = 64,
                 kv_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 draft=None, spec_k: int = 4,
                 model_parallel: int = 1,
                 default_model: str = "default"):
        self.host = host
        self.port = port
        # How long predict() waits for its batch; the first request after a
        # model/shape change pays a fresh XLA compile, so the default is
        # generous. None waits indefinitely.
        self.predict_timeout_s = predict_timeout_s
        self.max_batch_size = int(max_batch_size)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.warmup = bool(warmup)
        self.warmup_shape = (None if warmup_shape is None
                             else tuple(warmup_shape))
        self.batch_buckets = batch_buckets
        self.queue_depth = int(queue_depth)
        self.decode_slots = int(decode_slots)
        self.prompt_buckets = prompt_buckets
        self.generate_queue_depth = int(generate_queue_depth)
        self.scheduler_mode = scheduler_mode
        # Paged-KV / prefix-cache / speculative-decoding defaults
        # (per-model overrides in add_model). kv_cache="paged" swaps the
        # dense DecodeStepper for the page-pool stepper; `draft` is a
        # small zoo LM proposing spec_k tokens per decode round.
        self.kv_cache = kv_cache
        self.kv_page_size = int(kv_page_size)
        self.kv_pages = kv_pages
        self.prefix_cache = prefix_cache
        self.draft = draft
        self.spec_k = int(spec_k)
        # Tensor-parallel serving (PERF.md §28): n > 1 builds a
        # ("data", "model") mesh over this process's devices at attach
        # time, shards each hosted model's params over the model axis
        # (`parallel/mesh.shard_params` head-aware rules) and runs the
        # decode loop under the matching ParallelContext — per-chip HBM
        # drops ~1/n and XLA inserts the collectives.
        self.model_parallel = int(model_parallel)
        self.default_model = default_model
        self._contexts: dict = {}  # ways -> shared ParallelContext
        self.models = ModelHost(hbm_budget_bytes=hbm_budget_bytes,
                                on_load=self._attach)
        self._ready = threading.Event()
        self._ready.set()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._warmup_thread: Optional[threading.Thread] = None
        if net is not None:
            self.add_model(default_model, net=net)

    @classmethod
    def from_checkpoint(cls, path, **kwargs) -> "InferenceServer":
        """Serve straight from a checkpoint on disk: a sharded checkpoint
        directory (a committed step or a `CheckpointManager` root — latest
        committed step wins) or a legacy model ZIP. The deploy path is one
        call: train anywhere, point the server at the checkpoint store —
        with `warmup=True` the checkpointed model is pre-compiled before
        the first request arrives (watch `GET /healthz` for "ready").
        Keeping `path` on the default model makes it evictable (and
        reloadable) under an `hbm_budget_bytes`."""
        server = cls(None, **kwargs)
        server.add_model(server.default_model, path=path)
        return server

    # --------------------------------------------------------------- models

    @property
    def net(self):
        """The default model's engine (the PR 5 single-model attribute)."""
        return self.models.get(self.default_model).net

    def add_model(self, name: str, net=None, path=None, *,
                  max_batch_size: Optional[int] = None,
                  batch_buckets: Optional[Sequence[int]] = None,
                  max_delay_ms: Optional[float] = None,
                  queue_depth: Optional[int] = None,
                  warmup_shape: Optional[Tuple[int, ...]] = None,
                  lm: object = "auto",
                  decode_slots: Optional[int] = None,
                  prompt_buckets: Optional[Sequence[int]] = None,
                  generate_queue_depth: Optional[int] = None,
                  scheduler_mode: Optional[str] = None,
                  kv_cache: Optional[str] = None,
                  kv_page_size: Optional[int] = None,
                  kv_pages: object = _UNSET,
                  prefix_cache: object = _UNSET,
                  draft: object = _UNSET,
                  spec_k: Optional[int] = None,
                  model_parallel: Optional[int] = None,
                  pinned: Optional[bool] = None):
        """Host another model (server-level knobs are the defaults). With
        `path`, the checkpoint loads now and can be LRU-evicted/reloaded
        under the HBM budget; a live `net` with no path is pinned."""
        if net is None:
            if path is None:
                raise ValueError("add_model needs a net or a path")
            from deeplearning4j_tpu.checkpoint.legacy import load_any

            net = load_any(path)
        opts = {
            "max_batch_size": (self.max_batch_size if max_batch_size is None
                               else int(max_batch_size)),
            "batch_buckets": (self.batch_buckets if batch_buckets is None
                              else batch_buckets),
            "max_delay_s": (self.max_delay_s if max_delay_ms is None
                            else float(max_delay_ms) / 1000.0),
            "queue_depth": (self.queue_depth if queue_depth is None
                            else int(queue_depth)),
            "warmup_shape": (self.warmup_shape if warmup_shape is None
                             else tuple(warmup_shape)),
            "lm": lm,
            "decode_slots": (self.decode_slots if decode_slots is None
                             else int(decode_slots)),
            "prompt_buckets": (self.prompt_buckets if prompt_buckets is None
                               else prompt_buckets),
            "generate_queue_depth": (
                self.generate_queue_depth if generate_queue_depth is None
                else int(generate_queue_depth)),
            "scheduler_mode": (self.scheduler_mode if scheduler_mode is None
                               else scheduler_mode),
            "kv_cache": (self.kv_cache if kv_cache is None else kv_cache),
            "kv_page_size": (self.kv_page_size if kv_page_size is None
                             else int(kv_page_size)),
            "kv_pages": (self.kv_pages if kv_pages is _UNSET else kv_pages),
            "prefix_cache": (self.prefix_cache if prefix_cache is _UNSET
                             else prefix_cache),
            "draft": (self.draft if draft is _UNSET else draft),
            "spec_k": (self.spec_k if spec_k is None else int(spec_k)),
            "model_parallel": (self.model_parallel if model_parallel is None
                               else int(model_parallel)),
        }
        return self.models.add(name, net=net, path=path, pinned=pinned,
                               **opts)

    def _parallel_context(self, ways: int):
        """The server's ("data", "model") mesh context for `ways`-way
        tensor parallelism, built once and shared by every model that
        asks for the same width (one mesh -> one jit-cache/fingerprint
        identity across models and reloads)."""
        import jax

        from deeplearning4j_tpu.parallel import mesh as mesh_mod
        from deeplearning4j_tpu.parallel.context import ParallelContext

        ctx = self._contexts.get(ways)
        if ctx is None:
            n_dev = len(jax.devices())
            if ways > n_dev:
                raise ValueError(
                    f"model_parallel={ways} needs {ways} devices; this "
                    f"process has {n_dev}")
            mesh = mesh_mod.create_mesh((1, ways), ("data", "model"))
            ctx = ParallelContext(mesh, model_axis="model")
            self._contexts[ways] = ctx
        return ctx

    def _attach(self, model) -> None:
        """ModelHost on_load hook: build + start the model's serving
        runtime (runs at add time and again after an eviction reload)."""
        o = model.options
        ways = int(o.get("model_parallel") or 1)
        if ways > 1:
            from deeplearning4j_tpu.parallel import mesh as mesh_mod
            from deeplearning4j_tpu.serving.host import sharding_desc

            ctx = self._parallel_context(ways)
            # Restore-onto-mesh: the freshly loaded (or reloaded) params
            # land sharded before any program traces against them.
            mesh_mod.shard_params(model.net, ctx.mesh, model_axis="model")
            model.context = ctx
            model.sharding = sharding_desc(ctx)
        else:
            model.context = None
            model.sharding = "none"
        model.batcher = ShapeBucketBatcher(
            model.net, model_name=model.name,
            max_batch_size=o["max_batch_size"], buckets=o["batch_buckets"],
            max_delay_s=o["max_delay_s"], queue_depth=o["queue_depth"],
            warmup_shape=o["warmup_shape"]).start()
        # Multi-tenant hooks: warmup and per-request dispatch resolve
        # adapter-merged trees through the ServedModel registry (lazy, so
        # adapters loaded after _attach are picked up too).
        model.batcher.param_variants = (
            lambda: [model.adapter_params(n)
                     for n in sorted(model.adapters)])
        if o["lm"] and hasattr(model.net, "_get_jit"):
            try:
                model.scheduler = GenerationScheduler(
                    model.net, model_name=model.name,
                    slots=o["decode_slots"],
                    prompt_buckets=o["prompt_buckets"],
                    queue_depth=o["generate_queue_depth"],
                    mode=o["scheduler_mode"],
                    kv=o["kv_cache"], page_size=o["kv_page_size"],
                    kv_pages=o["kv_pages"],
                    prefix_cache=o["prefix_cache"],
                    draft=o["draft"], spec_k=o["spec_k"],
                    context=model.context)
                model.scheduler.adapter_params = model.adapter_params
                model.scheduler.adapter_names = (
                    lambda: sorted(model.adapters))
                model.scheduler.start()
            except Exception:
                # lm="auto" probes: a model without a KV-cached decode path
                # simply doesn't serve /generate.
                if o["lm"] is not True:
                    model.scheduler = None
                else:
                    raise
        model.ready.set()

    # ------------------------------------------------------------- adapters

    def load_adapter(self, name: str, path=None, net=None,
                     model: Optional[str] = None,
                     pinned: bool = True):
        """Host a LoRA adapter next to a resident base model. `path` loads
        an adapter checkpoint (`checkpoint/adapters.py` — refused unless
        its base fingerprint matches the resident base); `net` extracts
        the delta straight from a live fine-tuned engine. Requests then
        select it with `adapter=name` on predict/generate — the base stays
        resident once, every adapter adds only its rank-r delta to HBM,
        and (after warmup) hot-swapping adapters compiles nothing."""
        from deeplearning4j_tpu.nn import lora as lora_mod

        served = self.models.get(self.default_model if model is None
                                 else model)
        if (path is None) == (net is None):
            raise ValueError("load_adapter needs exactly one of path/net")
        if path is not None:
            from deeplearning4j_tpu.checkpoint import adapters as _adapters

            tree = _adapters.load_adapter(path, base_net=served.net)
        else:
            tree = lora_mod.extract_adapter(net.params_tree)
            if not tree:
                raise ValueError(
                    "net has no LoRA adapter leaves to extract")
        return served.add_adapter(name, tree, pinned=pinned)

    def _resolve_adapter(self, served, adapter: Optional[str]):
        """Adapter name -> merged params tree (None passes through); an
        unknown name is a 400, not a 500. Counting happens at OUTCOME
        time (`_count_adapter`), not here — the outcome label needs the
        request's fate."""
        if adapter is None:
            return None
        try:
            params = served.adapter_params(str(adapter))
        except KeyError as e:
            raise InputValidationError(str(e.args[0]) if e.args else str(e))
        return params

    @staticmethod
    def _count_adapter(model: str, adapter: Optional[str],
                       outcome: str) -> None:
        """dl4j_adapter_requests_total{model,adapter,outcome} — per-tenant
        error rates without joining the ledger. Base-model traffic
        (adapter=None) counts only under dl4j_requests_total."""
        if adapter is not None:
            _m.ADAPTER_REQUESTS.labels(
                model=model, adapter=str(adapter),
                outcome="failed" if outcome == "invalid" else outcome).inc()

    # -------------------------------------------------------------- warmup

    @property
    def _status(self) -> str:
        # Derived from the Event (its own lock) so the warmup thread and
        # the HTTP handlers never race on a plain attribute.
        return "ready" if self._ready.is_set() else "warming"

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until warmup finished (immediately True without warmup)."""
        return self._ready.wait(timeout)

    def _warmup_run(self) -> None:
        """Drive every model's batch-bucket ladder (and, for LMs, every
        prompt bucket + the decode step) through the AOT store so no real
        request triggers an XLA compile. Failures flip to "ready" anyway —
        the first real request then pays the compile, exactly the
        no-warmup behavior."""
        try:
            for name in self.models.names():
                model = self.models.get(name)
                try:
                    if model.batcher is not None:
                        model.batcher.warm()
                    if model.scheduler is not None:
                        model.scheduler.warmup()
                except Exception as e:
                    import warnings

                    warnings.warn(
                        f"serving warmup failed ({type(e).__name__}: {e}); "
                        "the first request will pay the compile")
                finally:
                    model.ready.set()
        finally:
            self._ready.set()

    # ------------------------------------------------------------- predict

    def predict(self, data, model: Optional[str] = None,
                timeout_s: object = _UNSET,
                adapter: Optional[str] = None) -> np.ndarray:
        """In-process entry (the HTTP handler calls this too). Observed once
        per caller request into the latency histograms, however many
        bucket-sized chunks it splits into. `adapter` routes the request
        through a loaded LoRA delta over the same resident base."""
        name = self.default_model if model is None else model
        timeout = (self.predict_timeout_s if timeout_s is _UNSET
                   else timeout_s)
        t0 = time.perf_counter()
        rec = _ledger.open(route="predict", model=name,
                           adapter="" if adapter is None else str(adapter))
        try:
            served = self.models.get(name)
            params = self._resolve_adapter(served, adapter)
            arr = canonicalize_features(served.net, data)
            rec.add_tokens_in(int(arr.shape[0]))  # predict: rows in
            result = self._predict_rows(served, arr, timeout,
                                        adapter=adapter, params=params,
                                        ledger_rec=rec)
        except Exception as e:
            _m.REQUESTS_LEGACY.labels(outcome="error").inc()
            _m.REQUESTS.labels(model=name, route="predict",
                               outcome=self._outcome(e)).inc()
            self._count_adapter(name, adapter, self._ledger_outcome(e))
            _ledger.close(rec, outcome=self._ledger_outcome(e))
            raise
        _m.REQUESTS_LEGACY.labels(outcome="ok").inc()
        _m.REQUESTS.labels(model=name, route="predict", outcome="ok").inc()
        self._count_adapter(name, adapter, "ok")
        _ledger.close(rec, outcome="ok")
        dt = time.perf_counter() - t0
        _m.REQ_LATENCY.observe(dt)
        _m.REQUEST_SECONDS.labels(model=name, route="predict").observe(dt)
        return result

    @staticmethod
    def _outcome(e: Exception) -> str:
        if isinstance(e, ServerOverloadedError):
            return "shed"
        if isinstance(e, (InputValidationError, ModelNotReadyError)):
            return "invalid"
        if isinstance(e, TimeoutError):
            # The batcher/scheduler already counted "timeout" when it
            # dropped the request; don't double count under it.
            return "error"
        return "error"

    @staticmethod
    def _ledger_outcome(e: Exception) -> str:
        """Ledger/adapter outcome vocabulary (ok/timeout/shed/failed plus
        'invalid', which _count_adapter folds into 'failed')."""
        if isinstance(e, ServerOverloadedError):
            return "shed"
        if isinstance(e, (RequestTimeoutError, TimeoutError)):
            return "timeout"
        if isinstance(e, (InputValidationError, ModelNotReadyError)):
            return "invalid"
        return "failed"

    def _predict_rows(self, served, arr: np.ndarray,
                      timeout: Optional[float],
                      adapter: Optional[str] = None,
                      params=None, ledger_rec=None) -> np.ndarray:
        deadline = None if timeout is None else time.monotonic() + timeout
        size = served.batcher.max_batch_size
        # Split oversized requests into bucket-sized chunks; all chunks are
        # queued up front so they coalesce into consecutive batches.
        chunks = ([arr[i:i + size] for i in range(0, arr.shape[0], size)]
                  or [arr])
        pendings = [served.batcher.submit(c, deadline, adapter=adapter,
                                          params=params,
                                          ledger_rec=ledger_rec)
                    for c in chunks]
        results = []
        for p in pendings:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            p.event.wait(timeout=remaining)
            if not p.event.is_set():
                for q in pendings:
                    q.cancelled = True  # the batcher drops + counts them
                raise TimeoutError(
                    f"prediction timed out after {timeout}s "
                    "(cold XLA compiles can be slow; raise predict_timeout_s "
                    "or pass None to wait indefinitely)")
            if p.error == "__deadline__":
                for q in pendings:
                    q.cancelled = True
                raise RequestTimeoutError(
                    f"prediction deadline ({timeout}s) expired in the "
                    "batch queue")
            if p.error is not None:
                raise RuntimeError(p.error)
            results.append(p.result)
        if len(results) == 1:
            return results[0]
        return np.concatenate(results, axis=0)

    # ------------------------------------------------------------ generate

    def generate(self, prompt_ids, n_steps: int,
                 model: Optional[str] = None,
                 timeout_s: object = _UNSET,
                 adapter: Optional[str] = None, **sampling):
        """Continuously-batched LM generation: returns the full token list
        (prompt + generated), float-close to `generate_lm(use_cache=True)`
        for the same seed/sampling knobs. `adapter` decodes through a
        loaded LoRA delta; slots on different adapters share the decode
        loop (grouped dispatch per round)."""
        name = self.default_model if model is None else model
        timeout = (self.predict_timeout_s if timeout_s is _UNSET
                   else timeout_s)
        t0 = time.perf_counter()
        rec = _ledger.open(route="generate", model=name,
                           adapter="" if adapter is None else str(adapter),
                           tokens_in=len(prompt_ids))
        try:
            served = self.models.get(name)
            if served.scheduler is None:
                raise InputValidationError(
                    f"model {name!r} does not serve generation (no "
                    "KV-cached decode path)")
            ids = served.scheduler.generate(prompt_ids, n_steps,
                                            timeout_s=timeout,
                                            adapter=adapter,
                                            ledger_rec=rec, **sampling)
        except Exception as e:
            _m.REQUESTS.labels(model=name, route="generate",
                               outcome=self._outcome(e)).inc()
            self._count_adapter(name, adapter, self._ledger_outcome(e))
            _ledger.close(rec, outcome=self._ledger_outcome(e))
            raise
        _m.REQUESTS.labels(model=name, route="generate",
                           outcome="ok").inc()
        self._count_adapter(name, adapter, "ok")
        _ledger.close(rec, outcome="ok")
        _m.REQUEST_SECONDS.labels(model=name, route="generate").observe(
            time.perf_counter() - t0)
        return ids

    # ------------------------------------------------------------- tenants

    def tenant_snapshot(self) -> list:
        """`GET /v1/tenants` payload: the ledger's per-(model, adapter)
        rollups joined with adapter HBM residency from the model host —
        requests, tokens in/out, attributed device-seconds, mean queue
        wait, and each adapter's share of its base model's HBM."""
        rows = _ledger.tenants()
        for row in rows:
            row["hbm_bytes"] = None
            row["hbm_share"] = None
            try:
                served = self.models.get(row["model"])
            except Exception:
                continue
            info = served.adapters.get(row["adapter"])
            if info is not None:
                row["hbm_bytes"] = int(info.get("bytes") or 0)
                base = getattr(served, "hbm_bytes", 0) or 0
                if base:
                    row["hbm_share"] = row["hbm_bytes"] / float(base)
        return rows

    # ---------------------------------------------------------------- http

    def start(self) -> "InferenceServer":
        from deeplearning4j_tpu.serving.http import make_handler

        _m.QUEUE_DEPTH.set_function(self._total_queue_depth)
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          make_handler(self))
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._serve_thread.start()
        if self.warmup:
            # The port is already bound and /healthz answers "warming", so
            # orchestrators can watch readiness while the models compile.
            self._ready.clear()
            for name in self.models.names():
                self.models.get(name).ready.clear()
            self._warmup_thread = threading.Thread(
                target=self._warmup_run, name="dl4j-serving-warmup",
                daemon=True)
            self._warmup_thread.start()
        return self

    def _total_queue_depth(self) -> int:
        total = 0
        for name in self.models.names():
            m = self.models._models.get(name)
            if m is not None and m.batcher is not None:
                total += m.batcher.qsize()
        return total

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        _m.QUEUE_DEPTH.set_function(None)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.models.stop()
