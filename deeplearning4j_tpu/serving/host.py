"""Multi-model hosting with HBM budgets and LRU eviction.

One serving process fronts several models. Each entry tracks an estimated
device-resident footprint — the summed `nbytes` of its param/state leaves
once loaded, or the checkpoint COMMIT manifest's file sizes before the
first load (no array data read). When the sum of resident footprints
exceeds `hbm_budget_bytes`, the host evicts the least-recently-USED
unpinned model: its batcher/scheduler stop, the engine reference drops
(freeing device buffers), and the entry stays registered so the next
request triggers a reload (503 `Retry-After` while it happens, never a
silent stall). Models constructed from a live net (no path) are pinned —
there is nothing to reload them from.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.analysis.locktrace import named_rlock
from deeplearning4j_tpu.serving import metrics as _m
from deeplearning4j_tpu.serving.errors import (
    ModelNotFoundError,
    ModelNotReadyError,
)


def estimate_hbm_bytes(net) -> int:
    """Summed `nbytes` over the engine's param + state leaves (the arrays
    actually resident on device once the model serves)."""
    import jax

    total = 0
    for attr in ("params_tree", "state"):
        tree = getattr(net, attr, None)
        if tree is not None:
            total += sum(int(getattr(leaf, "nbytes", 0))
                         for leaf in jax.tree_util.tree_leaves(tree))
    return total


def per_chip_bytes(tree) -> int:
    """Summed bytes of ONE device's shard of every leaf — the number that
    actually hits a single chip's HBM. `leaf.nbytes` is the GLOBAL array
    size regardless of sharding, so under N-way model parallelism it
    overstates per-chip residency by ~N; the addressable-shard walk is
    what the sharded-decode bench gates on."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += int(shards[0].data.nbytes)
        else:
            total += int(getattr(leaf, "nbytes", 0))
    return total


def sharding_desc(context=None) -> str:
    """Operator-facing layout string for `/v1/models` and the
    `dl4j_serving_model_sharding` info gauge: ``none`` (replicated
    single-chip serving) or ``model:<n>-way``."""
    if context is None or context.model_axis is None:
        return "none"
    n = context.axis_size("model")
    return "none" if n <= 1 else f"model:{n}-way"


def estimate_checkpoint_bytes(path) -> int:
    """Footprint estimate WITHOUT loading: the COMMIT manifest's summed
    file sizes (sharded store), the latest committed step under a manager
    root, or the ZIP size for a legacy checkpoint. 0 when unreadable —
    the estimate firms up to leaf nbytes after the first load."""
    from deeplearning4j_tpu.checkpoint import store

    path = str(path)
    try:
        if os.path.isdir(path):
            if not store.is_sharded_checkpoint(path):
                from deeplearning4j_tpu.checkpoint.manager import (
                    CheckpointManager,
                )

                latest = CheckpointManager(path).latest_path()
                if latest is None:
                    return 0
                path = latest
            commit = store.verify_checkpoint(path)
            return sum(int(s) for s in commit.get("files", {}).values())
        return int(os.path.getsize(path))
    except Exception:
        return 0


def model_dtype(net=None, path=None) -> str:
    """The serving dtype of a model: "int8" when the weights are
    post-training-quantized (any integer leaf with a `__scale` companion),
    else the first floating param leaf's dtype. For a non-resident entry
    the answer comes from the checkpoint's meta.json ("quantization" /
    "dtype_policy") without reading any array data."""
    import numpy as np

    if net is not None:
        first_float = None
        for lp in (getattr(net, "params_tree", None) or {}).values():
            if not isinstance(lp, dict):
                continue
            for k, a in lp.items():
                dt = getattr(a, "dtype", None)
                if dt is None:
                    continue
                if (np.issubdtype(dt, np.integer)
                        and k + "__scale" in lp):
                    return "int8"
                if first_float is None and jnp_floating(dt):
                    first_float = str(dt)
        return first_float or "float32"
    if path is not None:
        try:
            from deeplearning4j_tpu.checkpoint import store
            from deeplearning4j_tpu.checkpoint.manager import CheckpointManager

            path = str(path)
            if os.path.isdir(path) and not store.is_sharded_checkpoint(path):
                path = CheckpointManager(path).latest_path() or path
            meta = store.read_meta(path)
            if meta.get("quantization"):
                return "int8"
            pol = meta.get("dtype_policy")
            if pol:
                from deeplearning4j_tpu.nn.conf.dtype_policy import DtypePolicy

                return DtypePolicy.of(pol).resolved_param_dtype
        except Exception:
            pass
    return "float32"


def jnp_floating(dt) -> bool:
    import numpy as np

    try:
        import ml_dtypes

        if np.dtype(dt) in (np.dtype(ml_dtypes.bfloat16),):
            return True
    except Exception:
        pass
    try:
        return np.issubdtype(dt, np.floating)
    except Exception:
        return False


def _measure_hbm(model: "ServedModel") -> None:
    """Firm the footprint up from the leaf-nbytes estimate to measured
    device bytes (live jax.Array nbytes + the largest recorded program's
    temp+output scratch) when the runtime can report them; also registers
    the net for live-buffer attribution in `observability.memory`."""
    measured = None
    try:
        from deeplearning4j_tpu.observability import memory as _obsmem

        _obsmem.register_tree(model.name, model.net)
        measured = _obsmem.measured_model_bytes(model.net)
    except Exception:
        measured = None
    if measured:
        model.hbm_bytes = int(measured)
        model.hbm_source = "measured"
    else:
        model.hbm_source = "estimated"


class ServedModel:
    """One hosted model: the engine plus its serving runtime (batcher and,
    for LMs, the generation scheduler), readiness, and LRU bookkeeping.

    Multi-tenant serving: `adapters` holds LoRA deltas (`nn/lora.py`)
    registered next to this ONE resident base. Each entry keeps the tiny
    delta tree plus a lazily-built merged params tree (base arrays shared
    by reference — the per-adapter HBM cost is the delta alone); requests
    select an adapter by name and dispatch through the merged tree.
    Because every merged tree has the same pytree structure, all adapters
    share one compiled program per shape — hot-swapping adapters costs
    zero serving-path compiles."""

    def __init__(self, name: str, net=None, path=None, pinned=False,
                 options: Optional[dict] = None):
        self.name = name
        self.net = net
        self.path = None if path is None else str(path)
        self.pinned = bool(pinned)
        self.options = dict(options or {})
        self.batcher = None
        self.scheduler = None
        self.loading = False  # a reload is in flight off the host lock
        self.ready = threading.Event()
        self.last_used = time.monotonic()
        self.hbm_source = "estimated"
        self.hbm_bytes = (estimate_hbm_bytes(net) if net is not None
                          else estimate_checkpoint_bytes(path)
                          if path is not None else 0)
        self.dtype = model_dtype(net=net, path=path)
        # Tensor-parallel serving: the server's `_attach` shards the net
        # over a model mesh axis and records the ParallelContext + the
        # operator-facing layout string here (`sharding_desc`).
        self.context = None
        self.sharding = "none"
        # name -> {"tree": delta, "rank": int, "bytes": int,
        #          "pinned": bool, "merged": full tree or None (lazy)}
        self.adapters: Dict[str, dict] = {}

    @property
    def resident(self) -> bool:
        return self.net is not None

    def touch(self) -> None:
        self.last_used = time.monotonic()

    # ----------------------------------------------------------- adapters

    def add_adapter(self, name: str, tree, pinned: bool = True) -> dict:
        """Register one LoRA delta tree under `name` (idempotent re-adds
        of the same name replace the delta and drop its merged cache)."""
        from deeplearning4j_tpu.nn import lora as _lora

        entry = {
            "tree": tree,
            "rank": _lora.adapter_rank(tree),
            "bytes": _lora.adapter_nbytes(tree),
            "pinned": bool(pinned),
            "merged": None,
        }
        self.adapters[name] = entry
        _m.ADAPTERS_RESIDENT.labels(model=self.name).set(len(self.adapters))
        return entry

    def adapter_params(self, name: str):
        """The full serving tree for `name`: base params overlaid with the
        delta, built once and cached (the cache is dropped on eviction —
        merged trees hold references into the base arrays)."""
        entry = self.adapters.get(name)
        if entry is None:
            raise KeyError(
                f"model {self.name!r} hosts no adapter {name!r}; loaded: "
                f"{sorted(self.adapters) or '(none)'}")
        if entry["merged"] is None:
            from deeplearning4j_tpu.nn import lora as _lora

            if self.net is None:
                raise ModelNotReadyError(
                    f"model {self.name!r} is not resident; retry shortly")
            entry["merged"] = _lora.merge_adapter(self.net.params_tree,
                                                  entry["tree"])
        return entry["merged"]

    def adapter_trees(self):
        """{name: merged tree} for every registered adapter (warmup
        drives each through the compiled-program path)."""
        return {n: self.adapter_params(n) for n in sorted(self.adapters)}

    def adapter_rows(self) -> List[dict]:
        """`/v1/models` sub-rows for this model's adapters."""
        return [{
            "name": n,
            "rank": int(e["rank"]),
            "bytes": int(e["bytes"]),
            "pinned": bool(e["pinned"]),
        } for n, e in sorted(self.adapters.items())]


class ModelHost:
    """Registry + admission point for every hosted model. All structural
    mutation (add / load / evict) happens under one lock; the hot path
    (`get` on a resident model) only touches the LRU stamp."""

    def __init__(self, hbm_budget_bytes: Optional[int] = None,
                 on_load: Optional[Callable[[ServedModel], None]] = None,
                 on_evict: Optional[Callable[[ServedModel], None]] = None):
        self.hbm_budget_bytes = hbm_budget_bytes
        self.on_load = on_load      # server attaches batcher/scheduler here
        self.on_evict = on_evict
        self._lock = named_rlock("serving.host")
        self._models: Dict[str, ServedModel] = {}
        _m.MODELS_RESIDENT.set_function(
            lambda: sum(1 for m in self._models.values() if m.resident))

    # ----------------------------------------------------------- registry

    def add(self, name: str, net=None, path=None, pinned=None,
            **options) -> ServedModel:
        if net is None and path is None:
            raise ValueError("add() needs a live net or a checkpoint path")
        if pinned is None:
            pinned = path is None  # nothing to reload a net-only model from
        model = ServedModel(name, net=net, path=path, pinned=pinned,
                            options=options)
        with self._lock:
            if name in self._models:
                raise ValueError(f"model {name!r} is already hosted")
            self._models[name] = model
            if model.net is not None:
                _measure_hbm(model)
            _m.MODEL_HBM_BYTES.labels(model=name).set(model.hbm_bytes)
            _m.MODEL_DTYPE.labels(model=name, dtype=model.dtype).set(1)
            if model.net is not None and self.on_load is not None:
                self.on_load(model)
            # After on_load: the attach hook is what shards the net and
            # stamps the layout.
            _m.MODEL_SHARDING.labels(model=name,
                                     sharding=model.sharding).set(1)
            stoppables = self._enforce_budget(keep=model)
        self._stop_runtimes(stoppables)
        return model

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def get(self, name: str) -> ServedModel:
        """Resolve a model for a request: touches the LRU stamp and
        reloads an evicted entry (synchronously, under the lock — callers
        that must not block should check `.resident` first)."""
        model = self._models.get(name)
        if model is None:
            raise ModelNotFoundError(f"no model named {name!r}; hosted: "
                                     f"{self.names() or '(none)'}")
        model.touch()
        if not model.resident:
            self._reload(model)
        return model

    # ----------------------------------------------------- budget/evict

    def _reload(self, model: ServedModel) -> None:
        """Reload an evicted model. The slow synchronous load runs OFF the
        host lock: while one thread loads, `/v1/models` snapshots and
        `get()` on every OTHER model proceed — only callers of the
        reloading model see a 503 (`ModelNotReadyError`) until the load
        publishes. The first caller pays the load; concurrent callers of
        the same model are told to retry instead of queueing behind it."""
        from deeplearning4j_tpu.checkpoint.legacy import load_any
        from deeplearning4j_tpu.util.retry import with_retries

        with self._lock:
            if model.resident:
                return
            if model.loading:
                raise ModelNotReadyError(
                    f"model {model.name!r} is reloading; retry shortly")
            model.loading = True
            model.ready.clear()
        try:
            # A reload racing an atomic-rename republish can see a
            # half-moment of ENOENT; retry with backoff instead of
            # evicting the model over a publisher's rename window.
            net = with_retries(lambda: load_any(model.path),
                               retry_on=(OSError,), tries=3,
                               describe=f"model reload {model.name}")
        except Exception:
            with self._lock:
                model.loading = False
            raise
        stoppables: List = []
        try:
            with self._lock:
                try:
                    model.net = net
                    model.hbm_bytes = estimate_hbm_bytes(net)
                    _measure_hbm(model)
                    model.dtype = model_dtype(net=net)
                    _m.MODEL_HBM_BYTES.labels(model=model.name).set(
                        model.hbm_bytes)
                    _m.MODEL_DTYPE.labels(model=model.name,
                                          dtype=model.dtype).set(1)
                    if self.on_load is not None:
                        self.on_load(model)
                    _m.MODEL_SHARDING.labels(
                        model=model.name, sharding=model.sharding).set(1)
                    stoppables = self._enforce_budget(keep=model)
                except Exception:
                    # Publish failed (on_load hook, budget enforcement,
                    # ...): roll back to the evicted state so the next
                    # get() retries the load — a model stuck with
                    # loading=True would 503 forever with no recovery
                    # path.
                    try:
                        stoppables.extend(self._evict(model))
                    except Exception:
                        model.net = None
                        model.ready.clear()
                    raise
                finally:
                    model.loading = False
        finally:
            # Worker joins happen with the lock RELEASED: an eviction
            # drain must never stall snapshot()/get() on other models.
            self._stop_runtimes(stoppables)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(m.hbm_bytes for m in self._models.values()
                       if m.resident)

    def _enforce_budget(self, keep: Optional[ServedModel] = None) -> List:
        """Evict LRU unpinned resident models until under budget. `keep`
        (the model just loaded) is never evicted — a budget smaller than
        one model still serves that model. Returns the victims' detached
        runtimes for the caller to stop off-lock."""
        stoppables: List = []
        if self.hbm_budget_bytes is None:
            return stoppables
        while True:
            victims = [m for m in self._models.values()
                       if m.resident and not m.pinned and m is not keep]
            if (sum(m.hbm_bytes for m in self._models.values()
                    if m.resident) <= self.hbm_budget_bytes or not victims):
                return stoppables
            stoppables.extend(
                self._evict(min(victims, key=lambda m: m.last_used)))

    def _evict(self, model: ServedModel) -> List:
        """Evict under the host lock, but DETACH the batcher/scheduler
        instead of stopping them: `stop()` joins worker threads, and a
        join under `_lock` blocks every `get()`/`snapshot()` for the
        drain duration (JX018). Callers stop the returned runtimes after
        releasing the lock; a detached runtime drains its queue exactly
        as before, it just can't admit new work (the model is no longer
        resolvable to it)."""
        stoppables: List = []
        model.ready.clear()
        if model.batcher is not None:
            stoppables.append(model.batcher)
            model.batcher = None
        if model.scheduler is not None:
            stoppables.append(model.scheduler)
            model.scheduler = None
        if self.on_evict is not None:
            self.on_evict(model)
        # Merged adapter trees alias the base arrays: drop the caches (the
        # tiny deltas stay registered; a reload re-merges lazily against
        # the fresh base).
        for entry in model.adapters.values():
            entry["merged"] = None
        model.net = None  # drop the device buffers
        try:
            from deeplearning4j_tpu.observability import memory as _obsmem

            _obsmem.unregister_tree(model.name)
        except Exception:
            pass
        _m.MODEL_HBM_BYTES.labels(model=model.name).set(0)
        _m.EVICTIONS.labels(model=model.name).inc()
        model.hbm_source = "estimated"
        model.hbm_bytes = (estimate_checkpoint_bytes(model.path)
                           if model.path else 0)
        return stoppables

    @staticmethod
    def _stop_runtimes(stoppables: List) -> None:
        """Join detached batcher/scheduler workers — called with the host
        lock RELEASED so serving other models never waits on a drain."""
        for runtime in stoppables:
            try:
                runtime.stop()
            except Exception:
                pass

    # ---------------------------------------------------------- introspect

    def snapshot(self) -> List[dict]:
        """`GET /v1/models` payload: one row per hosted model."""
        with self._lock:
            return [{
                "name": m.name,
                "status": ("ready" if m.ready.is_set()
                           else "warming" if m.resident
                           else "loading" if m.loading else "evicted"),
                "resident": m.resident,
                "pinned": m.pinned,
                "hbm_bytes": int(m.hbm_bytes),
                "hbm_source": m.hbm_source,
                "dtype": m.dtype,
                "sharding": m.sharding,
                "path": m.path,
                "lm": m.scheduler is not None,
                "adapters": m.adapter_rows(),
            } for m in self._models.values()]

    def stop(self) -> None:
        _m.MODELS_RESIDENT.set_function(None)
        with self._lock:
            runtimes = [r for m in self._models.values()
                        for r in (m.batcher, m.scheduler) if r is not None]
        # Joins off-lock: shutdown of one model's workers must not block a
        # concurrent snapshot()/names() poll (JX018).
        self._stop_runtimes(runtimes)
