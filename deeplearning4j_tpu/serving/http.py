"""HTTP surface of the serving tier.

Routes (all JSON):

- `GET  /health`     liveness (+ hosted model names)
- `GET  /healthz`    readiness: `{"status": "warming"|"ready", "models": …}`
- `GET  /metrics`    Prometheus scrape (`?format=json` for the snapshot)
- `GET  /v1/models`  per-model status / residency / HBM estimate / loaded
                     LoRA adapters (name, rank, bytes, pinned)
- `GET  /v1/tenants` per-(model, adapter) cost rollups from the request
                     ledger: requests, tokens in/out, attributed
                     device-seconds, mean queue wait, adapter HBM share
- `POST /admin/flight-dump`  trigger a flight-recorder bundle
                     (`{"reason"?}`); rate-limited per reason, so the
                     response's `"path"` is null when a recent dump for
                     the same reason already exists
- `POST /predict`    `{"data": [[...]], "model"?, "adapter"?,
                       "timeout_ms"?}`
- `POST /generate`   `{"prompt_ids": [...], "n_steps": N, "temperature"?,
                       "top_k"?, "top_p"?, "seed"?, "eos_id"?, "model"?,
                       "adapter"?, "timeout_ms"?}`

`"adapter"` selects a LoRA delta loaded next to the model's resident base
(`InferenceServer.load_adapter`); an unknown name is a 400.

When the server is a fleet member (`server.fleet_replica` set by
`serving/fleet.py`), two admin routes appear and every predict/generate
passes through the replica's admission seam first — deterministic fleet
faults fire there and a draining replica refuses there with a clean 503:

- `POST /admin/drain`   start a graceful drain (returns immediately)
- `POST /admin/reload`  `{"path": ...}` drained rolling update: swap the
                        checkpoint, AOT-warm it, re-join the fleet; the
                        response carries the compile/warm ledger

Failure mapping is a table over the typed errors in `serving/errors.py`:
the status comes off the exception class, `Retry-After` appears whenever
the error carries one (load shedding, warming, eviction reload), plain
`TimeoutError` is a 504, malformed payloads are a 400 — a traceback-500
is reserved for genuinely unexpected failures."""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.observability import propagate as _prop
from deeplearning4j_tpu.serving.errors import ServingError


def make_handler(server):
    """Build the request-handler class bound to one `InferenceServer`."""

    class Handler(BaseHTTPRequestHandler):
        # Keep-alive: the federation aggregator (and the router's load
        # poll) scrape this surface continuously — re-dialing TCP and
        # spawning a fresh handler thread per poll is pure overhead.
        # Every response path sets Content-Length, which HTTP/1.1
        # persistence requires.
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _json(self, obj, code=200, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, e: Exception):
            if isinstance(e, ServingError):
                headers = ({"Retry-After": str(e.retry_after)}
                           if e.retry_after is not None else None)
                return self._json(e.payload(), e.status, headers=headers)
            if isinstance(e, TimeoutError):
                return self._json({"error": str(e)}, 504)
            if isinstance(e, (KeyError, ValueError, json.JSONDecodeError)):
                return self._json({"error": f"bad request: {e}"}, 400)
            return self._json({"error": str(e)}, 500)

        # ------------------------------------------------------------- GET

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/health":
                try:
                    model = type(server.net).__name__
                except Exception:
                    model = None
                self._json({"status": "ok", "model": model,
                            "models": server.models.names()})
            elif url.path == "/healthz":
                statuses = {row["name"]: row["status"]
                            for row in server.models.snapshot()}
                self._json({"status": server._status, "models": statuses})
            elif url.path == "/metrics":
                q = parse_qs(url.query)
                fmt = (q.get("format") or ["prometheus"])[0]
                names = (q["names"][0].split(",") if q.get("names")
                         else None)
                body, ctype = _obs.prometheus_payload(fmt, names=names)
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif url.path == "/api/trace":
                # This process's span ring, scrape-able by the federation
                # aggregator (same shape the UIServer exports). `since`
                # is the incremental cursor: only events recorded after
                # that `seq` are shipped.
                q = parse_qs(url.query)
                since = int(q["since"][0]) if q.get("since") else None
                self._json(_obs.tracer.export_chrome(since=since))
            elif url.path == "/v1/models":
                self._json({"models": server.models.snapshot()})
            elif url.path == "/v1/tenants":
                try:
                    self._json({"tenants": server.tenant_snapshot()})
                except Exception as e:
                    self._error(e)
            else:
                self._json({"error": "not found",
                            "routes": ["/health", "/healthz", "/metrics",
                                       "/api/trace", "/v1/models",
                                       "/v1/tenants", "/predict",
                                       "/generate",
                                       "/admin/flight-dump"]}, 404)

        # ------------------------------------------------------------ POST

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length))

        def _timeout_s(self, payload: dict) -> Optional[object]:
            from deeplearning4j_tpu.serving.server import _UNSET

            ms = payload.get("timeout_ms")
            return _UNSET if ms is None else float(ms) / 1000.0

        def _check_ready(self, name: Optional[str]) -> Optional[dict]:
            """503 + Retry-After while the server (or the target model) is
            warming: never park a caller behind an XLA compile."""
            if server._status != "ready":
                return {"error": "warming up", "status": server._status}
            if name is not None:
                model = server.models._models.get(name)
                if (model is not None and model.resident
                        and not model.ready.is_set()):
                    return {"error": f"model {name!r} is warming",
                            "status": "warming"}
            return None

        def do_POST(self):
            if self.path == "/predict":
                return self._post_predict()
            if self.path == "/generate":
                return self._post_generate()
            if self.path == "/admin/flight-dump":
                return self._post_flight_dump()
            replica = getattr(server, "fleet_replica", None)
            if replica is not None and self.path == "/admin/drain":
                return self._post_drain(replica)
            if replica is not None and self.path == "/admin/reload":
                return self._post_reload(replica)
            return self._json({"error": "not found"}, 404)

        def _admit(self, route: str):
            """Fleet admission seam: fleet faults fire here and a
            draining replica 503s here, BEFORE the request touches the
            batcher. Returns the replica when the caller owes a
            `request_done()`, None for a non-fleet server."""
            replica = getattr(server, "fleet_replica", None)
            if replica is None:
                return None
            replica.on_request(route)
            return replica

        def _trace_span(self, route: str):
            """Replica-side request span, parented to the caller's context
            when the request carried an ``X-DL4J-Trace`` header (the
            router attaches one per attempt)."""
            rctx = _prop.parse(self.headers.get(_prop.TRACE_HEADER))
            return _obs.tracer.span(f"replica.{route}", cat="serving",
                                    parent_ctx=rctx, route=route)

        def _post_predict(self):
            with self._trace_span("predict") as sp, _prop.bound(sp.ctx()):
                admitted = None
                try:
                    payload = self._payload()
                    name = payload.get("model")
                    warming = self._check_ready(name)
                    if warming is not None:
                        return self._json(warming, 503,
                                          headers={"Retry-After": "1"})
                    admitted = self._admit("predict")
                    preds = server.predict(
                        payload["data"], model=name,
                        adapter=payload.get("adapter"),
                        timeout_s=self._timeout_s(payload))
                except Exception as e:
                    return self._error(e)
                finally:
                    if admitted is not None:
                        admitted.request_done()
                self._json({"predictions": preds.tolist()})

        def _post_generate(self):
            with self._trace_span("generate") as sp, _prop.bound(sp.ctx()):
                admitted = None
                try:
                    payload = self._payload()
                    name = payload.get("model")
                    warming = self._check_ready(name)
                    if warming is not None:
                        return self._json(warming, 503,
                                          headers={"Retry-After": "1"})
                    sampling = {k: payload[k] for k in
                                ("temperature", "top_k", "top_p", "seed",
                                 "eos_id") if k in payload}
                    admitted = self._admit("generate")
                    ids = server.generate(
                        payload["prompt_ids"], int(payload["n_steps"]),
                        model=name, adapter=payload.get("adapter"),
                        timeout_s=self._timeout_s(payload), **sampling)
                except Exception as e:
                    return self._error(e)
                finally:
                    if admitted is not None:
                        admitted.request_done()
                self._json({"ids": [int(t) for t in ids]})

        # ----------------------------------------------------------- admin

        def _post_flight_dump(self):
            """SLO-page hook: the router's burn-rate engine POSTs here when
            a paging burn implicates this replica. force=False rides the
            recorder's per-reason rate limit — repeated pages within the
            window return path=null instead of a second bundle, which is
            how one sustained breach yields exactly one bundle."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = (json.loads(self.rfile.read(length))
                           if length else {})
                reason = str(payload.get("reason") or "admin")
                path = _obs.flight.dump(reason=reason, force=False)
            except Exception as e:
                return self._error(e)
            self._json({"path": None if path is None else str(path)})

        def _post_drain(self, replica):
            import threading

            threading.Thread(target=replica.drain,
                             name="dl4j-admin-drain", daemon=True).start()
            self._json({"status": "draining", "inflight": replica.inflight()})

        def _post_reload(self, replica):
            try:
                payload = self._payload()
                summary = replica.reload(payload["path"],
                                         warm=bool(payload.get("warm", True)))
            except Exception as e:
                return self._error(e)
            self._json(summary)

    return Handler
