"""Continuous-batching generation scheduler for the LM serving path.

`generate_lm_batch` advances B prompts in lockstep: a request arriving
mid-flight waits for the WHOLE batch to drain (p99 TTFT = longest
generation in front of you). This scheduler owns a `models.zoo.
DecodeStepper` — a fixed-width slot batch with per-slot KV-cache cursors —
and admits new sequences at STEP BOUNDARIES: a request waits only for the
next single-token dispatch (+ its own prefill), and a slot is recycled the
moment its sequence hits EOS / its token budget.

Per-request sampling replays `generate_lm`'s exact draw sequence (one
`np.random.RandomState(seed)` per request, `_sample_token` per token), so
a continuously-batched generation is float-close to the sequential
single-sequence path — the acceptance property `tests/test_serving_tier.py`
pins down.

`mode="drain"` disables mid-flight admission (refill only when every slot
is free): the control arm `bench.py serving_slo` compares against.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.observability import propagate as _prop
from deeplearning4j_tpu.observability.ledger import NOOP_RECORD
from deeplearning4j_tpu.serving import metrics as _m
from deeplearning4j_tpu.serving.errors import (
    InputValidationError,
    RequestTimeoutError,
    ServerOverloadedError,
)


def prompt_bucket_ladder(capacity: int,
                         buckets: Optional[Sequence[int]] = None):
    """Prompt-length pad ladder: powers of two from 8 up to the decode
    cache capacity (explicit `buckets` override, capped at capacity)."""
    if buckets:
        ladder = sorted({int(b) for b in buckets if 0 < int(b) <= capacity})
        if not ladder:
            raise ValueError(
                f"prompt_buckets must contain a size in [1, {capacity}]")
        if ladder[-1] < capacity:
            ladder.append(capacity)
        return tuple(ladder)
    out, b = [], 8
    while b < capacity:
        out.append(b)
        b *= 2
    out.append(int(capacity))
    return tuple(out)


class GenerationRequest:
    __slots__ = ("prompt", "n_steps", "temperature", "top_k", "top_p",
                 "seed", "eos_id", "ids", "error", "deadline", "cancelled",
                 "event", "t_submit", "rng", "ctx", "t_submit_ns",
                 "adapter", "params", "ledger_rec", "_last_tok_ns")

    def __init__(self, prompt, n_steps, *, temperature=1.0, top_k=0,
                 top_p=0.0, seed=0, eos_id=None, deadline=None,
                 adapter=None, ledger_rec=None):
        # Multi-tenant serving: the LoRA adapter name this request decodes
        # through (None = the base model). `params` is filled at submit
        # with the adapter-merged tree; the decode loop groups slots by
        # adapter per round.
        self.adapter = None if adapter is None else str(adapter)
        self.params = None
        self.prompt = [int(t) for t in prompt]
        self.n_steps = int(n_steps)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.ids: List[int] = list(self.prompt)
        self.error: Optional[str] = None
        self.deadline = deadline
        self.cancelled = False
        self.event = threading.Event()
        self.t_submit = time.monotonic()
        self.rng = np.random.RandomState(self.seed)
        # Trace context rides the request object into the decode-loop
        # thread (the submitter's thread-local binding stops at submit).
        self.ctx = _prop.current()
        self.t_submit_ns = time.perf_counter_ns()
        # Accounting record (observability/ledger.py): the decode loop
        # credits it marks, tokens, speculative accepts and its slot-share
        # of every round's wall time; the SERVER owns open/close. NOOP
        # default keeps direct scheduler users (tests, bench) branch-free.
        self.ledger_rec = NOOP_RECORD if ledger_rec is None else ledger_rec
        self._last_tok_ns: Optional[int] = None  # ITL anchor

    @property
    def done(self) -> bool:
        gen = len(self.ids) - len(self.prompt)
        if gen >= self.n_steps:
            return True
        return (self.eos_id is not None and gen > 0
                and self.ids[-1] == self.eos_id)


class GenerationScheduler:
    """One LM's continuous-batching decode loop (see module docstring)."""

    def __init__(self, cg, model_name: str = "default", slots: int = 4,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 queue_depth: int = 64, mode: str = "continuous",
                 kv: str = "dense", page_size: int = 64,
                 kv_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_entries: int = 32,
                 draft=None, spec_k: int = 4, context=None):
        from deeplearning4j_tpu.models.zoo import (DecodeStepper,
                                                   PagedDecodeStepper)

        if mode not in ("continuous", "drain"):
            raise ValueError(f"unknown scheduler mode {mode!r}")
        if kv not in ("dense", "paged"):
            raise ValueError(f"unknown kv cache layout {kv!r}; "
                             "want 'dense' or 'paged'")
        if kv == "dense" and prefix_cache:
            raise ValueError(
                "prefix_cache requires kv='paged' (a hit installs pool "
                "pages by reference; the dense stepper has none to share)")
        self.model_name = model_name
        self.mode = mode
        self.kv = kv
        # Tensor-parallel serving: the host sharded `cg` over
        # `context.mesh` at load; the stepper runs every dispatch inside
        # the context so the whole decode loop serves GSPMD programs.
        self.context = context
        if kv == "paged":
            self.stepper = PagedDecodeStepper(cg, slots,
                                              page_size=page_size,
                                              pages=kv_pages,
                                              context=context)
        else:
            self.stepper = DecodeStepper(cg, slots, context=context)
        self.slots = self.stepper.slots
        self.capacity = self.stepper.capacity
        # Draft-model speculative decoding: a second (small) stepper
        # proposes spec_k tokens per round; the target verifies them in
        # ONE step_k dispatch. Both steppers advance in lockstep, so the
        # effective capacity is the smaller of the two caches.
        self._draft_stepper = None
        self._spec_k = int(spec_k)
        if draft is not None:
            if self._spec_k < 1:
                raise ValueError("spec_k must be >= 1 with a draft model")
            self._draft_stepper = DecodeStepper(draft, self.slots)
            self.capacity = min(self.capacity,
                                self._draft_stepper.capacity)
        # Prefix cache rides the page pool (default on for paged): repeat
        # prompts install shared pages + replay the stored first-token
        # distribution instead of prefilling.
        self._prefix_cache = None
        if kv == "paged" and (prefix_cache is None or prefix_cache):
            from deeplearning4j_tpu.models.kv_pool import PrefixCache

            self._prefix_cache = PrefixCache(
                self.stepper.pool, max_entries=prefix_cache_entries)
            self.stepper.pool.reclaim = self._prefix_cache.evict_one
        # Multi-tenant hooks (set by serving/server.py when the hosted
        # model has LoRA adapters loaded): name -> merged params tree, and
        # the list of names to warm per-adapter dispatch for.
        self.adapter_params = None
        self.adapter_names = None
        # Set by `abort_inflight` (a sharded replica group losing a peer):
        # every active and queued generation fails with this reason at the
        # next step boundary — the caller gets a clean error instead of a
        # hang or a silently truncated sequence.
        self._abort: Optional[str] = None
        self.prompt_buckets = prompt_bucket_ladder(self.capacity,
                                                   prompt_buckets)
        self._queue: "queue.Queue[Optional[GenerationRequest]]" = queue.Queue(
            maxsize=int(queue_depth))
        self._thread: Optional[threading.Thread] = None
        _m.MODEL_QUEUE_DEPTH.labels(
            model=model_name, route="generate").set_function(self._queue.qsize)
        self._itl_hist = _m.ITL_SECONDS.labels(model=model_name)
        self._disp_prefill = _m.DISPATCH_SECONDS.labels(model=model_name,
                                                        phase="prefill")
        self._disp_decode = _m.DISPATCH_SECONDS.labels(model=model_name,
                                                       phase="decode")
        if kv == "paged":
            pool = self.stepper.pool
            for st in ("free", "used", "shared"):
                _m.KV_PAGES.labels(model=model_name, state=st).set_function(
                    lambda s=st, p=pool: p.counts()[s])

    # ------------------------------------------------------------ control

    def start(self) -> "GenerationScheduler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"dl4j-decode-{self.model_name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        if t is not None:
            self._thread = None
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            # Bounded join: see ShapeBucketBatcher.stop — a worker left
            # mid-dispatch at interpreter shutdown dies inside native code.
            t.join(timeout=10.0)

    def qsize(self) -> int:
        return self._queue.qsize()

    def abort_inflight(self, reason: str) -> None:
        """Fail every active and queued generation with `reason` at the
        next step boundary, and every later submit on arrival, until
        `clear_abort()`. Used by the sharded-group peer watchdog
        (`serving/fleet.py`): when a shard member dies, the survivors'
        in-flight sequences can never finish coherently — surfacing a
        prompt error beats a hang (the client) or a truncation passed off
        as completion (the caller's training data)."""
        self._abort = str(reason)

    def clear_abort(self) -> None:
        self._abort = None

    # ------------------------------------------------------------- warmup

    def warmup(self) -> None:
        """Compile every prefill bucket + the step program into the AOT
        store before traffic (one short throwaway generation per bucket).
        With a draft model, also warms the draft's programs and every
        speculative verify width (k_round shrinks from spec_k to 0 near
        capacity, and each T is its own traced program). With adapters
        loaded, every bucket is re-driven through ONE adapter-merged tree:
        merged trees all share a pytree structure (distinct from the bare
        base), so one variant warms per-adapter dispatch for every
        tenant."""
        for b in self.prompt_buckets:
            probs, slot_state, n = self.stepper.prefill([0], pad_to=b)
        self.stepper.install(0, slot_state, n)
        self.stepper.step([0] * self.slots)
        self.stepper.warm_page_copies()
        names = self.adapter_names() if callable(self.adapter_names) else ()
        if names and self.adapter_params is not None:
            try:
                self.stepper.set_params(self.adapter_params(names[0]))
                for b in self.prompt_buckets:
                    _, astate, an = self.stepper.prefill([0], pad_to=b)
                self.stepper.install(0, astate, an)
                self.stepper.step([0] * self.slots)
            finally:
                self.stepper.set_params(None)
        if self._draft_stepper is not None:
            for t in range(2, self._spec_k + 2):
                self.stepper.rewind_all([n] + [0] * (self.slots - 1))
                self.stepper.step_k(np.zeros((self.slots, t), np.int64))
            for b in self.prompt_buckets:
                _, dstate, dn = self._draft_stepper.prefill([0], pad_to=b)
            self._draft_stepper.install(0, dstate, dn)
            self._draft_stepper.step([0] * self.slots)
            self._draft_stepper.warm_page_copies()
            self._draft_stepper.clear(0)
        self.stepper.clear(0)

    # ---------------------------------------------------------- admission

    def submit(self, req: GenerationRequest) -> GenerationRequest:
        if not req.prompt:
            raise InputValidationError("prompt_ids must be non-empty")
        if req.n_steps < 1:
            raise InputValidationError("n_steps must be >= 1")
        if len(req.prompt) + req.n_steps > self.capacity:
            raise InputValidationError(
                f"prompt ({len(req.prompt)}) + n_steps ({req.n_steps}) "
                f"exceeds the decode cache capacity {self.capacity}")
        if req.adapter is not None:
            if self._draft_stepper is not None:
                raise InputValidationError(
                    "adapter selection is not supported with a draft "
                    "(speculative) model configured — the draft has no "
                    "per-tenant delta to propose with")
            if self.adapter_params is None:
                raise InputValidationError(
                    f"model {self.model_name!r} hosts no adapters "
                    f"(requested {req.adapter!r})")
            try:
                # Resolve at admission so an unknown name 400s here and
                # the decode loop only ever sees a ready merged tree.
                req.params = self.adapter_params(req.adapter)
            except KeyError as e:
                raise InputValidationError(str(e.args[0]) if e.args
                                           else str(e))
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            raise ServerOverloadedError(
                f"model {self.model_name!r} generation queue is full "
                f"({self._queue.maxsize} requests); retry later")
        return req

    def generate(self, prompt_ids, n_steps: int, *,
                 timeout_s: Optional[float] = None, adapter=None,
                 ledger_rec=None, **sampling) -> List[int]:
        """Blocking helper: submit + wait; cancels the request (recycled at
        the next step boundary) when the caller's timeout expires."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        req = GenerationRequest(prompt_ids, n_steps, deadline=deadline,
                                adapter=adapter, ledger_rec=ledger_rec,
                                **sampling)
        self.submit(req)
        req.event.wait(timeout=timeout_s)
        if not req.event.is_set():
            req.cancelled = True
            raise TimeoutError(
                f"generation timed out after {timeout_s}s; the slot is "
                "recycled at the next step boundary")
        if req.error == "__deadline__":
            raise RequestTimeoutError(
                "generation deadline expired before completion")
        if req.error is not None:
            raise RuntimeError(req.error)
        return req.ids

    # --------------------------------------------------------------- loop

    def _sample(self, req: GenerationRequest, probs) -> int:
        from deeplearning4j_tpu.models.zoo import _sample_token

        tok = _sample_token(probs, req.rng, req.temperature, req.top_k,
                            req.top_p)
        req.ids.append(tok)
        # Per-request inter-token gap: the SLO engine's itl_p99 objective
        # reads this distribution (TTFT covers the first token, so the
        # first sample only anchors the clock).
        now_ns = time.perf_counter_ns()
        if req._last_tok_ns is not None:
            self._itl_hist.observe((now_ns - req._last_tok_ns) / 1e9)
        req._last_tok_ns = now_ns
        req.ledger_rec.add_tokens_out(1)
        _m.GENERATED_TOKENS.labels(model=self.model_name).inc()
        return tok

    def _finish_timeout(self, req: GenerationRequest) -> None:
        _m.REQUESTS.labels(model=self.model_name, route="generate",
                           outcome="timeout").inc()
        if not req.cancelled:
            req.error = "__deadline__"
        req.event.set()

    def _install_prompt(self, slot: int, req: GenerationRequest,
                        pad_to: int):
        """Get `slot` holding `req.prompt`'s KV and return the first-token
        distribution. Prefix-cache hit: point the slot at the resident
        pages and replay the STORED distribution — zero model dispatches,
        so TTFT on a repeat prompt is pure sampling. Miss: prefill,
        install, and admit the fresh pages into the cache."""
        cache = self._prefix_cache
        # Prefix entries are namespaced by adapter: the same prompt
        # prefilled through different merged trees has different KV.
        hit = (cache.get(req.prompt, namespace=req.adapter)
               if cache is not None else None)
        if hit is not None:
            pages, n, probs = hit
            self.stepper.install_shared(slot, pages, n)
            _m.PREFIX_CACHE_HITS.labels(model=self.model_name).inc()
            req.ledger_rec.set_prefix_hit(True)
            req.ledger_rec.mark("prefix_hit")
        else:
            # parent_ctx is explicit: the decode-loop thread has no
            # enclosing span stack to inherit from.
            t_pf = time.perf_counter_ns()
            with _obs.tracer.span("serving.prefill", cat="serving",
                                  parent_ctx=req.ctx,
                                  model=self.model_name, pad_to=pad_to):
                self.stepper.set_params(req.params)
                probs, slot_state, n = self.stepper.prefill(req.prompt,
                                                            pad_to=pad_to)
                self.stepper.install(slot, slot_state, n)
            # Prefill is a single-request dispatch: its wall time is
            # attributed whole (no co-batched requests to split with).
            prefill_s = (time.perf_counter_ns() - t_pf) / 1e9
            self._disp_prefill.inc(prefill_s)
            req.ledger_rec.add_device_seconds(prefill_s)
            req.ledger_rec.mark("prefill")
            if cache is not None:
                _m.PREFIX_CACHE_MISSES.labels(model=self.model_name).inc()
                req.ledger_rec.set_prefix_hit(False)
                cache.admit(req.prompt, self.stepper.pool.pages_of(slot),
                            n, probs, namespace=req.adapter)
        if self._draft_stepper is not None:
            # The draft always prefills (its dense cache has no pages to
            # share) — it is the small model, so a prefix hit still skips
            # the expensive target prefill.
            _, dstate, dn = self._draft_stepper.prefill(req.prompt,
                                                        pad_to=pad_to)
            self._draft_stepper.install(slot, dstate, dn)
        return probs

    def _admit(self, slot: int, req: GenerationRequest) -> bool:
        """Prefill + install + first token. Returns True when the request
        stays active in `slot` (False: finished or failed at admission)."""
        pad_to = next(b for b in self.prompt_buckets
                      if len(req.prompt) <= b)
        if req.ctx is not None:
            # Retroactive admission-wait span: submit -> this step
            # boundary, parented to the replica request span.
            _obs.tracer.complete(
                "serving.admission_wait", req.t_submit_ns,
                time.perf_counter_ns() - req.t_submit_ns, cat="serving",
                parent_ctx=req.ctx, model=self.model_name)
        req.ledger_rec.set_queue_wait(
            (time.perf_counter_ns() - req.t_submit_ns) / 1e9)
        req.ledger_rec.mark("admitted")
        try:
            probs = self._install_prompt(slot, req, pad_to)
        except Exception as e:
            req.error = f"{type(e).__name__}: {e}"
            req.event.set()
            return False
        _m.TTFT_SECONDS.labels(model=self.model_name).observe(
            time.monotonic() - req.t_submit)
        self._sample(req, probs)
        req.ledger_rec.mark("first_token")
        if req.done:
            self._clear_slot(slot)
            req.event.set()
            return False
        return True

    def _clear_slot(self, slot: int) -> None:
        self.stepper.clear(slot)
        if self._draft_stepper is not None:
            self._draft_stepper.clear(slot)

    def _retire(self, slot: int, req: GenerationRequest,
                timed_out: bool = False) -> None:
        if self.kv == "paged":
            req.ledger_rec.add_cow_copies(
                self.stepper.pool.cow_count(slot))
        self._clear_slot(slot)
        if timed_out:
            self._finish_timeout(req)
        else:
            req.event.set()

    def _loop(self) -> None:
        active: Dict[int, GenerationRequest] = {}
        try:
            self._loop_inner(active)
        except Exception as e:
            # Decode-loop death strands every active sequence: dump the
            # flight bundle, fail the callers, then let the thread die.
            _obs.flight.on_crash("serving.decode_loop", e)
            for req in active.values():
                req.error = f"{type(e).__name__}: {e}"
                req.event.set()
            raise

    def _loop_inner(self, active: Dict[int, GenerationRequest]) -> None:
        free = list(reversed(range(self.slots)))
        busy_gauge = _m.DECODE_SLOTS_BUSY.labels(model=self.model_name)
        step_hist = _m.DECODE_STEP_SECONDS.labels(model=self.model_name)
        while True:
            if self._abort is not None and active:
                # Group failure: fail the batch at this step boundary.
                for slot, req in list(active.items()):
                    req.error = self._abort
                    req.event.set()
                    self._clear_slot(slot)
                    free.append(slot)
                active.clear()
            # Admission happens ONLY here — a step boundary. Continuous
            # mode refills any free slot mid-flight; drain mode waits for
            # the whole batch to finish (the control arm for the bench).
            admitting = bool(free) and (self.mode == "continuous"
                                        or not active)
            while admitting and free:
                try:
                    req = self._queue.get(timeout=None if not active
                                          else 0.0)
                except queue.Empty:
                    break
                if req is None:
                    self._shutdown(active)
                    return
                if self._abort is not None:
                    req.error = self._abort
                    req.event.set()
                    continue
                now = time.monotonic()
                if req.cancelled or (req.deadline is not None
                                     and now > req.deadline):
                    self._finish_timeout(req)
                    continue
                slot = free.pop()
                if self._admit(slot, req):
                    active[slot] = req
                else:
                    free.append(slot)
            busy_gauge.set(len(active))
            if not active:
                continue
            if self._draft_stepper is not None:
                self._spec_round(active, free, step_hist)
                continue
            t0_ns = time.perf_counter_ns()
            rows = self._decode_round(active)
            dur_ns = time.perf_counter_ns() - t0_ns
            step_hist.observe(dur_ns / 1e9)
            # Cost attribution choke point: one round's wall time splits
            # EVENLY across the co-batched slots (every slot rides every
            # dispatch of the round, including other groups' rewinds).
            round_s = dur_ns / 1e9
            self._disp_decode.inc(round_s)
            share = round_s / len(active)
            for req in active.values():
                req.ledger_rec.add_device_seconds(share)
                if req.ctx is not None:
                    _obs.tracer.complete(
                        "serving.decode_step", t0_ns, dur_ns,
                        cat="serving", parent_ctx=req.ctx,
                        model=self.model_name)
            now = time.monotonic()
            for slot, req in list(active.items()):
                if req.cancelled or (req.deadline is not None
                                     and now > req.deadline):
                    self._retire(slot, req, timed_out=True)
                    del active[slot]
                    free.append(slot)
                    continue
                self._sample(req, rows[slot])
                if req.done:
                    self._retire(slot, req)
                    del active[slot]
                    free.append(slot)

    def _decode_round(self, active: Dict[int, GenerationRequest]):
        """One decode step for every active slot, grouped by adapter.
        Returns `{slot: next-token distribution}`.

        All requests on one adapter (the overwhelmingly common round,
        including the no-adapter case) are ONE dispatch — identical to
        the pre-adapter loop. Mixed rounds dispatch once per adapter
        group: each group's `step` advances EVERY slot (the batch is the
        whole slot bank), so after each dispatch the caches rewind —
        slots whose own group has run stay at `L+1` (their position-L KV
        row was just written with the RIGHT params; later groups deposit
        garbage at `L+1`, beyond the cursor and overwritten next round),
        slots still waiting drop back to `L` so their group rewrites
        position L correctly. A slot's returned row always comes from its
        own group's dispatch."""
        tokens = [active[s].ids[-1] if s in active else 0
                  for s in range(self.slots)]
        order: List[Optional[str]] = []
        groups: Dict[Optional[str], List[int]] = {}
        for s in sorted(active):
            a = active[s].adapter
            if a not in groups:
                groups[a] = []
                order.append(a)
            groups[a].append(s)
        if len(order) == 1:
            self.stepper.set_params(active[groups[order[0]][0]].params)
            probs = self.stepper.step(tokens)
            return {s: probs[s] for s in active}
        L = [len(active[s].ids) - 1 if s in active else 0
             for s in range(self.slots)]
        rows: Dict[int, object] = {}
        done: set = set()
        for a in order:
            gslots = groups[a]
            self.stepper.set_params(active[gslots[0]].params)
            probs = self.stepper.step(tokens)
            done.update(gslots)
            for s in gslots:
                rows[s] = probs[s]
            self.stepper.rewind_all([L[s] + 1 if s in done else L[s]
                                     for s in range(self.slots)])
        return rows

    def _spec_round(self, active: Dict[int, GenerationRequest],
                    free: List[int], step_hist) -> None:
        """One speculative decode round (Leviathan et al., ICML 2023,
        greedy acceptance).

        Invariant at entry: BOTH steppers have consumed exactly
        `ids[:-1]` for every active slot (the last sampled token has not
        been fed yet). The round feeds `[x, d1..dk]` — the pending token
        plus k draft proposals — through ONE target `step_k` dispatch;
        row j of the result is the target's distribution after
        `ids + d1..dj`, so a greedy slot emits tokens left to right while
        the target's argmax keeps agreeing with the draft (+1 bonus token
        from the first disagreeing row: that sample is still drawn from a
        correctly-conditioned target distribution). Both steppers are then
        REWOUND to `len(ids) - 1`, restoring the invariant regardless of
        how many rows were accepted — rejected rows stay in the caches
        beyond the cursor, masked until overwritten. Greedy output is
        therefore bit-identical to the non-speculative scheduler; the
        only thing speculation changes is how many target dispatches the
        same token sequence costs.

        Non-greedy slots emit one token per round from row 0 (exactly the
        distribution a plain `step` would have produced), so sampled
        requests stay correct — they just don't accelerate.
        """
        draft = self._draft_stepper
        # Clamp k so target writes (positions len(ids)-1 .. len(ids)+k-1)
        # never cross capacity — a clamped page index would corrupt the
        # last page.
        k = max(0, min(self._spec_k,
                       min(self.capacity - len(r.ids)
                           for r in active.values())))
        x = [active[s].ids[-1] if s in active else 0
             for s in range(self.slots)]
        tok = np.zeros((self.slots, k + 1), np.int64)
        tok[:, 0] = x
        t0_ns = time.perf_counter_ns()
        for j in range(k):
            dprobs = draft.step(tok[:, j])
            tok[:, j + 1] = dprobs.argmax(axis=-1)
        if k:
            # Feed the last proposal so the draft has consumed tok[:, :k+1]
            # too; the result is unused (rewound below either way).
            draft.step(tok[:, k])
        probs = self.stepper.step_k(tok)
        dur_ns = time.perf_counter_ns() - t0_ns
        step_hist.observe(dur_ns / 1e9)
        round_s = dur_ns / 1e9
        self._disp_decode.inc(round_s)
        share = round_s / len(active)
        for req in active.values():
            req.ledger_rec.add_device_seconds(share)
            if req.ctx is not None:
                _obs.tracer.complete(
                    "serving.decode_step", t0_ns, dur_ns, cat="serving",
                    parent_ctx=req.ctx, model=self.model_name)
        spec_acc = _m.SPECULATIVE_TOKENS.labels(model=self.model_name,
                                                outcome="accepted")
        spec_rej = _m.SPECULATIVE_TOKENS.labels(model=self.model_name,
                                                outcome="rejected")
        now = time.monotonic()
        for slot, req in list(active.items()):
            if req.cancelled or (req.deadline is not None
                                 and now > req.deadline):
                self._retire(slot, req, timed_out=True)
                del active[slot]
                free.append(slot)
                continue
            greedy = req.temperature <= 0
            accepted = 0
            for j in range(k + 1):
                t = self._sample(req, probs[slot, j])
                if (req.done or not greedy or j >= k
                        or t != int(tok[slot, j + 1])):
                    break
                accepted += 1
            if greedy and k:
                spec_acc.inc(accepted)
                spec_rej.inc(k - accepted)
                req.ledger_rec.add_speculative(accepted, k - accepted)
            if req.done:
                self._retire(slot, req)
                del active[slot]
                free.append(slot)
        # Restore the invariant: truncate both caches back to the tokens
        # actually kept (retired slots to 0 — their pool pages are
        # already freed and their table rows zeroed).
        lengths = [len(active[s].ids) - 1 if s in active else 0
                   for s in range(self.slots)]
        self.stepper.rewind_all(lengths)
        draft.rewind_all(lengths)

    def _shutdown(self, active: Dict[int, GenerationRequest]) -> None:
        for slot, req in active.items():
            req.error = "server stopped"
            req.event.set()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not None:
                req.error = "server stopped"
                req.event.set()
