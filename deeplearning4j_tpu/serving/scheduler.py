"""Continuous-batching generation scheduler for the LM serving path.

`generate_lm_batch` advances B prompts in lockstep: a request arriving
mid-flight waits for the WHOLE batch to drain (p99 TTFT = longest
generation in front of you). This scheduler owns a `models.zoo.
DecodeStepper` — a fixed-width slot batch with per-slot KV-cache cursors —
and admits new sequences at STEP BOUNDARIES: a request waits only for the
next single-token dispatch (+ its own prefill), and a slot is recycled the
moment its sequence hits EOS / its token budget.

Per-request sampling replays `generate_lm`'s exact draw sequence (one
`np.random.RandomState(seed)` per request, `_sample_token` per token), so
a continuously-batched generation is float-close to the sequential
single-sequence path — the acceptance property `tests/test_serving_tier.py`
pins down.

`mode="drain"` disables mid-flight admission (refill only when every slot
is free): the control arm `bench.py serving_slo` compares against.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.observability import propagate as _prop
from deeplearning4j_tpu.serving import metrics as _m
from deeplearning4j_tpu.serving.errors import (
    InputValidationError,
    RequestTimeoutError,
    ServerOverloadedError,
)


def prompt_bucket_ladder(capacity: int,
                         buckets: Optional[Sequence[int]] = None):
    """Prompt-length pad ladder: powers of two from 8 up to the decode
    cache capacity (explicit `buckets` override, capped at capacity)."""
    if buckets:
        ladder = sorted({int(b) for b in buckets if 0 < int(b) <= capacity})
        if not ladder:
            raise ValueError(
                f"prompt_buckets must contain a size in [1, {capacity}]")
        if ladder[-1] < capacity:
            ladder.append(capacity)
        return tuple(ladder)
    out, b = [], 8
    while b < capacity:
        out.append(b)
        b *= 2
    out.append(int(capacity))
    return tuple(out)


class GenerationRequest:
    __slots__ = ("prompt", "n_steps", "temperature", "top_k", "top_p",
                 "seed", "eos_id", "ids", "error", "deadline", "cancelled",
                 "event", "t_submit", "rng", "ctx", "t_submit_ns")

    def __init__(self, prompt, n_steps, *, temperature=1.0, top_k=0,
                 top_p=0.0, seed=0, eos_id=None, deadline=None):
        self.prompt = [int(t) for t in prompt]
        self.n_steps = int(n_steps)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.ids: List[int] = list(self.prompt)
        self.error: Optional[str] = None
        self.deadline = deadline
        self.cancelled = False
        self.event = threading.Event()
        self.t_submit = time.monotonic()
        self.rng = np.random.RandomState(self.seed)
        # Trace context rides the request object into the decode-loop
        # thread (the submitter's thread-local binding stops at submit).
        self.ctx = _prop.current()
        self.t_submit_ns = time.perf_counter_ns()

    @property
    def done(self) -> bool:
        gen = len(self.ids) - len(self.prompt)
        if gen >= self.n_steps:
            return True
        return (self.eos_id is not None and gen > 0
                and self.ids[-1] == self.eos_id)


class GenerationScheduler:
    """One LM's continuous-batching decode loop (see module docstring)."""

    def __init__(self, cg, model_name: str = "default", slots: int = 4,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 queue_depth: int = 64, mode: str = "continuous"):
        from deeplearning4j_tpu.models.zoo import DecodeStepper

        if mode not in ("continuous", "drain"):
            raise ValueError(f"unknown scheduler mode {mode!r}")
        self.model_name = model_name
        self.mode = mode
        self.stepper = DecodeStepper(cg, slots)
        self.slots = self.stepper.slots
        self.capacity = self.stepper.capacity
        self.prompt_buckets = prompt_bucket_ladder(self.capacity,
                                                   prompt_buckets)
        self._queue: "queue.Queue[Optional[GenerationRequest]]" = queue.Queue(
            maxsize=int(queue_depth))
        self._thread: Optional[threading.Thread] = None
        _m.MODEL_QUEUE_DEPTH.labels(
            model=model_name, route="generate").set_function(self._queue.qsize)

    # ------------------------------------------------------------ control

    def start(self) -> "GenerationScheduler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"dl4j-decode-{self.model_name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        if t is not None:
            self._thread = None
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            # Bounded join: see ShapeBucketBatcher.stop — a worker left
            # mid-dispatch at interpreter shutdown dies inside native code.
            t.join(timeout=10.0)

    def qsize(self) -> int:
        return self._queue.qsize()

    # ------------------------------------------------------------- warmup

    def warmup(self) -> None:
        """Compile every prefill bucket + the step program into the AOT
        store before traffic (one short throwaway generation per bucket)."""
        for b in self.prompt_buckets:
            probs, slot_state, n = self.stepper.prefill([0], pad_to=b)
        self.stepper.install(0, slot_state, n)
        self.stepper.step([0] * self.slots)
        self.stepper.clear(0)

    # ---------------------------------------------------------- admission

    def submit(self, req: GenerationRequest) -> GenerationRequest:
        if not req.prompt:
            raise InputValidationError("prompt_ids must be non-empty")
        if req.n_steps < 1:
            raise InputValidationError("n_steps must be >= 1")
        if len(req.prompt) + req.n_steps > self.capacity:
            raise InputValidationError(
                f"prompt ({len(req.prompt)}) + n_steps ({req.n_steps}) "
                f"exceeds the decode cache capacity {self.capacity}")
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            raise ServerOverloadedError(
                f"model {self.model_name!r} generation queue is full "
                f"({self._queue.maxsize} requests); retry later")
        return req

    def generate(self, prompt_ids, n_steps: int, *,
                 timeout_s: Optional[float] = None,
                 **sampling) -> List[int]:
        """Blocking helper: submit + wait; cancels the request (recycled at
        the next step boundary) when the caller's timeout expires."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        req = GenerationRequest(prompt_ids, n_steps, deadline=deadline,
                                **sampling)
        self.submit(req)
        req.event.wait(timeout=timeout_s)
        if not req.event.is_set():
            req.cancelled = True
            raise TimeoutError(
                f"generation timed out after {timeout_s}s; the slot is "
                "recycled at the next step boundary")
        if req.error == "__deadline__":
            raise RequestTimeoutError(
                "generation deadline expired before completion")
        if req.error is not None:
            raise RuntimeError(req.error)
        return req.ids

    # --------------------------------------------------------------- loop

    def _sample(self, req: GenerationRequest, probs) -> int:
        from deeplearning4j_tpu.models.zoo import _sample_token

        tok = _sample_token(probs, req.rng, req.temperature, req.top_k,
                            req.top_p)
        req.ids.append(tok)
        _m.GENERATED_TOKENS.labels(model=self.model_name).inc()
        return tok

    def _finish_timeout(self, req: GenerationRequest) -> None:
        _m.REQUESTS.labels(model=self.model_name, route="generate",
                           outcome="timeout").inc()
        if not req.cancelled:
            req.error = "__deadline__"
        req.event.set()

    def _admit(self, slot: int, req: GenerationRequest) -> bool:
        """Prefill + install + first token. Returns True when the request
        stays active in `slot` (False: finished or failed at admission)."""
        pad_to = next(b for b in self.prompt_buckets
                      if len(req.prompt) <= b)
        if req.ctx is not None:
            # Retroactive admission-wait span: submit -> this step
            # boundary, parented to the replica request span.
            _obs.tracer.complete(
                "serving.admission_wait", req.t_submit_ns,
                time.perf_counter_ns() - req.t_submit_ns, cat="serving",
                parent_ctx=req.ctx, model=self.model_name)
        try:
            # parent_ctx is explicit: the decode-loop thread has no
            # enclosing span stack to inherit from.
            with _obs.tracer.span("serving.prefill", cat="serving",
                                  parent_ctx=req.ctx,
                                  model=self.model_name, pad_to=pad_to):
                probs, slot_state, n = self.stepper.prefill(req.prompt,
                                                            pad_to=pad_to)
                self.stepper.install(slot, slot_state, n)
        except Exception as e:
            req.error = f"{type(e).__name__}: {e}"
            req.event.set()
            return False
        _m.TTFT_SECONDS.labels(model=self.model_name).observe(
            time.monotonic() - req.t_submit)
        self._sample(req, probs)
        if req.done:
            self.stepper.clear(slot)
            req.event.set()
            return False
        return True

    def _retire(self, slot: int, req: GenerationRequest,
                timed_out: bool = False) -> None:
        self.stepper.clear(slot)
        if timed_out:
            self._finish_timeout(req)
        else:
            req.event.set()

    def _loop(self) -> None:
        active: Dict[int, GenerationRequest] = {}
        try:
            self._loop_inner(active)
        except Exception as e:
            # Decode-loop death strands every active sequence: dump the
            # flight bundle, fail the callers, then let the thread die.
            _obs.flight.on_crash("serving.decode_loop", e)
            for req in active.values():
                req.error = f"{type(e).__name__}: {e}"
                req.event.set()
            raise

    def _loop_inner(self, active: Dict[int, GenerationRequest]) -> None:
        free = list(reversed(range(self.slots)))
        busy_gauge = _m.DECODE_SLOTS_BUSY.labels(model=self.model_name)
        step_hist = _m.DECODE_STEP_SECONDS.labels(model=self.model_name)
        while True:
            # Admission happens ONLY here — a step boundary. Continuous
            # mode refills any free slot mid-flight; drain mode waits for
            # the whole batch to finish (the control arm for the bench).
            admitting = bool(free) and (self.mode == "continuous"
                                        or not active)
            while admitting and free:
                try:
                    req = self._queue.get(timeout=None if not active
                                          else 0.0)
                except queue.Empty:
                    break
                if req is None:
                    self._shutdown(active)
                    return
                now = time.monotonic()
                if req.cancelled or (req.deadline is not None
                                     and now > req.deadline):
                    self._finish_timeout(req)
                    continue
                slot = free.pop()
                if self._admit(slot, req):
                    active[slot] = req
                else:
                    free.append(slot)
            busy_gauge.set(len(active))
            if not active:
                continue
            tokens = [active[s].ids[-1] if s in active else 0
                      for s in range(self.slots)]
            t0_ns = time.perf_counter_ns()
            probs = self.stepper.step(tokens)
            dur_ns = time.perf_counter_ns() - t0_ns
            step_hist.observe(dur_ns / 1e9)
            for req in active.values():
                if req.ctx is not None:
                    _obs.tracer.complete(
                        "serving.decode_step", t0_ns, dur_ns,
                        cat="serving", parent_ctx=req.ctx,
                        model=self.model_name)
            now = time.monotonic()
            for slot, req in list(active.items()):
                if req.cancelled or (req.deadline is not None
                                     and now > req.deadline):
                    self._retire(slot, req, timed_out=True)
                    del active[slot]
                    free.append(slot)
                    continue
                self._sample(req, probs[slot])
                if req.done:
                    self._retire(slot, req)
                    del active[slot]
                    free.append(slot)

    def _shutdown(self, active: Dict[int, GenerationRequest]) -> None:
        for slot, req in active.items():
            req.error = "server stopped"
            req.event.set()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is not None:
                req.error = "server stopped"
                req.event.set()
