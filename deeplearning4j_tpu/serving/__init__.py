"""Production serving tier.

What was one module (`deeplearning4j_tpu/serving.py`: one model, one
unbounded queue, one fixed padded batch shape) is now a package:

- `batcher`   — shape-bucket batching with bounded admission, deadlines
                and cancellation (`ShapeBucketBatcher`);
- `scheduler` — continuous-batching LM generation over per-slot KV-cache
                cursors (`GenerationScheduler`);
- `host`      — multi-model hosting with HBM budgets + LRU eviction
                (`ModelHost`);
- `server`    — the `InferenceServer` facade and the back-compat predict
                path;
- `http`      — the route handlers;
- `errors`    — typed failures with their HTTP statuses;
- `metrics`   — the SLO instrument families;
- `fleet`     — replica runtime: coordinator membership, graceful drain,
                rolling updates, autoscaling (`ReplicaServer`,
                `FleetManager`, `Autoscaler`);
- `router`    — the fleet front-end: least-loaded routing with
                deadline-budgeted failover (`FleetRouter`).

`from deeplearning4j_tpu.serving import InferenceServer` and
`InferenceServer.from_checkpoint(...)` are unchanged from the module era.
"""

from deeplearning4j_tpu.serving.batcher import (
    ShapeBucketBatcher,
    bucket_ladder,
    canonicalize_features,
    expected_input_kind,
)
from deeplearning4j_tpu.serving.errors import (
    InputValidationError,
    ModelNotFoundError,
    ModelNotReadyError,
    ReplicaDrainingError,
    RequestTimeoutError,
    ServerOverloadedError,
    ServingError,
)
from deeplearning4j_tpu.serving.fleet import (
    Autoscaler,
    FleetManager,
    ReplicaServer,
)
from deeplearning4j_tpu.serving.host import ModelHost, ServedModel
from deeplearning4j_tpu.serving.router import FleetRouter
from deeplearning4j_tpu.serving.scheduler import (
    GenerationRequest,
    GenerationScheduler,
    prompt_bucket_ladder,
)
from deeplearning4j_tpu.serving.server import InferenceServer

__all__ = [
    "InferenceServer",
    "ShapeBucketBatcher",
    "GenerationScheduler",
    "GenerationRequest",
    "ModelHost",
    "ServedModel",
    "ReplicaServer",
    "FleetManager",
    "FleetRouter",
    "Autoscaler",
    "ServingError",
    "ReplicaDrainingError",
    "InputValidationError",
    "ModelNotFoundError",
    "ModelNotReadyError",
    "ServerOverloadedError",
    "RequestTimeoutError",
    "bucket_ladder",
    "prompt_bucket_ladder",
    "canonicalize_features",
    "expected_input_kind",
]
