"""Shape-bucket request batching for the predict path.

The PR 5 batcher padded every coalesced batch to ONE `max_batch_size`
shape; at real traffic that wastes the MXU on mostly-padding batches. The
bucket batcher pads to the smallest bucket in a ladder (powers of two up
to `max_batch_size` by default), and the serving warmup drives EVERY
bucket through the `compilation/` AOT store at startup — mixed-size
traffic then never compiles (`dl4j_xla_compiles_total` stays flat).

Admission is bounded: the queue has a hard depth, `submit` raises
`ServerOverloadedError` (-> 503 + `Retry-After`) instead of buffering
without bound, and every `_Pending` carries a deadline plus a `cancelled`
flag so a request whose caller gave up is DROPPED at batch-build time
instead of burning device time (counted under
`dl4j_requests_total{outcome="timeout"}`).

Input dtype policy (the float32-mangles-token-ids fix): the expected
feature dtype is resolved from the model's declared structure — the same
policy source as `nn/conf/preprocessors.py` (`_uint8_policy` /
`_uint8_policies` on the engines) — ids models get int32 features and a
400 on fractional floats, value models get float32 and a 400 on
non-numeric payloads.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.observability import propagate as _prop
from deeplearning4j_tpu.serving import metrics as _m
from deeplearning4j_tpu.serving.errors import (
    InputValidationError,
    ServerOverloadedError,
)


def bucket_ladder(max_batch_size: int,
                  buckets: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
    """The padded batch-size ladder: explicit `buckets` (capped/extended to
    include `max_batch_size`), or powers of two up to it."""
    if buckets:
        ladder = sorted({int(b) for b in buckets if 0 < int(b)})
        if not ladder:
            raise ValueError("batch_buckets must contain a positive size")
        return tuple(b for b in ladder if b < max_batch_size) + (
            int(max_batch_size),)
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(int(max_batch_size))
    return tuple(out)


# ------------------------------------------------------------ input dtype


def expected_input_kind(net) -> str:
    """'ids' when the model's declared structure consumes integer token
    ids (ids-format EmbeddingLayer first layer / single-input consumer —
    the `nn/conf/preprocessors.py` policy), else 'values'."""
    from deeplearning4j_tpu.nn.conf import preprocessors as _pre

    try:
        policy = getattr(net, "_uint8_policy", None)
        if policy is None:
            policies = getattr(net, "_uint8_policies", None)
            if policies and len(policies) == 1:
                policy = next(iter(policies.values()))
    except Exception:
        policy = None
    return "ids" if policy == _pre.UINT8_IDS else "values"


def canonicalize_features(net, data) -> np.ndarray:
    """Stage one request's features for batching, or raise
    `InputValidationError` (-> 400). Ids models keep integer precision
    (int32, never a float round-trip) and 2-D token grids gain the
    trailing index axis the ids EmbeddingLayer expects."""
    try:
        arr = np.asarray(data)
    except Exception as e:
        raise InputValidationError(f"features are not array-like: {e}")
    if arr.dtype.kind not in "fiub":
        raise InputValidationError(
            f"features must be numeric, got dtype {arr.dtype}")
    if arr.ndim == 0:
        raise InputValidationError("features must be a batch of examples")
    if expected_input_kind(net) == "ids":
        if arr.dtype.kind == "f":
            if not np.all(np.isfinite(arr)) or np.any(np.mod(arr, 1) != 0):
                raise InputValidationError(
                    "this model consumes integer token ids; got fractional "
                    "or non-finite floats")
        arr = arr.astype(np.int32)
        if arr.ndim == 2:
            arr = arr[..., None]  # [b, t] -> [b, t, 1] index layout
        return arr
    return np.ascontiguousarray(arr, np.float32)


def serving_feature_spec(net, warmup_shape=None):
    """(per-example shape, dtype) the batcher pads and warms with. An
    explicit `warmup_shape` is trusted; otherwise the declared input type
    decides, with ids models switching the feature axis to the [t, 1]
    token-index layout and int32."""
    from deeplearning4j_tpu.compilation.warmup import infer_feature_shape

    kind = expected_input_kind(net)
    dtype = np.int32 if kind == "ids" else np.float32
    if warmup_shape is not None:
        return tuple(warmup_shape), dtype
    shape = infer_feature_shape(net)
    if shape is not None and kind == "ids" and len(shape) == 2:
        shape = (shape[0], 1)
    return shape, dtype


# ---------------------------------------------------------------- batcher


class _Pending:
    __slots__ = ("array", "event", "result", "error", "deadline",
                 "cancelled", "ctx", "t_submit_ns", "adapter", "params",
                 "ledger_rec")

    def __init__(self, array: np.ndarray,
                 deadline: Optional[float] = None,
                 adapter: Optional[str] = None, params=None,
                 ledger_rec=None):
        self.array = array
        # Multi-tenant serving: the adapter name is part of the batch
        # grouping key (rows dispatched through different param trees
        # can't share one forward), `params` the merged tree to dispatch
        # with (None = the model's own base params).
        self.adapter = adapter
        self.params = params
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None
        self.deadline = deadline          # time.monotonic() instant or None
        self.cancelled = False            # set by an abandoning caller
        # Trace context rides the queue item: the batch loop runs on its
        # own thread, where the submitter's thread-local binding is gone.
        self.ctx = _prop.current()
        self.t_submit_ns = time.perf_counter_ns()
        # The request's accounting record (observability/ledger.py): the
        # batch loop credits it queue-wait and its row-share of each
        # dispatch's wall time; the SERVER owns open/close.
        self.ledger_rec = ledger_rec


class ShapeBucketBatcher:
    """One model's predict-path batcher: bounded admission queue, delay-
    window coalescing, bucket-padded dispatch. Lifecycle: `start()` spawns
    the daemon loop, `submit()` enqueues (or sheds), `stop()` drains."""

    def __init__(self, net, model_name: str = "default",
                 max_batch_size: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 max_delay_s: float = 0.005,
                 queue_depth: int = 256,
                 warmup_shape=None):
        self.net = net
        self.model_name = model_name
        self.buckets = bucket_ladder(max_batch_size, buckets)
        self.max_batch_size = self.buckets[-1]
        self.max_delay_s = float(max_delay_s)
        self.warmup_shape = warmup_shape
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue(
            maxsize=int(queue_depth))
        self._thread: Optional[threading.Thread] = None
        # Multi-tenant hook (serving/server.py): a callable returning the
        # adapter-merged param trees to warm alongside the base — the
        # merged trees carry `__lora_*` leaves, which is a DIFFERENT jit
        # signature than the bare base tree, so an unwarmed adapter path
        # would compile on the first adapter request.
        self.param_variants = None
        _m.MODEL_QUEUE_DEPTH.labels(
            model=model_name, route="predict").set_function(self._queue.qsize)
        self._dispatch_seconds = _m.DISPATCH_SECONDS.labels(
            model=model_name, phase="forward")

    # ------------------------------------------------------------ control

    def start(self) -> "ShapeBucketBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._batch_loop,
                name=f"dl4j-batcher-{self.model_name}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        if t is not None:
            self._thread = None
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass  # the loop sheds the backlog and exits on the sentinel
            # Bounded join: a worker left mid-dispatch at interpreter
            # shutdown dies inside native code (SIGABRT, not a clean exit).
            t.join(timeout=10.0)

    def qsize(self) -> int:
        return self._queue.qsize()

    # ---------------------------------------------------------- admission

    def submit(self, arr: np.ndarray,
               deadline: Optional[float] = None,
               adapter: Optional[str] = None, params=None,
               ledger_rec=None) -> _Pending:
        """Enqueue one request's rows; sheds (503 + Retry-After) when the
        bounded queue is full instead of growing it."""
        p = _Pending(arr, deadline, adapter=adapter, params=params,
                     ledger_rec=ledger_rec)
        try:
            self._queue.put_nowait(p)
        except queue.Full:
            raise ServerOverloadedError(
                f"model {self.model_name!r} admission queue is full "
                f"({self._queue.maxsize} requests); retry later")
        return p

    # ------------------------------------------------------------- warmup

    def warm(self) -> None:
        """Pre-compile every bucket through the AOT store. Engines warm
        via `warmup_buckets` (no execution); bare objects that only expose
        `output` fall back to one executed max-bucket batch — the PR 5
        behavior."""
        from deeplearning4j_tpu.compilation.warmup import warmup_buckets

        shape, dtype = serving_feature_spec(self.net, self.warmup_shape)
        if shape is None:
            raise ValueError(
                "cannot infer the model's input shape; pass "
                "warmup_shape=(...) to InferenceServer")
        variants = (self.param_variants() if callable(self.param_variants)
                    else self.param_variants)
        if hasattr(self.net, "_get_jit"):
            warmup_buckets(self.net, self.buckets, shape=shape, dtype=dtype,
                           param_variants=variants)
        else:
            x = np.zeros((self.max_batch_size,) + tuple(shape), dtype)
            np.asarray(self._forward(x))

    # ------------------------------------------------------------ batching

    def _forward(self, x: np.ndarray, params=None) -> np.ndarray:
        out = (self.net.output(x, params=params) if params is not None
               else self.net.output(x))
        if isinstance(out, list):  # ComputationGraph returns [out, ...]
            out = out[0]
        return np.asarray(out)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _run_batch(self, pending: List[_Pending]) -> None:
        now = time.monotonic()
        live: List[_Pending] = []
        for p in pending:
            expired = p.deadline is not None and now > p.deadline
            if p.cancelled or expired:
                # Dropped BEFORE the device sees it: an abandoned request
                # must not burn a forward pass.
                _m.REQUESTS.labels(model=self.model_name, route="predict",
                                   outcome="timeout").inc()
                if expired and not p.cancelled:
                    p.error = "__deadline__"
                p.event.set()
                continue
            live.append(p)
        # Requests with different per-example shapes can't share one padded
        # batch, and neither can requests dispatching through different
        # adapter trees — run one sub-batch per (shape, adapter) group.
        groups: dict = {}
        for p in live:
            groups.setdefault((p.array.shape[1:], p.adapter), []).append(p)
        for group in groups.values():
            self._run_group(group)

    def _run_group(self, live: List[_Pending]) -> None:
        counts = [p.array.shape[0] for p in live]
        # Traced requests get retroactive queue-wait spans (submit ->
        # batch build) and a per-request device-dispatch span parented to
        # the replica request span — untraced traffic skips all of it.
        traced = [p for p in live if p.ctx is not None]
        now_ns = time.perf_counter_ns()
        for p in traced:
            _obs.tracer.complete(
                "serving.queue_wait", p.t_submit_ns,
                now_ns - p.t_submit_ns, cat="serving",
                parent_ctx=p.ctx, model=self.model_name)
        try:
            x = np.concatenate([p.array for p in live], axis=0)
            n = x.shape[0]
            _m.BATCH_SIZE.observe(n)
            bucket = self._bucket_for(n)
            if n < bucket:
                pad = np.zeros((bucket - n,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            t_fwd = time.perf_counter_ns()
            with _obs.tracer.span("serving.batch", cat="serving",
                                  model=self.model_name, requests=len(live),
                                  rows=n, padded_to=bucket):
                preds = self._forward(x, params=live[0].params)[:n]
            dur_fwd = time.perf_counter_ns() - t_fwd
            for p in traced:
                _obs.tracer.complete(
                    "serving.device_dispatch", t_fwd, dur_fwd,
                    cat="serving", parent_ctx=p.ctx,
                    model=self.model_name, rows=n, padded_to=bucket)
            # Cost attribution choke point: ONE dispatch's wall time is
            # split across its co-batched requests by real (pre-padding)
            # row share, so tenant device-seconds sum to measured
            # dispatch seconds.
            dispatch_s = dur_fwd / 1e9
            self._dispatch_seconds.inc(dispatch_s)
            for p, c in zip(live, counts):
                rec = p.ledger_rec
                if rec is not None:
                    rec.set_queue_wait((t_fwd - p.t_submit_ns) / 1e9)
                    rec.mark("queue_done")
                    rec.add_device_seconds(dispatch_s * (c / n))
            off = 0
            for p, c in zip(live, counts):
                p.result = preds[off:off + c]
                off += c
        except Exception as e:  # surface the failure to every caller; the
            for p in live:      # loop thread must survive any bad batch
                p.error = f"{type(e).__name__}: {e}"
            _obs.flight.on_crash("serving.batch", e)
        for p in live:
            p.event.set()

    def _batch_loop(self) -> None:
        try:
            self._batch_loop_inner()
        except Exception as e:
            # The loop thread is about to die with requests in flight:
            # capture the flight bundle before the stack unwinds.
            _obs.flight.on_crash("serving.batch_loop", e)
            raise

    def _batch_loop_inner(self) -> None:
        holdover: Optional[_Pending] = None
        while True:
            first = holdover if holdover is not None else self._queue.get()
            holdover = None
            if first is None:
                return
            batch = [first]
            total = first.array.shape[0]
            # Coalesce whatever arrives within the delay window, up to the
            # LARGEST bucket; a request that would overflow it is held for
            # the next batch (bucket shapes are the only compiled shapes).
            end = time.monotonic() + self.max_delay_s
            while total < self.max_batch_size:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if item is None:
                    self._run_batch(batch)
                    return
                if total + item.array.shape[0] > self.max_batch_size:
                    holdover = item
                    break
                batch.append(item)
                total += item.array.shape[0]
            self._run_batch(batch)
