"""Serving-tier instruments on the process-global registry.

Two generations coexist deliberately:

- the PR 5-era unlabeled families (`dl4j_serving_requests_total{outcome}`,
  `dl4j_request_latency_seconds`, `dl4j_serving_batch_size`,
  `dl4j_serving_queue_depth`) keep their names and shapes — dashboards and
  the observability acceptance tests scrape them, and the registry
  (correctly) refuses to re-register a family with different labels;
- the SLO families below are labeled per model/route so a multi-model
  host exposes p50/p99 request latency, TTFT, queue depth, and HBM
  residency PER MODEL in one `GET /metrics` scrape.
"""

from __future__ import annotations

from deeplearning4j_tpu import observability as _obs

# ---------------------------------------------------------------- legacy
REQUESTS_LEGACY = _obs.metrics.counter(
    "dl4j_serving_requests_total", "predict() requests",
    label_names=("outcome",))
REQ_LATENCY = _obs.metrics.histogram(
    "dl4j_request_latency_seconds",
    "End-to-end predict() latency (queue wait + batch + forward)",
    buckets=_obs.WIDE_BUCKETS)
BATCH_SIZE = _obs.metrics.histogram(
    "dl4j_serving_batch_size",
    "Real (pre-padding) rows per coalesced inference batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
QUEUE_DEPTH = _obs.metrics.gauge(
    "dl4j_serving_queue_depth",
    "Requests waiting in the batcher queue (scrape-time)")

# ------------------------------------------------------------------- SLO
REQUESTS = _obs.metrics.counter(
    "dl4j_requests_total",
    "Serving requests by model, route and outcome (ok / timeout / shed / "
    "invalid / error)",
    label_names=("model", "route", "outcome"))
REQUEST_SECONDS = _obs.metrics.histogram(
    "dl4j_serving_request_seconds",
    "Per-model end-to-end request latency (SLO histogram: p50/p99 via "
    "bucket interpolation)",
    label_names=("model", "route"), buckets=_obs.WIDE_BUCKETS)
TTFT_SECONDS = _obs.metrics.histogram(
    "dl4j_serving_ttft_seconds",
    "Generation time-to-first-token: submit -> first sampled token",
    label_names=("model",), buckets=_obs.WIDE_BUCKETS)
DECODE_STEP_SECONDS = _obs.metrics.histogram(
    "dl4j_serving_decode_step_seconds",
    "One continuous-batching decode step (all slots, one dispatch)",
    label_names=("model",))
ITL_SECONDS = _obs.metrics.histogram(
    "dl4j_serving_itl_seconds",
    "Inter-token latency: wall-clock gap between consecutive sampled "
    "tokens of ONE request (the per-request token-gap distribution the "
    "SLO engine's itl_p99 objective reads; TTFT covers the first token)",
    label_names=("model",), buckets=_obs.WIDE_BUCKETS)
GENERATED_TOKENS = _obs.metrics.counter(
    "dl4j_serving_generated_tokens_total",
    "Tokens sampled by the generation scheduler",
    label_names=("model",))
MODEL_QUEUE_DEPTH = _obs.metrics.gauge(
    "dl4j_serving_model_queue_depth",
    "Queued requests per model and route (scrape-time)",
    label_names=("model", "route"))
MODEL_HBM_BYTES = _obs.metrics.gauge(
    "dl4j_serving_model_hbm_bytes",
    "Estimated device-resident bytes per hosted model (params + state; "
    "checkpoint manifest size before load)",
    label_names=("model",))
MODEL_DTYPE = _obs.metrics.gauge(
    "dl4j_serving_model_dtype",
    "Info gauge (value 1): the serving dtype of each hosted model — "
    "'int8' for post-training-quantized weights, else the param dtype "
    "(float32/bfloat16/...). Join on {model} with "
    "dl4j_serving_model_hbm_bytes to attribute HBM by precision",
    label_names=("model", "dtype"))
MODEL_SHARDING = _obs.metrics.gauge(
    "dl4j_serving_model_sharding",
    "Info gauge (value 1): the parameter/KV layout each hosted model "
    "actually serves — 'none' (replicated single-chip) or "
    "'model:<n>-way' (tensor-parallel over a model mesh axis). Join on "
    "{model} with dl4j_serving_model_hbm_bytes: under n-way sharding "
    "that gauge reports GLOBAL bytes, per-chip is ~1/n",
    label_names=("model", "sharding"))
MODELS_RESIDENT = _obs.metrics.gauge(
    "dl4j_serving_models_resident",
    "Hosted models currently resident (loaded) in this process")
EVICTIONS = _obs.metrics.counter(
    "dl4j_serving_evictions_total",
    "LRU evictions of cold models from the multi-model host",
    label_names=("model",))
DECODE_SLOTS_BUSY = _obs.metrics.gauge(
    "dl4j_serving_decode_slots_busy",
    "Generation scheduler slots currently holding an active sequence",
    label_names=("model",))

# ----------------------------------------------------------- multi-tenant
# LoRA adapter serving (nn/lora.py, checkpoint/adapters.py): hundreds of
# rank-r deltas resident next to ONE base model, selected per request.
ADAPTERS_RESIDENT = _obs.metrics.gauge(
    "dl4j_adapters_resident",
    "LoRA adapters loaded next to each hosted base model (each is a "
    "rank-r delta, typically <1% of the base's HBM — see /v1/models for "
    "per-adapter bytes)",
    label_names=("model",))
ADAPTER_REQUESTS = _obs.metrics.counter(
    "dl4j_adapter_requests_total",
    "Requests served through a named LoRA adapter over a shared base, by "
    "outcome (ok / timeout / shed / failed) — per-tenant error rates "
    "without joining the ledger (adapter='' rows would be the base "
    "itself; those count only under dl4j_requests_total)",
    label_names=("model", "adapter", "outcome"))

# ------------------------------------------------------------- accounting
# Per-tenant cost attribution (observability/ledger.py): every batched
# dispatch's wall time is split across its co-batched requests at the two
# dispatch choke points (batcher._run_group, scheduler decode rounds) and
# rolled up here by (model, adapter). adapter='' is base-model traffic.
DISPATCH_SECONDS = _obs.metrics.counter(
    "dl4j_serving_dispatch_seconds_total",
    "Total measured dispatch wall seconds at the serving choke points, "
    "UNSPLIT (phase: forward = batcher sub-batch, prefill = prompt "
    "install, decode = one decode/speculative round). The per-tenant "
    "split of the same durations lands in "
    "dl4j_tenant_device_seconds_total; across tenants the two must "
    "reconcile",
    label_names=("model", "phase"))
TENANT_DEVICE_SECONDS = _obs.metrics.counter(
    "dl4j_tenant_device_seconds_total",
    "Attributed device-seconds per tenant: each dispatch's wall time "
    "split across co-batched requests (by row share in the batcher, "
    "evenly across active slots in the decode loop). Sums to total "
    "measured dispatch seconds across tenants",
    label_names=("model", "adapter"))
TENANT_TOKENS = _obs.metrics.counter(
    "dl4j_tenant_tokens_total",
    "Tokens in/out per tenant (direction: in = prompt tokens admitted, "
    "out = tokens sampled). Predict rows count as 'in' per input row",
    label_names=("model", "adapter", "direction"))

# ------------------------------------------------------------- paged decode
# Paged-KV / prefix-cache / speculative-decoding families (PR 15). Same
# JX008 shape as everything above: family registered at import, children
# created once at scheduler construction, scrape-time gauges via
# set_function.
KV_PAGES = _obs.metrics.gauge(
    "dl4j_kv_pages",
    "KV page-pool pages by state: free (allocatable), used (refcount 1), "
    "shared (refcount >= 2 — prefix pages resident once for N readers). "
    "The reserved zero page is none of them",
    label_names=("model", "state"))
PREFIX_CACHE_HITS = _obs.metrics.counter(
    "dl4j_prefix_cache_hits_total",
    "Generation admissions that reused a cached prompt prefix (prefill "
    "skipped entirely; TTFT ~ one decode step)",
    label_names=("model",))
PREFIX_CACHE_MISSES = _obs.metrics.counter(
    "dl4j_prefix_cache_misses_total",
    "Generation admissions that prefilled from scratch (prompt not in the "
    "prefix cache)",
    label_names=("model",))
SPECULATIVE_TOKENS = _obs.metrics.counter(
    "dl4j_speculative_tokens_total",
    "Draft-model speculative proposals by outcome: accepted (target's "
    "greedy argmax agreed — token emitted without its own target step) or "
    "rejected (disagreed — rewound). accepted/(accepted+rejected) is the "
    "measured accept rate alpha in PERF.md §23",
    label_names=("model", "outcome"))

# ------------------------------------------------------------------ fleet
# Router/fleet SLO families: same one-scrape registry, so a single
# `GET /metrics` on the router shows fleet membership, request outcomes
# and failover latency next to the per-replica serving families.
FLEET_REPLICAS = _obs.metrics.gauge(
    "dl4j_fleet_replicas",
    "Serving replicas known to the router by state (live = routable, "
    "warming = joined but pre-warming, draining = finishing in-flight, "
    "dead = lease-expired and evicted since router start)",
    label_names=("state",))
ROUTER_REQUESTS = _obs.metrics.counter(
    "dl4j_router_requests_total",
    "Fleet-router requests by outcome: ok (first replica answered), "
    "failover (answered after rerouting off a failed replica), shed "
    "(503 + Retry-After — every live replica saturated or none live), "
    "failed (deadline/retry budget exhausted — counted separately from "
    "shed by design)",
    label_names=("outcome",))
ROUTER_FAILOVER_SECONDS = _obs.metrics.histogram(
    "dl4j_router_failover_seconds",
    "First failure on the original replica -> success on another "
    "(detection + reroute + answer)",
    buckets=_obs.WIDE_BUCKETS)
