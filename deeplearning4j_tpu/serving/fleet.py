"""Serving-replica runtime: membership, graceful drain, rolling updates.

One `InferenceServer` process becomes a *fleet member* by wrapping it in
a `ReplicaServer`: the replica registers with the PR 9 elastic
`Coordinator` under a ``replica`` role (health = the same heartbeat
leases that detect a lost trainer), the front-end `FleetRouter`
(`serving/router.py`) reads the membership table and routes, and the
replica's lifecycle is driven through role re-joins — the state machine
the router sees IS the coordinator's role field:

    replica:warming  ->  replica  ->  replica:draining  ->  (left)

- **warming**: joined (so the fleet is visible) but pre-compiling every
  batch/prompt bucket through the `compilation/` AOT store; the router
  does not route here, so a cold replica never costs a caller a compile.
- **replica**: routable. Heartbeats refresh the lease; lease expiry gets
  the replica reaped server-side and evicted from the routing table.
- **draining**: stops admitting (503 + Retry-After — a clean failover
  signal, the request was never admitted), finishes in-flight work, then
  leaves. SIGTERM triggers exactly this, so `kubectl delete pod` /
  preemption is a zero-error event; a **rolling update** is a drain that
  swaps the checkpoint, re-warms every bucket (PERF.md §14's warm-start,
  per replica), and re-joins as ``replica`` — the deploy never costs a
  user a compile OR a 5xx.

`FleetManager` spawns/retires replica subprocesses via this module's CLI
(``python -m deeplearning4j_tpu.serving.fleet``); `Autoscaler` calls
spawn/retire on sustained queue-depth or p99 SLO breach. Deterministic
chaos comes from `util/faultinject.py`'s fleet kinds (``kill_replica`` /
``hang_replica`` / ``slow_decode``), fired at the replica's request-
admission seam at an exact (request_n, replica_index).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.analysis.locktrace import named_condition
from deeplearning4j_tpu.observability import fleet as _fev
from deeplearning4j_tpu.parallel.coordinator import (
    HEARTBEAT_S,
    CoordinatorClient,
)
from deeplearning4j_tpu.serving.errors import ReplicaDrainingError
from deeplearning4j_tpu.serving.server import InferenceServer
from deeplearning4j_tpu.util.faultinject import Fault, FaultPlan

ROLE_LIVE = "replica"
ROLE_WARMING = "replica:warming"
ROLE_DRAINING = "replica:draining"


def shard_role(shard_index: int, shard_count: int, state: str = "") -> str:
    """Role string for one member of a tensor-parallel shard group:
    ``replica:shard<i>/<n>`` (+ ``:warming`` / ``:draining``). The shard
    topology rides the coordinator's ONLY per-member metadata plane — the
    role string — so the router can reassemble groups from membership
    alone (`router.parse_replica_role`), with no new coordinator RPCs."""
    base = f"replica:shard{int(shard_index)}/{int(shard_count)}"
    return f"{base}:{state}" if state else base


def compiles_total() -> int:
    """Process-total `dl4j_xla_compiles_total` (0 when the jax compile
    hook isn't installed) — the number the rolling-update ledger and the
    zero-compile acceptance check read."""
    fam = _obs.metrics.get_family("dl4j_xla_compiles_total")
    if fam is None:
        return 0
    return int(sum(c.get() for c in fam.children()))


class ReplicaServer:
    """One fleet member: an `InferenceServer` plus coordinator membership,
    drain/rolling-update lifecycle, and the deterministic fault seam.

    The HTTP layer calls `on_request()` at admission (faults fire here,
    draining 503s here) and `request_done()` when the request finishes
    (the drain waits on in-flight hitting zero).
    """

    def __init__(self, coordinator_address: str, *, name: str = "replica",
                 net=None, path=None, replica_index: int = 0,
                 shard_index: int = 0, shard_count: int = 1,
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_s: Optional[float] = None, warm: bool = True,
                 fault_plan: Optional[FaultPlan] = None,
                 drain_timeout_s: float = 30.0,
                 handle_sigterm: bool = True, **server_kwargs):
        if net is None and path is None:
            raise ValueError("ReplicaServer needs a live net or a "
                             "checkpoint path")
        self.coordinator_address = str(coordinator_address)
        self.name = str(name)
        self.shard_index = int(shard_index)
        self.shard_count = int(shard_count)
        if self.shard_count > 1:
            if not (0 <= self.shard_index < self.shard_count):
                raise ValueError(
                    f"shard_index {shard_index} out of range for "
                    f"shard_count {shard_count}")
            # Group identity = the member-name prefix before '#': every
            # member of group "lm" is "lm#<i>", so peers find each other in
            # the membership table by name alone.
            if "#" not in self.name:
                self.name = f"{self.name}#{self.shard_index}"
            self.role_live = shard_role(self.shard_index, self.shard_count)
            self.role_warming = shard_role(self.shard_index,
                                           self.shard_count, "warming")
            self.role_draining = shard_role(self.shard_index,
                                            self.shard_count, "draining")
        else:
            self.role_live = ROLE_LIVE
            self.role_warming = ROLE_WARMING
            self.role_draining = ROLE_DRAINING
        self.replica_index = int(replica_index)
        self.warm = bool(warm)
        self.heartbeat_s = (HEARTBEAT_S if heartbeat_s is None
                            else float(heartbeat_s))
        self.drain_timeout_s = float(drain_timeout_s)
        self.handle_sigterm = bool(handle_sigterm)
        self.fault_plan = fault_plan or FaultPlan.from_env()
        # Count real backend compiles in every replica process: the
        # rolling-update ledger and the fleet bench read this counter.
        _obs.install_jax_compile_hook()
        self.server = InferenceServer(net=net, host=host, port=port,
                                      **server_kwargs)
        if net is None:
            self.server.add_model(self.server.default_model, path=path)
        self.server.fleet_replica = self
        self.client: Optional[CoordinatorClient] = None
        self._cond = named_condition("serving.fleet")
        self._request_n = 0
        self._inflight = 0
        self._hang_until = 0.0
        self._slow_ms = 0.0
        self._draining = threading.Event()
        self._stopped = threading.Event()
        # Both guarded by self._cond: _terminating is the sticky "a real
        # drain was requested" flag (SIGTERM / retire), distinct from the
        # temporary _draining a rolling update sets and clears.
        self._terminating = False
        self._reloading = False
        # Sharded-group failure plane (shard_count > 1 only): the peer
        # watchdog sets _group_failed when a sibling shard dies hard, the
        # admission seam then 503s new work and the schedulers fail
        # in-flight generations (-> router 502, never a hang or a silently
        # truncated completion).
        self._group_failed: Optional[str] = None
        self._peer_watch: Optional[threading.Thread] = None
        self._peer_roles: Dict[str, str] = {}
        self._peers_armed = False
        self._fault_handlers: Dict[str, Callable[[Fault], None]] = {
            "kill_replica": lambda f: os._exit(137),
            "hang_replica": self._on_hang_fault,
            "slow_decode": self._on_slow_fault,
        }

    # ----------------------------------------------------------- lifecycle

    @property
    def url(self) -> str:
        return self.server.url

    def start(self) -> "ReplicaServer":
        """Bind, register as warming, pre-compile every bucket, THEN
        become routable — the router never sees a replica that would cost
        a caller an XLA compile."""
        self.server.start()
        worker_id = f"{self.name}@{self.server.host}:{self.server.port}"
        self.client = CoordinatorClient(self.coordinator_address, worker_id,
                                        role=self.role_warming)
        self.client.join(role=self.role_warming)
        self.client.start_heartbeats(self.heartbeat_s)
        _fev.record_event("replica_warming", replica=self.name,
                          url=self.url)
        if self.warm:
            self._warm_all()
        self.server._ready.set()
        self.client.join(role=self.role_live)
        _fev.record_event("replica_join", replica=self.name, url=self.url)
        self._install_sigterm()
        if self.shard_count > 1:
            self._peer_watch = threading.Thread(
                target=self._watch_peers, name="dl4j-shard-peer-watch",
                daemon=True)
            self._peer_watch.start()
        return self

    def _warm_all(self) -> None:
        for name in self.server.models.names():
            model = self.server.models.get(name)
            try:
                if model.batcher is not None:
                    model.batcher.warm()
                if model.scheduler is not None:
                    model.scheduler.warmup()
            except Exception as e:
                import warnings

                warnings.warn(
                    f"replica warmup failed for {name!r} "
                    f"({type(e).__name__}: {e}); the first request will "
                    "pay the compile")
            finally:
                model.ready.set()

    def _install_sigterm(self) -> None:
        if (not self.handle_sigterm or threading.current_thread()
                is not threading.main_thread()):
            return
        try:
            signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:
            pass

    def _on_sigterm(self, signum, frame) -> None:
        # Drain off the signal frame: the handler must return immediately
        # so in-flight request threads can finish.
        threading.Thread(target=self.drain, name="dl4j-replica-drain",
                         daemon=True).start()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the replica has drained and stopped (the CLI's
        main thread parks here)."""
        return self._stopped.wait(timeout)

    # ----------------------------------------------------------- admission

    def on_request(self, route: str) -> None:
        """Request-admission seam: deterministic faults fire here, an
        injected hang stalls here (wedging this handler thread, exactly
        like a hung replica), and a draining replica refuses here with a
        clean 503. Callers MUST pair with `request_done()`."""
        with self._cond:
            n = self._request_n
            self._request_n += 1
        self.fault_plan.maybe_fire(n, self.replica_index,
                                   self._fault_handlers)
        while True:
            with self._cond:
                remaining = self._hang_until - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 0.05))
        if self._slow_ms > 0:
            time.sleep(self._slow_ms / 1000.0)
        if self._group_failed is not None:
            # A sibling shard is gone: this member cannot produce a correct
            # answer on its own, and refusing BEFORE admission is the clean
            # failover signal (503; the router retries another unit).
            raise ReplicaDrainingError(
                f"shard group for {self.name!r} lost a member "
                f"({self._group_failed}); retry another replica")
        if self._draining.is_set():
            raise ReplicaDrainingError(
                f"replica {self.name!r} is draining; retry another replica")
        with self._cond:
            self._inflight += 1

    def request_done(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._cond.notify_all()

    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    # -------------------------------------------------------- shard group

    @property
    def group(self) -> Optional[str]:
        """Group id (member-name prefix before '#'); None when unsharded."""
        if self.shard_count <= 1:
            return None
        return self.name.rsplit("#", 1)[0]

    def _watch_peers(self) -> None:
        """Sharded-group death watchdog (runs only when shard_count > 1).

        Polls coordinator membership at heartbeat cadence for the sibling
        shards (same name prefix). Arms once the FULL group has been seen
        live together; after that, a peer that vanishes without ever
        showing the draining role — or whose lease goes stale past half
        the reap threshold — is a hard death. A decode step that spans the
        group cannot complete correctly once a member is gone, so the
        watchdog fails in-flight generations immediately
        (`scheduler.abort_inflight` -> 500 -> router `PartialFailureError`
        502: an explicit error, never a hang or a silently truncated
        completion) and flips `_group_failed` so new work is refused with
        a pre-admission 503. Group-wide routability decays on its own:
        the dead member's lease expiry removes it from the table, and the
        router requires a COMPLETE live group to route."""
        prefix = self.group + "#"
        while not self._stopped.wait(self.heartbeat_s):
            if self._draining.is_set() or self._terminating:
                return
            if self._group_failed is not None:
                return
            try:
                doc = self.client.status()
            except Exception:
                continue  # coordinator unreachable: peers may be fine
            detail = doc.get("detail", {})
            lost_after_s = float(doc.get("lost_after_s", 15.0))
            seen: Dict[str, str] = {}
            stale: Dict[str, float] = {}
            for wid in doc.get("members", []):
                member_name = wid.partition("@")[0]
                if member_name == self.name \
                        or not member_name.startswith(prefix):
                    continue
                info = detail.get(wid, {})
                seen[wid] = str(info.get("role", ""))
                stale[wid] = float(info.get("lease_age_s", 0.0))
            if not self._peers_armed:
                live_peers = sum(
                    1 for role in seen.values()
                    if role and not role.endswith((":warming", ":draining")))
                if live_peers >= self.shard_count - 1:
                    self._peers_armed = True
                    self._peer_roles = dict(seen)
                continue
            for wid, last_role in list(self._peer_roles.items()):
                if last_role.endswith(":draining"):
                    # Clean goodbye in progress (retire / rolling update):
                    # its disappearance later is NOT a death.
                    if wid not in seen:
                        self._peer_roles.pop(wid)
                    continue
                if wid not in seen:
                    self._on_peer_lost(wid, "lease-reaped")
                    return
                if stale.get(wid, 0.0) >= 0.5 * lost_after_s:
                    self._on_peer_lost(wid, "lease stale")
                    return
            self._peer_roles.update(seen)

    def _on_peer_lost(self, wid: str, why: str) -> None:
        reason = (f"shard group {self.group!r} lost member "
                  f"{wid.partition('@')[0]!r} ({why})")
        self._group_failed = reason
        _fev.record_event("shard_peer_lost", replica=self.name,
                          peer=wid, why=why)
        for name in self.server.models.names():
            try:
                model = self.server.models.get(name)
            except Exception:
                continue
            sched = getattr(model, "scheduler", None)
            if sched is not None:
                sched.abort_inflight(reason)

    # -------------------------------------------------------------- faults

    def _on_hang_fault(self, fault: Fault) -> None:
        seconds = float(fault.args.get("seconds", 1.0))
        if fault.args.get("stop_heartbeats"):
            # A hang that also stops heartbeats exercises lease-expiry
            # eviction; with heartbeats running it exercises the router's
            # request-timeout + quarantine path instead.
            if self.client is not None:
                self.client.stop_heartbeats()
        with self._cond:
            self._hang_until = max(self._hang_until,
                                   time.monotonic() + seconds)

    def _on_slow_fault(self, fault: Fault) -> None:
        self._slow_ms = float(fault.args.get("ms", 100.0))

    # ----------------------------------------------------- drain / update

    def _wait_inflight_zero(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.25))
        return True

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Graceful exit: stop admitting, tell the router (role flip),
        finish in-flight work, leave the cluster cleanly, stop serving.
        Idempotent — a second SIGTERM during a drain is a no-op. If a
        rolling update currently owns the drained state, the exit is
        deferred, not dropped: `reload()` observes the terminating flag
        when it finishes and completes the drain instead of rejoining."""
        if self._stopped.is_set():
            return
        with self._cond:
            first = not self._terminating
            self._terminating = True
            reloading = self._reloading
        self._draining.set()
        if not first or reloading:
            return
        self._finish_drain(timeout_s)

    def _finish_drain(self, timeout_s: Optional[float] = None) -> None:
        with _obs.tracer.span("fleet.drain", cat="fleet",
                              replica=self.name):
            self._finish_drain_inner(timeout_s)

    def _finish_drain_inner(self,
                            timeout_s: Optional[float] = None) -> None:
        _fev.record_event("replica_draining", replica=self.name)
        if self.client is not None:
            try:
                self.client.join(role=self.role_draining)
            except Exception:
                pass  # coordinator gone: still drain locally
        self._wait_inflight_zero(timeout_s if timeout_s is not None
                                 else self.drain_timeout_s)
        if self.client is not None:
            self.client.leave()
            self.client.stop_heartbeats()
        self.server.stop()
        _fev.record_event("replica_left", replica=self.name)
        self._stopped.set()

    def reload(self, path, warm: bool = True) -> Dict[str, Any]:
        """Rolling model update on THIS replica: drain from the routing
        table, finish in-flight, swap the default model to `path`,
        AOT-warm every bucket while drained, then re-join as routable.
        Every compile the new checkpoint needs happens inside the drain
        window — zero compiles (and zero 5xx) on the serving path. A
        failed swap restores the previous checkpoint and rejoins, so a
        bad deploy never takes the replica out of rotation; the result
        carries ``ok=False`` so the rollout can abort."""
        with _obs.tracer.span("fleet.reload", cat="fleet",
                              replica=self.name, path=str(path)):
            return self._reload_inner(path, warm=warm)

    def _reload_inner(self, path, warm: bool = True) -> Dict[str, Any]:
        t0 = time.monotonic()
        c0 = compiles_total()
        with self._cond:
            if self._stopped.is_set() or self._terminating:
                raise ReplicaDrainingError(
                    f"replica {self.name!r} is terminating; not reloading")
            if self._reloading:
                raise ReplicaDrainingError(
                    f"replica {self.name!r} already has a reload in "
                    f"flight; retry shortly")
            self._reloading = True
        self._draining.set()
        if self.client is not None:
            try:
                self.client.join(role=self.role_draining)
            except Exception:
                pass
        self._wait_inflight_zero(self.drain_timeout_s)
        host = self.server.models
        name = self.server.default_model
        with host._lock:
            model = host._models[name]
            old_path, old_pinned = model.path, model.pinned
        error: Optional[str] = None
        restored = False
        try:
            with host._lock:
                model.path = str(path)
                model.pinned = False  # path-backed: evictable + reloadable
                host._evict(model)
            host._reload(model)
            if warm:
                try:
                    if model.batcher is not None:
                        model.batcher.warm()
                    if model.scheduler is not None:
                        model.scheduler.warmup()
                finally:
                    model.ready.set()
        except Exception as e:
            # A bad checkpoint must not leave the replica drained forever:
            # put the old model back and rejoin. Only an unrestorable
            # replica (net-backed old model, or the restore itself failed)
            # stays out of rotation.
            error = f"{type(e).__name__}: {e}"
            restored = self._restore_model(host, model, old_path,
                                           old_pinned)
        compiled = compiles_total() - c0
        with self._cond:
            self._reloading = False
            terminating = self._terminating
        if terminating:
            # SIGTERM landed mid-update: complete the real drain instead
            # of rejoining, so `kubectl delete pod` during a deploy still
            # exits promptly and gracefully.
            self._finish_drain()
        elif error is None or restored:
            self._draining.clear()
            if self.client is not None:
                self.client.join(role=self.role_live)
        seconds = round(time.monotonic() - t0, 4)
        if error is not None:
            _fev.record_event("rolling_update_failed", replica=self.name,
                              path=str(path), error=error,
                              restored=restored)
            return {"ok": False, "model": name, "path": str(path),
                    "error": error, "restored": restored,
                    "seconds": seconds}
        _fev.record_event("rolling_update", replica=self.name,
                          path=str(path), compiled=compiled,
                          seconds=seconds)
        return {"ok": True, "model": name, "path": str(path),
                "compiled_during_warm": compiled, "seconds": seconds}

    def _restore_model(self, host, model, old_path, old_pinned) -> bool:
        """Best-effort rollback after a failed swap: re-point the model at
        the previous checkpoint and load it. False when there is nothing
        to restore from (the old model was net-backed) or the restore
        itself failed — the replica then stays drained."""
        if old_path is None:
            return False
        try:
            with host._lock:
                if model.resident:
                    host._evict(model)
                model.path = old_path
                model.pinned = old_pinned
            host._reload(model)
            model.ready.set()
            return True
        except Exception:
            return False


# ------------------------------------------------------------------ fleet


class FleetManager:
    """Spawns and retires replica subprocesses through this module's CLI.

    Each replica is one OS process (its own device runtime, its own
    fate): `spawn()` launches it against the shared coordinator,
    `retire()` SIGTERMs it (graceful drain), `kill()` SIGKILLs it (chaos
    / failover drills), `rolling_update()` walks the live fleet one
    replica at a time through `POST /admin/reload`.
    """

    def __init__(self, coordinator_address: str, path, *,
                 python: Optional[str] = None, host: str = "127.0.0.1",
                 heartbeat_s: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None,
                 extra_args: Optional[List[str]] = None,
                 log_dir=None):
        self.coordinator_address = str(coordinator_address)
        self.path = str(path)
        self.python = python or sys.executable
        self.host = host
        self.heartbeat_s = heartbeat_s
        self.env = dict(env or {})
        self.extra_args = list(extra_args or [])
        self.log_dir = None if log_dir is None else str(log_dir)
        self.procs: Dict[str, subprocess.Popen] = {}
        self._next_index = 0

    def spawn(self, name: Optional[str] = None, port: int = 0,
              replica_index: Optional[int] = None,
              extra_env: Optional[Dict[str, str]] = None,
              extra_args: Optional[List[str]] = None) -> str:
        idx = self._next_index if replica_index is None else int(
            replica_index)
        self._next_index = max(self._next_index, idx) + 1
        name = name or f"replica-{idx}"
        cmd = [self.python, "-m", "deeplearning4j_tpu.serving.fleet",
               "--coordinator", self.coordinator_address,
               "--name", name, "--path", self.path,
               "--host", self.host, "--port", str(port),
               "--replica-index", str(idx)]
        if self.heartbeat_s is not None:
            cmd += ["--heartbeat-s", str(self.heartbeat_s)]
        cmd += self.extra_args
        cmd += list(extra_args or [])
        env = dict(os.environ)
        env.update(self.env)
        env.update(extra_env or {})
        stdout = None
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(os.path.join(self.log_dir, f"{name}.log"), "ab")
        self.procs[name] = subprocess.Popen(
            cmd, env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout is not None else None)
        return name

    def spawn_group(self, group: str, shards: int, *,
                    model_parallel: Optional[int] = None,
                    extra_env: Optional[Dict[str, str]] = None,
                    extra_args: Optional[List[str]] = None) -> List[str]:
        """Spawn one tensor-parallel shard group: `shards` member
        processes named ``<group>#<i>`` carrying ``replica:shard<i>/<n>``
        roles. The router treats the group as ONE routable unit (entry =
        shard 0); health is the AND of every member's lease. On CPU each
        member emulates its shard over a local host-device mesh, so
        `model_parallel` (default = `shards`) is forced into the child's
        XLA_FLAGS before its backends initialize."""
        ways = shards if model_parallel is None else int(model_parallel)
        env = dict(extra_env or {})
        names: List[str] = []
        for i in range(int(shards)):
            args = ["--shard-index", str(i), "--shard-count", str(shards)]
            if ways > 1:
                args += ["--model-parallel", str(ways)]
            args += list(extra_args or [])
            names.append(self.spawn(name=f"{group}#{i}",
                                    extra_env=env, extra_args=args))
        return names

    def alive(self) -> Dict[str, bool]:
        return {n: p.poll() is None for n, p in self.procs.items()}

    def retire(self, name: Optional[str] = None,
               timeout_s: float = 30.0) -> Optional[int]:
        """Graceful retire: SIGTERM -> the replica drains, leaves, exits
        0. Returns the exit code (None if it had already exited)."""
        name = name or self._newest_alive()
        if name is None:
            return None
        proc = self.procs[name]
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        return proc.returncode

    def kill(self, name: str) -> None:
        """Hard loss (chaos drills): SIGKILL, no drain, no leave — the
        coordinator's reaper and the router's failover must clean up."""
        proc = self.procs[name]
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)

    def _newest_alive(self) -> Optional[str]:
        for name in reversed(list(self.procs)):
            if self.procs[name].poll() is None:
                return name
        return None

    def rolling_update(self, new_path, router,
                       timeout_s: float = 300.0) -> Dict[str, Any]:
        """Deploy `new_path` across the live fleet one replica at a time:
        each replica drains, warms the new checkpoint through the AOT
        store, and re-joins before the next one starts — capacity never
        drops by more than one replica and no caller ever sees a compile.
        A replica whose reload FAILS (``ok=False`` or an HTTP error from
        the reload endpoint) ABORTS the rollout: the same checkpoint would
        fail identically on every remaining replica, and continuing would
        walk the whole fleet into the same bad deploy.

        Sharded groups roll as ONE unit: every member of the group is
        reloaded before the rollout moves on, and the group counts as
        rejoined only when ALL its members are live again — the router
        refuses to route to a partially-updated group (it is incomplete
        the whole time), so a generation can never straddle two
        checkpoint versions of one model."""
        from deeplearning4j_tpu.serving.router import post_json

        results: Dict[str, Any] = {}
        deadline = time.monotonic() + timeout_s
        rows = router.table()
        done_groups: set = set()
        for row in rows:
            if row["state"] != "live":
                continue
            group = row.get("group")
            if group is None:
                unit = [row]
            else:
                if group in done_groups:
                    continue
                done_groups.add(group)
                unit = sorted(
                    (r for r in rows if r.get("group") == group),
                    key=lambda r: r.get("shard_index") or 0)
            aborted = False
            for member in unit:
                try:
                    summary = post_json(
                        member["url"] + "/admin/reload",
                        {"path": str(new_path)}, timeout_s=timeout_s)
                except urllib.error.HTTPError as e:
                    # The reload endpoint itself errored (bad checkpoint,
                    # replica terminating, ...). HTTPError subclasses
                    # OSError, so catch it FIRST — this is a failed
                    # deploy, not a dead replica, and it must stop the
                    # rollout.
                    results[member["name"]] = {"ok": False,
                                               "error": f"HTTP {e.code}"}
                    _fev.record_event("rolling_update_aborted",
                                      replica=member["name"],
                                      error=f"HTTP {e.code}")
                    aborted = True
                    break
                except OSError as e:
                    # The replica died between the table snapshot and its
                    # turn (its lease may not have expired yet, so it
                    # still read as live). The router discovers that on
                    # its own; the rollout moves on to the survivors.
                    results[member["name"]] = {"ok": False,
                                               "error": str(e)}
                    continue
                results[member["name"]] = summary
                if not summary.get("ok"):
                    _fev.record_event("rolling_update_aborted",
                                      replica=member["name"],
                                      error=str(summary.get("error")))
                    aborted = True
                    break
            if aborted:
                break
            # Don't drain the next unit until the router has actually
            # observed this one back in the live set — otherwise its stale
            # table can briefly show zero routable replicas and shed.
            want = {m["name"] for m in unit
                    if results.get(m["name"], {}).get("ok")}
            while want and time.monotonic() < deadline:
                live = {r["name"] for r in router.table()
                        if r["state"] == "live"}
                if want <= live:
                    break
                time.sleep(0.05)
        return results

    def stop_all(self, timeout_s: float = 30.0) -> Dict[str, Optional[int]]:
        for name, proc in self.procs.items():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout_s
        codes: Dict[str, Optional[int]] = {}
        for name, proc in self.procs.items():
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
            codes[name] = proc.returncode
        return codes


# -------------------------------------------------------------- autoscale


class Autoscaler:
    """Spawn/retire replicas on *sustained* SLO breach.

    Signals come from `FleetRouter.load_stats()` (queue depth per live
    replica, request p99); actions are injected callables (production:
    `FleetManager.spawn` / `.retire`). Breach must persist for
    `breach_s` before an action fires, and actions are `cooldown_s`
    apart — a one-scrape spike never flaps the fleet. The clock is
    injectable so tests drive the state machine deterministically.
    """

    def __init__(self, router, spawn: Callable[[], Any],
                 retire: Callable[[], Any], *,
                 min_replicas: int = 1, max_replicas: int = 8,
                 queue_high: float = 8.0, queue_low: float = 1.0,
                 p99_slo_s: Optional[float] = None,
                 breach_s: float = 5.0, cooldown_s: float = 10.0,
                 interval_s: float = 1.0,
                 _clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.spawn = spawn
        self.retire = retire
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.p99_slo_s = p99_slo_s
        self.breach_s = float(breach_s)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self._clock = _clock
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action = -float("inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.actions: List[Dict[str, Any]] = []

    def evaluate(self) -> Optional[str]:
        """One decision step; returns "up" / "down" / None. Called by the
        background loop — and directly by tests with a pinned clock."""
        now = self._clock()
        stats = self.router.load_stats()
        live = int(stats.get("live", 0))
        per_replica = (stats.get("total_load", 0.0) / live if live
                       else float("inf"))
        p99 = stats.get("p99_s")
        breach = per_replica > self.queue_high or (
            self.p99_slo_s is not None and p99 is not None
            and p99 > self.p99_slo_s)
        idle = live > self.min_replicas and per_replica < self.queue_low
        if breach:
            if self._breach_since is None:
                self._breach_since = now
        else:
            self._breach_since = None
        if idle:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        if now - self._last_action < self.cooldown_s:
            # Conditions observed during cooldown don't count toward the
            # sustain window — the fleet must re-prove the breach after the
            # last action settles.
            self._breach_since = None
            self._idle_since = None
            return None
        if (self._breach_since is not None
                and now - self._breach_since >= self.breach_s
                and live < self.max_replicas):
            self._act("up", now, stats)
            return "up"
        if (self._idle_since is not None
                and now - self._idle_since >= self.breach_s):
            self._act("down", now, stats)
            return "down"
        return None

    def _act(self, direction: str, now: float, stats: Dict[str, Any]) -> None:
        (self.spawn if direction == "up" else self.retire)()
        self._last_action = now
        self._breach_since = None
        self._idle_since = None
        self.actions.append({"direction": direction, "at": now,
                             "stats": dict(stats)})
        _fev.record_event(f"autoscale_{direction}", **{
            k: v for k, v in stats.items() if isinstance(v, (int, float))})

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()

            def loop():
                while not self._stop.wait(self.interval_s):
                    try:
                        self.evaluate()
                    except Exception:
                        pass  # scaling must never kill the poller

            self._thread = threading.Thread(
                target=loop, name="dl4j-fleet-autoscaler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# -------------------------------------------------------------------- cli


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m deeplearning4j_tpu.serving.fleet`` — run one replica
    until SIGTERM (graceful drain). Prints one JSON "ready" line with the
    bound URL so spawners can wire the fleet without port guessing."""
    import argparse
    import json

    ap = argparse.ArgumentParser(description="run one serving replica")
    ap.add_argument("--coordinator", required=True,
                    help="coordinator host:port")
    ap.add_argument("--path", required=True, help="checkpoint to serve")
    ap.add_argument("--name", default="replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--replica-index", type=int, default=0)
    ap.add_argument("--shard-index", type=int, default=0)
    ap.add_argument("--shard-count", type=int, default=1)
    ap.add_argument("--model-parallel", type=int, default=1)
    ap.add_argument("--heartbeat-s", type=float, default=None)
    ap.add_argument("--max-batch-size", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--decode-slots", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=256)
    ap.add_argument("--kv-cache", default="dense",
                    choices=("dense", "paged"))
    ap.add_argument("--kv-page-size", type=int, default=64)
    ap.add_argument("--kv-pages", type=int, default=None)
    ap.add_argument("--no-warm", action="store_true")
    args = ap.parse_args(argv)

    if args.model_parallel > 1:
        # Must land in XLA_FLAGS before jax initializes its backends (a
        # jax.devices() probe here would itself trigger that init), so
        # inspect the env var, not the backend.
        import re

        from deeplearning4j_tpu.parallel.distributed import (
            force_host_device_count,
        )

        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        if m is None or int(m.group(1)) < args.model_parallel:
            force_host_device_count(args.model_parallel)

    replica = ReplicaServer(
        args.coordinator, name=args.name, path=args.path,
        replica_index=args.replica_index, shard_index=args.shard_index,
        shard_count=args.shard_count, host=args.host, port=args.port,
        heartbeat_s=args.heartbeat_s, warm=not args.no_warm,
        max_batch_size=args.max_batch_size, max_delay_ms=args.max_delay_ms,
        decode_slots=args.decode_slots, queue_depth=args.queue_depth,
        kv_cache=args.kv_cache, kv_page_size=args.kv_page_size,
        kv_pages=args.kv_pages, model_parallel=args.model_parallel)
    replica.start()
    print(json.dumps({"event": "ready", "name": args.name,
                      "url": replica.url, "pid": os.getpid()}), flush=True)
    replica.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
