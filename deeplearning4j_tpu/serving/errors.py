"""Typed serving errors: every failure mode the tier can hand a caller
maps to exactly one HTTP status, so the handler layer is a table lookup
and overload/timeout/validation can NEVER surface as a 500 traceback."""

from __future__ import annotations

from typing import Optional


class ServingError(Exception):
    """Base: `status` is the HTTP code; `retry_after` (seconds) adds a
    `Retry-After` header when set (503 load shedding / warming)."""

    status = 500
    retry_after: Optional[int] = None

    def payload(self) -> dict:
        return {"error": str(self)}


class InputValidationError(ServingError):
    """Request payload rejected before touching the device (bad dtype,
    non-numeric data, shape that can't batch)."""

    status = 400


class ModelNotFoundError(ServingError):
    status = 404


class ModelNotReadyError(ServingError):
    """Model still warming (or reloading after eviction): callers retry
    instead of stalling behind an XLA compile."""

    status = 503
    retry_after = 1


class ServerOverloadedError(ServingError):
    """Bounded queue full — load is shed, never buffered without bound."""

    status = 503
    retry_after = 1


class ReplicaDrainingError(ServingError):
    """This replica is draining (SIGTERM / rolling update): it finishes
    its in-flight work but admits nothing new. The fleet router treats
    the 503 as a clean failover signal — the request was never admitted,
    so retrying it on another replica is always safe."""

    status = 503
    retry_after = 1


class RequestTimeoutError(ServingError, TimeoutError):
    """Deadline expired (in queue or waiting for a batch). Subclasses
    TimeoutError so pre-package callers catching TimeoutError still work."""

    status = 504
