"""Keras interop: HDF5 model import + pretrained zoo.

TPU-native replacement for the reference's `deeplearning4j-modelimport`
module (`KerasModelImport.java`, `KerasModel.java`,
`trainedmodels/TrainedModels.java`).
"""

from deeplearning4j_tpu.keras.import_model import (  # noqa: F401
    KerasImportException,
    KerasModelImport,
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)
from deeplearning4j_tpu.keras.trained_models import (  # noqa: F401
    TrainedModels,
    preprocess_imagenet,
    vgg16_config,
)
