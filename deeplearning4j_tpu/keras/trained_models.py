"""Pretrained model zoo (Keras-import backed).

Reference: `deeplearning4j-modelimport/.../keras/trainedmodels/TrainedModels.java:16-19`
(VGG16 / VGG16NOTOP download + import) and
`trainedmodels/Utils/ImageNetLabels.java` preprocessing. The reference
downloads the weights over HTTP; this environment has no egress, so
`TrainedModels.vgg16(weights_path=...)` imports a locally-provided Keras
VGG-16 .h5 (the exact file the reference downloads), and without a path
returns the architecture with fresh init — same topology either way.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration

# ImageNet channel means used by the reference's VGG16 preprocessing
# (BGR order, `TrainedModels.java` getPreProcessor).
VGG_MEAN_BGR = (103.939, 116.779, 123.68)

_VGG16_BLOCKS = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]


def vgg16_config(n_classes: int = 1000, include_top: bool = True,
                 image: int = 224, dtype: str = "bfloat16"):
    """The VGG-16 topology (Simonyan & Zisserman) as a MultiLayerConfiguration,
    layer-for-layer the Keras-1 file's structure (ZeroPadding folded into
    SAME-padded 3x3 convs, as the importer does)."""
    builder = (NeuralNetConfiguration.builder()
               .seed(123).updater("nesterovs").learning_rate(0.01)
               .weight_init("xavier").dtype(dtype)
               .list())
    for n_filters, reps in _VGG16_BLOCKS:
        for _ in range(reps):
            builder.layer(ConvolutionLayer(
                n_out=n_filters, kernel_size=(3, 3), stride=(1, 1),
                padding=(1, 1), convolution_mode="truncate",
                activation="relu"))
        builder.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    if include_top:
        builder.layer(DenseLayer(n_out=4096, activation="relu"))
        builder.layer(DenseLayer(n_out=4096, activation="relu"))
        builder.layer(OutputLayer(n_out=n_classes, activation="softmax",
                                  loss_function="mcxent"))
    return builder.set_input_type(
        InputType.convolutional(image, image, 3)).build()


def preprocess_imagenet(images: np.ndarray) -> np.ndarray:
    """Reference VGG16 preprocessing: RGB->BGR + mean subtraction
    (`TrainedModels.getPreProcessor`). images: [b, h, w, 3] RGB float."""
    bgr = images[..., ::-1].astype("float32")
    return bgr - np.asarray(VGG_MEAN_BGR, "float32")


class TrainedModels:
    """Facade matching the reference's `TrainedModels` enum."""

    @staticmethod
    def vgg16(weights_path: Optional[str] = None, n_classes: int = 1000,
              dtype: str = "bfloat16"):
        """VGG16 with ImageNet weights when a Keras .h5 is provided locally
        (no-egress stand-in for the reference's download), else fresh init."""
        if weights_path is not None:
            from deeplearning4j_tpu.keras.import_model import (
                KerasModelImport)
            return KerasModelImport.import_keras_model(weights_path)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(
            vgg16_config(n_classes=n_classes, dtype=dtype)).init()

    VGG16 = vgg16

    @staticmethod
    def vgg16_notop(weights_path: Optional[str] = None,
                    dtype: str = "bfloat16"):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if weights_path is not None:
            from deeplearning4j_tpu.keras.import_model import (
                KerasModelImport)
            return KerasModelImport.import_keras_model(weights_path)
        return MultiLayerNetwork(
            vgg16_config(include_top=False, dtype=dtype)).init()
