"""Keras HDF5 model import.

TPU-native equivalent of the reference's `deeplearning4j-modelimport`
(`KerasModel.java`, `KerasSequentialModel.java`, `KerasModelImport.java`,
`Hdf5Archive.java` — JavaCPP HDF5 there, `h5py` here): parses the Keras
1.x/2.x JSON topology stored in a `.h5` model file into this framework's
config DSL and maps the stored weight tensors onto the engines' param
pytrees.

Scope (mirrors the reference's supported layer set,
`deeplearning4j-modelimport/.../keras/layers/`):
- Sequential -> `MultiLayerConfiguration` / `MultiLayerNetwork`
- Model (functional, linear or merge DAGs) -> `ComputationGraph`
- Layers: Dense, Convolution2D/Conv2D, MaxPooling2D, AveragePooling2D,
  GlobalMax/AveragePooling2D, ZeroPadding2D (folded into the next conv),
  Flatten (becomes a preprocessor), Dropout, Activation, Embedding, LSTM,
  BatchNormalization, Merge/Add/Concatenate, InputLayer.

Weight-layout conversions:
- Conv kernels: Theano dim-ordering `[out, in, kh, kw]` -> HWIO
  `[kh, kw, in, out]`; TensorFlow ordering passes through.
- LSTM: Keras-1 twelve-array form (`W_i,U_i,b_i,W_c,U_c,b_c,...`) and
  Keras-2 packed form (kernel/recurrent/bias, gate order i,f,c,o) both map
  to this framework's `[n_in, 4u]` i,f,o,g packing.
- BatchNormalization: gamma/beta params + running mean/var state.

Data layout note: imported nets use this framework's feature-last layouts
(`[b, h, w, c]` images, `[b, t, f]` sequences) regardless of the Keras
file's `dim_ordering` — only the weights are transposed, so activations
match the original model on equivalently-transposed inputs.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf.enums import PoolingType
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration


class KerasImportException(Exception):
    """Unsupported/invalid Keras file (reference:
    `InvalidKerasConfigurationException`/`UnsupportedKerasConfigurationException`)."""


_ACTIVATIONS = {
    "relu": "relu", "tanh": "tanh", "sigmoid": "sigmoid", "softmax": "softmax",
    "linear": "identity", "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "elu": "elu", "selu": "selu",
    "swish": "swish", "gelu": "gelu",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mean_absolute_error", "mae": "mean_absolute_error",
    "kullback_leibler_divergence": "kl_divergence",
    "poisson": "poisson",
    "cosine_proximity": "cosine_proximity",
    "hinge": "hinge", "squared_hinge": "squared_hinge",
}


def _map_activation(name: Optional[str]) -> str:
    if not name:
        return "identity"
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KerasImportException(f"Unsupported Keras activation: {name!r}")


_LOSS_CLASS_NAMES = {
    # tf.keras >=2.3 serialized loss objects ({'class_name','config'}).
    "CategoricalCrossentropy": "categorical_crossentropy",
    "SparseCategoricalCrossentropy": "sparse_categorical_crossentropy",
    "BinaryCrossentropy": "binary_crossentropy",
    "MeanSquaredError": "mse",
    "MeanAbsoluteError": "mae",
    "KLDivergence": "kullback_leibler_divergence",
    "Poisson": "poisson",
    "CosineSimilarity": "cosine_proximity",
    "Hinge": "hinge",
    "SquaredHinge": "squared_hinge",
}


def _map_loss(name) -> str:
    """Map a Keras loss identifier to a framework loss name.

    The reference raises `UnsupportedKerasConfigurationException` for
    unknown losses (`KerasLayer.mapLossFunction`); mirror that instead of
    silently substituting mse."""
    if not name:
        return "mse"
    if isinstance(name, dict) and "class_name" in name:
        cname = name["class_name"]
        if cname not in _LOSS_CLASS_NAMES:
            raise KerasImportException(
                f"Unsupported serialized Keras loss class: {cname!r}")
        name = _LOSS_CLASS_NAMES[cname]
    if isinstance(name, (dict, list, tuple)):
        raise KerasImportException(
            f"Per-output loss specs ({type(name).__name__}) must be resolved "
            "per output before mapping — use _loss_for_output")
    try:
        return _LOSSES[name]
    except KeyError:
        raise KerasImportException(f"Unsupported Keras loss: {name!r}")


def _loss_for_output(training, output_name: str, index: int) -> str:
    """Resolve the compiled loss for one output of a (possibly multi-output)
    model: dict losses map by output name, list losses by position."""
    loss = (training or {}).get("loss")
    if isinstance(loss, dict):
        if "class_name" in loss:  # serialized loss object, not a per-output map
            return _map_loss(loss)
        entry = loss.get(output_name)
        if entry is None and len(loss) == 1:
            entry = next(iter(loss.values()))
        if entry is None:
            raise KerasImportException(
                f"training_config loss dict has no entry for output "
                f"{output_name!r} (keys: {sorted(loss)})")
        return _map_loss(entry)
    if isinstance(loss, (list, tuple)):
        if index >= len(loss):
            raise KerasImportException(
                f"training_config loss list has {len(loss)} entries but "
                f"output index is {index}")
        return _map_loss(loss[index])
    return _map_loss(loss)


def _pair(v, default=(1, 1)) -> Tuple[int, int]:
    if v is None:
        return default
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


class _KerasLayer:
    """One parsed Keras layer: class name, config dict, weight group name."""

    def __init__(self, spec: Dict[str, Any]):
        self.class_name = spec.get("class_name")
        self.config = spec.get("config", {}) or {}
        self.name = self.config.get("name") or spec.get("name")
        self.inbound = _inbound_names(spec)


def _inbound_names(spec) -> List[str]:
    nodes = spec.get("inbound_nodes") or []
    if not nodes:
        return []
    first = nodes[0]
    if isinstance(first, dict):  # Keras 3-style
        first = first.get("args", [])
    names = []
    for entry in first:
        if isinstance(entry, (list, tuple)) and entry:
            names.append(entry[0])
    return names


def _input_type_from_shape(shape, dim_ordering: str) -> InputType:
    """Keras batch_input_shape (minus batch dim) -> InputType."""
    dims = [int(d) for d in shape if d is not None]
    if len(dims) == 3:
        if dim_ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(f, t)
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    raise KerasImportException(f"Unsupported input shape {shape}")


def _layer_dim_ordering(cfg: Dict[str, Any], default="th"):
    v = cfg.get("dim_ordering") or cfg.get("data_format")
    if v in ("th", "channels_first"):
        return "th"
    if v in ("tf", "channels_last"):
        return "tf"
    return default  # Keras 1 default is "th"; see _model_dim_ordering


def _model_dim_ordering(specs: List[Dict[str, Any]], h5_attrs=None) -> str:
    """Infer the model-wide dim ordering when the layer carrying
    batch_input_shape has no dim_ordering/data_format key (real Keras files
    never store it on InputLayer — ADVICE r2). Order of evidence: the first
    Conv/Pooling layer that records an ordering, then the file's
    keras_version attr (Keras 2 default = channels_last), else Keras 1's
    'th' default."""
    def walk(spec_list):
        for spec in spec_list:
            cfg = spec.get("config", {}) or {}
            found = _layer_dim_ordering(cfg, default=None)
            if found:
                return found
            inner = cfg.get("layers")
            if isinstance(inner, list):  # nested Model/Sequential
                found = walk(inner)
                if found:
                    return found
        return None

    found = walk(specs)
    if found:
        return found
    if h5_attrs is not None:
        kv = h5_attrs.get("keras_version")
        if isinstance(kv, bytes):
            kv = kv.decode()
        if kv and not str(kv).startswith("1"):
            return "tf"
    return "th"


class _Converter:
    """Keras layer list -> framework layers, tracking weight mapping."""

    def __init__(self, training_config: Optional[Dict[str, Any]] = None,
                 default_dim_ordering: str = "th"):
        self.training_config = training_config or {}
        self.layers: List[Any] = []
        # our-layer-index -> (_KerasLayer, kind) for weight loading
        self.weight_map: Dict[int, Tuple[_KerasLayer, str]] = {}
        self.input_type: Optional[InputType] = None
        self._pending_pad: Tuple[int, int] = (0, 0)
        self.default_dim_ordering = default_dim_ordering
        self.dim_ordering = default_dim_ordering

    # -------------------------------------------------------------- layers

    def convert(self, kl: _KerasLayer) -> None:
        cfg = kl.config
        cname = kl.class_name
        if self.input_type is None and cfg.get("batch_input_shape"):
            self.dim_ordering = _layer_dim_ordering(
                cfg, self.default_dim_ordering)
            self.input_type = _input_type_from_shape(
                cfg["batch_input_shape"][1:], self.dim_ordering)
        handler = getattr(self, f"_on_{cname}", None)
        if handler is None:
            raise KerasImportException(f"Unsupported Keras layer: {cname!r}")
        handler(kl)

    def _add(self, layer, kl: Optional[_KerasLayer] = None, kind: str = ""):
        self.layers.append(layer)
        if kl is not None:
            self.weight_map[len(self.layers) - 1] = (kl, kind)

    def _on_InputLayer(self, kl):
        pass  # shape captured in convert()

    def _on_Dense(self, kl):
        cfg = kl.config
        n_out = int(cfg.get("output_dim") or cfg.get("units"))
        act = _map_activation(cfg.get("activation"))
        self._add(DenseLayer(n_out=n_out, activation=act), kl, "dense")

    def _on_Convolution2D(self, kl):
        cfg = kl.config
        n_out = int(cfg.get("nb_filter") or cfg.get("filters"))
        if cfg.get("nb_row") is not None:
            kernel = (int(cfg["nb_row"]), int(cfg["nb_col"]))
        else:
            kernel = _pair(cfg.get("kernel_size"))
        stride = _pair(cfg.get("subsample") or cfg.get("strides"))
        border = cfg.get("border_mode") or cfg.get("padding") or "valid"
        mode = "same" if border == "same" else "truncate"
        pad = self._pending_pad
        self._pending_pad = (0, 0)
        self._add(
            ConvolutionLayer(
                n_out=n_out, kernel_size=kernel, stride=stride, padding=pad,
                convolution_mode=mode,
                activation=_map_activation(cfg.get("activation")),
            ),
            kl, "conv",
        )

    _on_Conv2D = _on_Convolution2D

    def _on_ZeroPadding2D(self, kl):
        p = kl.config.get("padding") or (1, 1)
        if isinstance(p, (list, tuple)) and p and isinstance(p[0], (list, tuple)):
            # ((top, bottom), (left, right)) — only symmetric supported
            (t, b), (l, r) = p
            if t != b or l != r:
                raise KerasImportException("Asymmetric ZeroPadding2D unsupported")
            p = (t, l)
        ph, pw = _pair(p)
        self._pending_pad = (self._pending_pad[0] + ph, self._pending_pad[1] + pw)

    def _pool(self, kl, ptype):
        cfg = kl.config
        kernel = _pair(cfg.get("pool_size"), (2, 2))
        stride = _pair(cfg.get("strides"), kernel)
        border = cfg.get("border_mode") or cfg.get("padding") or "valid"
        self._add(SubsamplingLayer(
            pooling_type=ptype, kernel_size=kernel, stride=stride,
            convolution_mode="same" if border == "same" else "truncate",
        ))

    def _on_MaxPooling2D(self, kl):
        self._pool(kl, PoolingType.MAX)

    def _on_AveragePooling2D(self, kl):
        self._pool(kl, PoolingType.AVG)

    def _on_GlobalMaxPooling2D(self, kl):
        self._add(GlobalPoolingLayer(pooling_type=PoolingType.MAX))

    def _on_GlobalAveragePooling2D(self, kl):
        self._add(GlobalPoolingLayer(pooling_type=PoolingType.AVG))

    _on_GlobalMaxPooling1D = _on_GlobalMaxPooling2D
    _on_GlobalAveragePooling1D = _on_GlobalAveragePooling2D

    def _on_Flatten(self, kl):
        pass  # shape change handled by automatic input-type preprocessors

    def _on_Dropout(self, kl):
        p = float(kl.config.get("p", kl.config.get("rate", 0.5)))
        # Keras p = drop fraction; framework dropout = retain probability.
        self._add(DropoutLayer(dropout=1.0 - p))

    def _on_Activation(self, kl):
        self._add(ActivationLayer(
            activation=_map_activation(kl.config.get("activation"))))

    def _on_Embedding(self, kl):
        cfg = kl.config
        self._add(EmbeddingLayer(
            n_in=int(cfg.get("input_dim")),
            n_out=int(cfg.get("output_dim")),
            has_bias=False,
        ), kl, "embedding")
        if self.input_type is None and cfg.get("input_length"):
            self.input_type = InputType.feed_forward(int(cfg["input_length"]))

    def _on_LSTM(self, kl):
        cfg = kl.config
        if cfg.get("return_sequences") is False:
            raise KerasImportException(
                "LSTM(return_sequences=False) unsupported in Sequential import"
                " — use the functional import with a LastTimeStep vertex")
        n_out = int(cfg.get("output_dim") or cfg.get("units"))
        self._add(LSTM(
            n_out=n_out,
            activation=_map_activation(cfg.get("activation")),
            gate_activation=_map_activation(
                cfg.get("inner_activation") or cfg.get("recurrent_activation")),
        ), kl, "lstm")

    def _on_BatchNormalization(self, kl):
        cfg = kl.config
        self._add(BatchNormalization(
            eps=float(cfg.get("epsilon", 1e-5)),
            decay=float(cfg.get("momentum", 0.9)),
            activation="identity",  # Keras BN has no fused activation
        ), kl, "batchnorm")

    # ------------------------------------------------------- finalization

    def finalize_output_layer(self):
        """Make the net trainable: the tail becomes an output layer carrying
        the training-config loss (reference: `KerasModel` uses the compiled
        loss when enforceTrainingConfig). Dense tails convert to OutputLayer
        (identical weight layout); Activation tails convert to a param-free
        LossLayer; any other tail (LSTM, pooling, ...) gets a LossLayer
        appended — appending keeps `weight_map` indices valid."""
        from deeplearning4j_tpu.nn.conf.layers import LossLayer

        loss = _loss_for_output(self.training_config, "", 0)
        # Trailing Dropout layers are no-ops at inference and would sit
        # after the output head; drop them (they carry no weights, and only
        # trailing indices are removed, so weight_map stays valid).
        while self.layers and isinstance(self.layers[-1], DropoutLayer):
            self.layers.pop()
        if not self.layers:
            raise KerasImportException("Model has no convertible layers")
        layer = self.layers[-1]
        act = getattr(layer, "activation", None) or "identity"
        if loss == "mse" and act == "softmax":
            loss = "mcxent"
        if isinstance(layer, DenseLayer) and not isinstance(layer, OutputLayer):
            self.layers[-1] = OutputLayer(
                n_out=layer.n_out, activation=act, loss_function=loss)
        elif isinstance(layer, ActivationLayer):
            self.layers[-1] = LossLayer(activation=act, loss_function=loss)
        elif type(layer).__name__ not in (
                "OutputLayer", "RnnOutputLayer", "LossLayer"):
            # param-free loss head keeps the Keras function unchanged
            self.layers.append(LossLayer(activation="identity",
                                         loss_function=loss))


# ----------------------------------------------------------- weight loading


def _gate_slices(u):
    return slice(0, u), slice(u, 2 * u), slice(2 * u, 3 * u), slice(3 * u, 4 * u)


def _lstm_from_keras(arrays: List[np.ndarray], n_in: int, u: int):
    """Keras LSTM weights -> {W [n_in,4u], RW [u,4u], b [4u]} (i,f,o,g)."""
    if len(arrays) == 12:
        # Keras 1: W_i,U_i,b_i, W_c,U_c,b_c, W_f,U_f,b_f, W_o,U_o,b_o
        Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = arrays
        W = np.concatenate([Wi, Wf, Wo, Wc], axis=1)
        RW = np.concatenate([Ui, Uf, Uo, Uc], axis=1)
        b = np.concatenate([bi, bf, bo, bc])
    elif len(arrays) == 3:
        # Keras 2: kernel/recurrent_kernel/bias, gate order i,f,c,o
        k, rk, b2 = arrays
        i, f, c, o = (k[:, s] for s in _gate_slices(u))
        ri, rf, rc, ro = (rk[:, s] for s in _gate_slices(u))
        bi_, bf_, bc_, bo_ = (b2[s] for s in _gate_slices(u))
        W = np.concatenate([i, f, o, c], axis=1)
        RW = np.concatenate([ri, rf, ro, rc], axis=1)
        b = np.concatenate([bi_, bf_, bo_, bc_])
    else:
        raise KerasImportException(
            f"Unexpected LSTM weight count: {len(arrays)}")
    if W.shape != (n_in, 4 * u) or RW.shape != (u, 4 * u):
        raise KerasImportException(
            f"LSTM weight shapes {W.shape}/{RW.shape} don't match "
            f"n_in={n_in}, units={u}")
    return {"W": W, "RW": RW, "b": b}


def _conv_kernel(kernel: np.ndarray, cfg: Dict[str, Any], n_in: int,
                 n_out: int) -> np.ndarray:
    """Keras conv kernel -> HWIO.

    "th" kernels are additionally rotated 180° spatially: the Theano
    backend applies TRUE convolution (flipping kernels at application
    time), so reproducing the model with a cross-correlation conv (XLA,
    like DL4J's) needs the flip baked into the weights — reference
    `KerasConvolution.java:126-141` does the same reversal on import.
    Validated against a real Keras-1.1.2-written model in
    `tests/test_keras_import.py::TestRealKerasGoldenFile`."""
    if kernel.ndim != 4:
        raise KerasImportException(f"Conv kernel ndim {kernel.ndim}")
    ordering = _layer_dim_ordering(cfg)
    if ordering == "th" and kernel.shape[0] == n_out and kernel.shape[1] == n_in:
        return np.transpose(kernel[:, :, ::-1, ::-1], (2, 3, 1, 0))
    if kernel.shape[-1] == n_out and kernel.shape[-2] == n_in:
        return kernel  # already HWIO (tf ordering: cross-correlation, no flip)
    if kernel.shape[0] == n_out and kernel.shape[1] == n_in:
        return np.transpose(kernel[:, :, ::-1, ::-1], (2, 3, 1, 0))
    raise KerasImportException(
        f"Conv kernel shape {kernel.shape} doesn't match n_in={n_in}, "
        f"n_out={n_out}")


def _layer_weight_arrays(weights_root, name: str) -> List[np.ndarray]:
    if name not in weights_root:
        return []
    grp = weights_root[name]
    names = [n.decode() if isinstance(n, bytes) else str(n)
             for n in grp.attrs.get("weight_names", [])]
    if not names:
        # fall back: datasets in insertion order (h5py preserves creation order
        # only with track_order; sort as best effort)
        def walk(g, prefix=""):
            out = []
            for k in g:
                item = g[k]
                if hasattr(item, "shape"):
                    out.append(prefix + k)
                else:
                    out.extend(walk(item, prefix + k + "/"))
            return out
        names = walk(grp)
    return [np.asarray(grp[n]) for n in names]


def _th_flatten_perm(pre, dim_ordering: str):
    """Row-permutation indices for features crossing a CNN->dense flatten
    in a th-ordered file: the file indexes the feature map channel-first
    [c, h, w], the framework flattens NHWC [h, w, c]. Returns None when no
    permutation applies. Validated against a real Keras-1.1.2 model
    (tests/test_keras_import.py::TestRealKerasGoldenFile); reference
    analog: dl4j stays NCHW so its th flatten matches natively, while its
    tf path uses TensorFlowCnnToFeedForwardPreProcessor."""
    if dim_ordering != "th" or type(pre).__name__ != \
            "CnnToFeedForwardPreProcessor":
        return None
    h, w, c = pre.input_height, pre.input_width, pre.num_channels
    return np.arange(c * h * w).reshape(c, h, w).transpose(1, 2, 0).reshape(-1)


def _apply_weights(net, weight_map, weights_root, key_for_index,
                   conf_for_index, preproc_for_index=lambda i: None,
                   dim_ordering: str = "th") -> None:
    import jax.numpy as jnp

    for our_idx, (kl, kind) in weight_map.items():
        arrays = _layer_weight_arrays(weights_root, kl.name)
        if not arrays:
            raise KerasImportException(
                f"No weights found for Keras layer {kl.name!r}")
        # Conf comes from the BUILT net (shape inference has filled n_in).
        conf = conf_for_index(our_idx)
        lk = key_for_index(our_idx)
        tgt = dict(net.params_tree.get(lk, {}))
        dtype = next(iter(tgt.values())).dtype if tgt else jnp.float32
        if kind == "dense":
            W, b = (arrays + [np.zeros(conf.n_out)])[:2]
            if W.shape != (conf.n_in, conf.n_out):
                raise KerasImportException(
                    f"Dense weight shape {W.shape} != "
                    f"({conf.n_in}, {conf.n_out}) for {kl.name!r}")
            idx = _th_flatten_perm(preproc_for_index(our_idx), dim_ordering)
            if idx is not None:
                W = W[idx]
            tgt["W"] = jnp.asarray(W, dtype)
            if "b" in tgt:
                tgt["b"] = jnp.asarray(b, dtype)
        elif kind == "conv":
            kernel = _conv_kernel(arrays[0], kl.config, conf.n_in, conf.n_out)
            tgt["W"] = jnp.asarray(kernel, dtype)
            if "b" in tgt and len(arrays) > 1:
                tgt["b"] = jnp.asarray(arrays[1], dtype)
        elif kind == "embedding":
            tgt["W"] = jnp.asarray(arrays[0], dtype)
        elif kind == "lstm":
            mapped = _lstm_from_keras(arrays, conf.n_in, conf.n_out)
            for k, v in mapped.items():
                tgt[k] = jnp.asarray(v, dtype)
        elif kind == "batchnorm":
            gamma, beta, mean, var = arrays[:4]
            # A th-file BN between Flatten and the first Dense carries its
            # per-feature vectors in channel-first order too.
            idx = _th_flatten_perm(preproc_for_index(our_idx), dim_ordering)
            if idx is not None and gamma.shape[0] == idx.shape[0]:
                gamma, beta, mean, var = (a[idx] for a in
                                          (gamma, beta, mean, var))
            tgt["gamma"] = jnp.asarray(gamma, dtype)
            tgt["beta"] = jnp.asarray(beta, dtype)
            st = dict(net.state.get(lk, {}))
            st["mean"] = jnp.asarray(mean, dtype)
            st["var"] = jnp.asarray(var, dtype)
            net.state[lk] = st
        net.params_tree[lk] = tgt


# ------------------------------------------------------------- entry points


def _read_model_file(path):
    import h5py

    f = h5py.File(path, "r")
    cfg_raw = f.attrs.get("model_config")
    if cfg_raw is None:
        f.close()
        raise KerasImportException(
            f"{path}: no model_config attribute (weights-only file? The "
            "reference requires topology+weights too, KerasModelImport.java)")
    if isinstance(cfg_raw, bytes):
        cfg_raw = cfg_raw.decode()
    topo = json.loads(cfg_raw)
    train_raw = f.attrs.get("training_config")
    training = None
    if train_raw is not None:
        if isinstance(train_raw, bytes):
            train_raw = train_raw.decode()
        training = json.loads(train_raw)
    weights_root = f["model_weights"] if "model_weights" in f else f
    return f, topo, training, weights_root


def _sequential_layer_specs(topo) -> List[Dict[str, Any]]:
    cfg = topo.get("config")
    if isinstance(cfg, list):  # Keras 1
        return cfg
    if isinstance(cfg, dict) and "layers" in cfg:  # Keras 2
        return cfg["layers"]
    raise KerasImportException("Unrecognized Sequential config format")


def import_keras_sequential_model_and_weights(path, input_type: Optional[InputType] = None):
    """Keras Sequential .h5 -> initialized `MultiLayerNetwork`.

    Reference: `KerasModelImport.importKerasSequentialModelAndWeights`
    (`deeplearning4j-modelimport/.../KerasModelImport.java`)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    f, topo, training, weights_root = _read_model_file(path)
    try:
        if topo.get("class_name") != "Sequential":
            raise KerasImportException(
                f"Not a Sequential model: {topo.get('class_name')!r} "
                "(use import_keras_model_and_weights)")
        specs = _sequential_layer_specs(topo)
        conv = _Converter(training,
                          default_dim_ordering=_model_dim_ordering(specs, f.attrs))
        for spec in specs:
            conv.convert(_KerasLayer(spec))
        conv.finalize_output_layer()
        itype = input_type or conv.input_type
        if itype is None:
            raise KerasImportException(
                "Could not infer input shape; pass input_type=")
        builder = (NeuralNetConfiguration.builder()
                   .updater("sgd").learning_rate(
                       float(_training_lr(training)))
                   .list())
        for layer in conv.layers:
            builder.layer(layer)
        mln_conf = builder.set_input_type(itype).build()
        net = MultiLayerNetwork(mln_conf).init()
        def flatten_preproc(i):
            # The flatten preprocessor may sit a few indices before the
            # dense (param-free Dropout/Activation/BN in between); only the
            # FIRST weighted layer after it sees channel-ordered features.
            # The barrier check must precede the preprocessor lookup for
            # j < i: a preprocessor AT a weighted layer's index belongs to
            # that layer, not to a later one.
            for j in range(i, -1, -1):
                if j < i and type(net.layers[j]).__name__ in (
                        "DenseLayer", "ConvolutionLayer", "OutputLayer"):
                    return None
                pre = mln_conf.input_preprocessors.get(j)
                if pre is not None:
                    return pre
            return None

        _apply_weights(net, conv.weight_map, weights_root,
                       lambda i: net.layer_keys[i],
                       lambda i: net.layers[i],
                       flatten_preproc,
                       conv.dim_ordering)
        return net
    finally:
        f.close()


def _training_lr(training) -> float:
    try:
        return float(training["optimizer_config"]["config"]["lr"])
    except Exception:
        return 0.01


def import_keras_model_and_weights(path):
    """Keras functional Model .h5 -> initialized `ComputationGraph`.

    Supports linear chains plus Merge/Add/Concatenate join vertices
    (reference: `KerasModel.java` graph construction)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    f, topo, training, weights_root = _read_model_file(path)
    try:
        if topo.get("class_name") == "Sequential":
            raise KerasImportException(
                "Sequential model: use import_keras_sequential_model_and_weights")
        cfg = topo["config"]
        specs = [_KerasLayer(s) for s in cfg["layers"]]
        default_ordering = _model_dim_ordering(cfg["layers"], f.attrs)
        input_names = [e[0] for e in cfg.get("input_layers", [])]
        output_names = [e[0] for e in cfg.get("output_layers", [])]

        gb = (NeuralNetConfiguration.builder()
              .updater("sgd").learning_rate(float(_training_lr(training)))
              .graph_builder())
        input_types = []
        graph_names: Dict[str, str] = {}  # keras name -> graph vertex name
        # keras ZeroPadding2D name -> (ph, pw): folded into the conv that
        # actually CONSUMES it (graph connectivity, not file order).
        zero_pads: Dict[str, Tuple[int, int]] = {}
        weight_jobs = []  # (graph name, keras layer, kind, our conf)
        for kl in specs:
            cname = kl.class_name
            if cname == "InputLayer":
                shape = kl.config.get("batch_input_shape")
                # InputLayer configs never carry dim_ordering/data_format in
                # real Keras files; fall back to the model-wide ordering
                # inferred from the first conv/pool layer or keras_version.
                ordering = _layer_dim_ordering(kl.config, default_ordering)
                input_types.append(_input_type_from_shape(shape[1:], ordering))
                gb.add_inputs(kl.name)
                graph_names[kl.name] = kl.name
                continue
            # Resolve each inbound ref through any ZeroPadding chain,
            # accumulating that branch's padding.
            pad = (0, 0)
            inputs = []
            for n in kl.inbound:
                if n in zero_pads:  # chains collapse at registration
                    ph, pw = zero_pads[n]
                    pad = (pad[0] + ph, pad[1] + pw)
                inputs.append(graph_names.get(n, n))
            if cname in ("Merge", "Concatenate", "Add"):
                from deeplearning4j_tpu.nn.conf.graph import (
                    ElementWiseVertex, MergeVertex)
                if pad != (0, 0):
                    raise KerasImportException(
                        "ZeroPadding2D feeding a merge vertex is unsupported")
                mode = kl.config.get("mode") or cname.lower()
                if mode in ("concat", "concatenate"):
                    gb.add_vertex(kl.name, MergeVertex(), *inputs)
                elif mode in ("sum", "add"):
                    gb.add_vertex(kl.name, ElementWiseVertex(op="add"), *inputs)
                else:
                    raise KerasImportException(f"Unsupported merge mode {mode!r}")
                graph_names[kl.name] = kl.name
                continue
            if cname == "ZeroPadding2D":
                sub = _Converter(training)
                sub.input_type = InputType.feed_forward(1)
                sub.convert(kl)
                zero_pads[kl.name] = (
                    sub._pending_pad[0] + pad[0], sub._pending_pad[1] + pad[1])
                graph_names[kl.name] = inputs[0]
                continue
            sub = _Converter(training)
            sub.input_type = InputType.feed_forward(1)  # suppress re-infer
            sub.convert(kl)
            if not sub.layers:  # Flatten — passthrough
                graph_names[kl.name] = inputs[0]
                continue
            layer = sub.layers[0]
            if pad != (0, 0):
                if not isinstance(layer, ConvolutionLayer):
                    raise KerasImportException(
                        f"ZeroPadding2D must feed a conv, got {cname!r}")
                layer.padding = (layer.padding[0] + pad[0],
                                 layer.padding[1] + pad[1])
            gb.add_layer(kl.name, layer, *inputs)
            graph_names[kl.name] = kl.name
            if 0 in sub.weight_map:
                weight_jobs.append((kl.name, kl, sub.weight_map[0][1], layer))

        # Output vertices: convert a trailing plain Dense into an OutputLayer
        # with the compiled loss so the imported graph is trainable
        # (reference: `KerasModel` attaches the loss to output layers).
        from deeplearning4j_tpu.nn.conf.graph import LayerVertex as _LV

        from deeplearning4j_tpu.nn.conf.layers import LossLayer as _LossLayer

        for out_idx, name in enumerate(output_names):
            loss = _loss_for_output(training, name, out_idx)
            vname = graph_names[name]
            v = gb._vertices.get(vname)
            if not isinstance(v, _LV):
                continue
            act = getattr(v.layer, "activation", None) or "identity"
            out_loss = "mcxent" if (loss == "mse" and act == "softmax") else loss
            if isinstance(v.layer, DenseLayer) and not isinstance(v.layer, OutputLayer):
                v.layer = OutputLayer(n_out=v.layer.n_out, activation=act,
                                      loss_function=out_loss)
            elif isinstance(v.layer, ActivationLayer):
                v.layer = _LossLayer(activation=act, loss_function=out_loss)
        gb.set_outputs(*[graph_names[n] for n in output_names])
        gb.set_input_types(*input_types)
        graph_conf = gb.build()
        net = ComputationGraph(graph_conf).init()

        wmap = {i: (kl, kind) for i, (_, kl, kind, _) in enumerate(weight_jobs)}
        _apply_weights(
            net, wmap, weights_root,
            lambda i: weight_jobs[i][0],
            lambda i: net.layer_vertices[weight_jobs[i][0]].layer,
            lambda i: getattr(
                graph_conf.vertices[weight_jobs[i][0]], "preprocessor", None),
            default_ordering)
        return net
    finally:
        f.close()


class KerasModelImport:
    """Static façade matching the reference's `KerasModelImport.java`."""

    import_keras_sequential_model_and_weights = staticmethod(
        import_keras_sequential_model_and_weights)
    import_keras_model_and_weights = staticmethod(import_keras_model_and_weights)

    @staticmethod
    def import_keras_model(path):
        """Dispatch on the stored class_name."""
        import h5py

        with h5py.File(path, "r") as f:
            raw = f.attrs.get("model_config")
            if isinstance(raw, bytes):
                raw = raw.decode()
            cname = json.loads(raw).get("class_name") if raw else None
        if cname == "Sequential":
            return import_keras_sequential_model_and_weights(path)
        return import_keras_model_and_weights(path)
