"""Production observability core: metrics, tracing, step profiling.

Three parts (see each module's docstring):

- `metrics`   — process-global `MetricsRegistry` (Prometheus text + JSON)
- `tracer`    — process-global `Tracer` (Chrome trace-event ring buffer)
- `StepProfiler` — compile/execute/transfer/FLOPs split for one engine

Scrape points: `UIServer` and `InferenceServer` both serve `/metrics`
(Prometheus text) and the UIServer adds `/api/trace` (Chrome trace JSON —
save it and open in ui.perfetto.dev). `bench.py` embeds `bench_snapshot()`
into BENCH_out.json.

Env knobs (read once at import):

- `DL4J_TPU_OBS`              — "0"/"false"/"off" disables both the default
                                registry and tracer (mutators become one
                                bool check; spans become a shared no-op).
- `DL4J_TPU_OBS_SAMPLE_EVERY` — record every Nth iteration span (default 1;
                                metrics are never sampled, only spans).
- `DL4J_TPU_TRACE_BUFFER`     — trace ring-buffer capacity (default 16384).
- `DL4J_TPU_FLIGHT*`          — flight-recorder knobs (see `flight.py`).

PR 7 adds the forensics + memory tier: `flight` (always-on crash/NaN/
preemption FlightRecorder, bundles inspectable with `python -m
deeplearning4j_tpu.observability.flight <bundle>`) and `memory`
(per-program HBM gauges from `memory_analysis()`, live-buffer
attribution, measured serving footprints). UIServer serves both at
`/api/flight` and `/api/memory`.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from deeplearning4j_tpu.observability import propagate
from deeplearning4j_tpu.observability.metrics import (
    DEFAULT_BUCKETS, WIDE_BUCKETS, MetricsRegistry,
    install_builtin_collectors)
from deeplearning4j_tpu.observability.tracing import NOOP_SPAN, Tracer
from deeplearning4j_tpu.observability.profiler import (
    StepProfiler, chip_peak_flops, chip_peak_hbm_bw, estimate_step_cost,
    estimate_step_flops)

__all__ = [
    "metrics", "tracer", "config", "StepProfiler", "MetricsRegistry",
    "Tracer", "DEFAULT_BUCKETS", "WIDE_BUCKETS", "enable", "disable",
    "iteration_span", "host_nbytes", "install_jax_compile_hook",
    "bench_snapshot", "prometheus_payload", "chip_peak_flops",
    "chip_peak_hbm_bw", "estimate_step_cost",
    "estimate_step_flops", "flight", "FlightRecorder", "memory",
    "propagate", "install_build_info", "request_ledger", "RequestLedger",
    "slo",
]

OBS_ENABLED = os.environ.get("DL4J_TPU_OBS", "1").lower() not in (
    "0", "false", "off")


class _Config:
    """Mutable runtime knobs (import-time defaults from the environment)."""

    def __init__(self):
        try:
            self.sample_every = max(
                1, int(os.environ.get("DL4J_TPU_OBS_SAMPLE_EVERY", "1")))
        except ValueError:
            self.sample_every = 1


config = _Config()

# The process-global instruments. Hot-loop call sites resolve their labeled
# children from `metrics` once at module import; `enable()`/`disable()` flip
# both at runtime regardless of the env default.
metrics = MetricsRegistry(enabled=OBS_ENABLED)
install_builtin_collectors(metrics)
tracer = Tracer(enabled=OBS_ENABLED)


def install_build_info(registry: Optional[MetricsRegistry] = None) -> None:
    """Register the `dl4j_build_info{version,jax,backend,device_kind}`
    info-gauge (constant 1). Labels resolve at scrape time — jax is never
    imported just to report a version, and the series upgrades in place
    once jax/the backend come up. Federated scrapes read this to spot
    mixed-version fleets mid-rolling-update."""
    reg = registry or metrics
    fam = reg.gauge(
        "dl4j_build_info",
        "Build/runtime identity of this process (value is always 1); "
        "compare worker_id series in a federated scrape to detect "
        "mixed-version fleets during rolling updates",
        label_names=("version", "jax", "backend", "device_kind"))
    state: Dict[str, Any] = {}

    def collect(_reg: MetricsRegistry) -> None:
        import sys

        import deeplearning4j_tpu as _pkg

        labels = {"version": getattr(_pkg, "__version__", "unknown"),
                  "jax": "unloaded", "backend": "unknown",
                  "device_kind": "unknown"}
        jax = sys.modules.get("jax")  # never import jax just to report it
        if jax is not None:
            try:
                labels["jax"] = jax.__version__
                labels["backend"] = jax.default_backend()
                labels["device_kind"] = jax.devices()[0].device_kind
            except Exception:
                pass
        key = tuple(labels.values())
        if state.get("key") != key:
            prev = state.get("child")
            if prev is not None:
                prev.set(0.0)  # labels upgraded (jax came up): retire old
            state["key"] = key
            state["child"] = fam.labels(**labels)
        state["child"].set(1.0)

    reg.register_collector(collect)


install_build_info(metrics)


def enable() -> None:
    metrics.enable()
    tracer.enabled = True


def disable() -> None:
    metrics.disable()
    tracer.enabled = False


def iteration_span(engine: str, iteration: int, **args):
    """Span for one training iteration, honoring `config.sample_every`.
    Returns the shared no-op for sampled-out iterations so the hot loop
    never allocates for them."""
    if not tracer.enabled or iteration % config.sample_every:
        return NOOP_SPAN
    return tracer.span(f"{engine}.iteration", cat="train", engine=engine,
                       iteration=iteration, **args)


def host_nbytes(*parts) -> int:
    """Total bytes of host-resident numpy arrays among `parts` (arrays,
    lists/tuples of arrays, or None) — the host->device transfer cost of
    staging them; device-resident jax arrays count 0."""
    import numpy as np

    total = 0
    for part in parts:
        if part is None:
            continue
        arrays = part if isinstance(part, (list, tuple)) else [part]
        for a in arrays:
            if isinstance(a, np.ndarray):
                total += a.nbytes
    return total


# ------------------------------------------------------- XLA compile hook

_hook_lock = threading.Lock()
_hook_installed = False
_hook_registries: list = []
# Persistent-cache hit attribution: on a hit jax STILL emits a
# `backend_compile` duration event (near-zero — the "compile" was a disk
# read), which used to be miscounted as a real compile. The cache_hits
# event precedes it on the same thread, so a thread-local pending flag
# re-routes the next backend_compile event to the persistent bucket.
_hook_tls = threading.local()


def _register_hook_families(reg: MetricsRegistry) -> None:
    reg.counter("dl4j_xla_compiles_total",
                "XLA backend compiles observed via jax.monitoring "
                "(persistent-cache hits excluded)")
    reg.counter("dl4j_xla_compile_seconds_total",
                "Seconds in jax compile pipeline phases",
                label_names=("phase",))
    reg.counter("dl4j_compile_cache_hits_total",
                "Compile-cache hits by layer (aot = framework executable "
                "store, persistent = jax/XLA persistent compilation cache)",
                label_names=("source",))
    reg.counter("dl4j_compile_cache_misses_total",
                "Compile-cache misses by layer (see "
                "dl4j_compile_cache_hits_total)",
                label_names=("source",))
    reg.histogram("dl4j_compile_seconds",
                  "Seconds to make one program runnable, by source (trace = "
                  "full lowering + backend compile, persistent = XLA cache "
                  "retrieval, aot = executable deserialization)",
                  label_names=("source",), buckets=WIDE_BUCKETS)


def install_jax_compile_hook(registry: Optional[MetricsRegistry] = None) -> bool:
    """Feed `jax.monitoring` compile events into the registry:

    - `dl4j_xla_compiles_total` — real backend compiles (a persistent-cache
      hit fires jax's backend_compile event with ~zero duration; those are
      attributed to the cache, not counted here)
    - `dl4j_xla_compile_seconds_total{phase}` — trace / mlir / backend...
    - `dl4j_compile_cache_hits_total` / `_misses_total` {source=persistent}
    - `dl4j_compile_seconds{source=trace|persistent}` (the `aot` source is
      observed by `compilation.store`, not here)

    The jax listeners are installed once per process; additional registries
    passed on later calls are fanned out to. Returns True when the hook is
    (now) active."""
    global _hook_installed
    reg = registry or metrics
    with _hook_lock:
        if reg not in _hook_registries:
            _hook_registries.append(reg)
            _register_hook_families(reg)
        if _hook_installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False

        def on_cache_event(event: str, **kw) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                _hook_tls.persistent_hit = True
                for r in _hook_registries:
                    r.counter("dl4j_compile_cache_hits_total",
                              label_names=("source",)).labels(
                                  source="persistent").inc()
            elif event == "/jax/compilation_cache/cache_misses":
                _hook_tls.persistent_hit = False
                for r in _hook_registries:
                    r.counter("dl4j_compile_cache_misses_total",
                              label_names=("source",)).labels(
                                  source="persistent").inc()

        def on_event(event: str, duration: float, **kw) -> None:
            if event.endswith("/cache_retrieval_time_sec"):
                for r in _hook_registries:
                    r.histogram("dl4j_compile_seconds",
                                label_names=("source",)).labels(
                                    source="persistent").observe(duration)
                return
            if not event.startswith("/jax/core/compile"):
                return
            # '/jax/core/compile/backend_compile_duration' -> 'backend_compile'
            phase = event.rsplit("/", 1)[-1]
            if phase.endswith("_duration"):
                phase = phase[:-len("_duration")]
            is_backend = phase == "backend_compile"
            pending_hit = is_backend and getattr(
                _hook_tls, "persistent_hit", False)
            if pending_hit:
                _hook_tls.persistent_hit = False
            for r in _hook_registries:
                r.counter("dl4j_xla_compile_seconds_total",
                          label_names=("phase",)).labels(
                              phase=phase).inc(duration)
                if is_backend and not pending_hit:
                    r.counter("dl4j_xla_compiles_total").inc()
                    r.histogram("dl4j_compile_seconds",
                                label_names=("source",)).labels(
                                    source="trace").observe(duration)

        try:
            monitoring.register_event_listener(on_cache_event)
            monitoring.register_event_duration_secs_listener(on_event)
        except Exception:
            return False
        _hook_installed = True
        return True


# ------------------------------------------------------------- exposition


def prometheus_payload(fmt: str = "prometheus",
                       registry: Optional[MetricsRegistry] = None,
                       names: Optional[Any] = None):
    """One scrape body for every HTTP surface (`UIServer` and the serving
    tier both mount `GET /metrics` on this): returns `(body_bytes,
    content_type)`. `fmt="json"` serves the structured snapshot instead of
    Prometheus text 0.0.4. `names` (iterable of family names, from the
    `?names=a,b` query param) narrows the body to those families — the
    needle scrape the fleet router's load poll uses, whose cost must not
    scale with how many families the process hosts."""
    import json

    reg = registry or metrics
    if fmt == "json":
        return (json.dumps(reg.to_json(names=names)).encode(),
                "application/json")
    return (reg.to_prometheus(names=names).encode(),
            "text/plain; version=0.0.4")


# ------------------------------------------------------------ bench glue


def bench_snapshot(registry: Optional[MetricsRegistry] = None) -> Dict[str, Any]:
    """Compact observability summary for BENCH_out.json: step-latency
    histogram summaries, compile totals, MFU, jit-cache hit/miss, transfer
    and checkpoint byte counters. Safe to call with nothing recorded."""
    reg = registry or metrics
    out: Dict[str, Any] = {}

    def family_values(name):
        fam = reg.get_family(name)
        if fam is None:
            return None
        vals = {}
        for child in fam.children():
            key = ",".join(f"{k}={v}" for k, v in child.labels.items()) or "_"
            vals[key] = child.get()
        return vals or None

    for hist in ("dl4j_step_latency_seconds", "dl4j_step_dispatch_seconds",
                 "dl4j_infer_latency_seconds", "dl4j_request_latency_seconds",
                 "dl4j_serving_request_seconds", "dl4j_serving_ttft_seconds",
                 "dl4j_serving_itl_seconds",
                 "dl4j_serving_decode_step_seconds", "dl4j_compile_seconds",
                 "dl4j_input_wait_seconds"):
        fam = reg.get_family(hist)
        if fam is None:
            continue
        for child in fam.children():
            summary = child.summarize()
            if not summary.get("count"):
                continue
            key = ",".join(f"{k}={v}" for k, v in child.labels.items())
            out.setdefault(hist, {})[key or "_"] = summary
    for name in ("dl4j_xla_compiles_total", "dl4j_xla_compile_seconds_total",
                 "dl4j_compile_cache_hits_total",
                 "dl4j_compile_cache_misses_total",
                 "dl4j_requests_total",
                 "dl4j_serving_generated_tokens_total",
                 "dl4j_serving_evictions_total",
                 "dl4j_tenant_device_seconds_total",
                 "dl4j_tenant_tokens_total",
                 "dl4j_jit_cache_hits_total", "dl4j_jit_cache_misses_total",
                 "dl4j_host_to_device_bytes_total",
                 "dl4j_checkpoint_bytes_written_total",
                 "dl4j_program_hbm_bytes", "dl4j_flight_dumps_total",
                 "dl4j_profiler_compile_seconds",
                 "dl4j_profiler_execute_seconds_median",
                 "dl4j_train_flops_per_step", "dl4j_train_mfu"):
        vals = family_values(name)
        if vals:
            out[name] = vals
    return out


# ------------------------------------------------- forensics + memory tier
# Imported LAST: both modules resolve their metric families from the
# process-global `metrics` defined above. `flight` is re-exported as the
# recorder INSTANCE (`observability.flight.dump()` / `.record_step(...)`);
# the module itself stays importable as
# `deeplearning4j_tpu.observability.flight` (and runnable with -m).

from deeplearning4j_tpu.observability import memory  # noqa: E402,F401
from deeplearning4j_tpu.observability.flight import (  # noqa: E402
    FlightRecorder, recorder as flight)
# `request_ledger` is the instance; the module keeps its dotted name
# (`deeplearning4j_tpu.observability.ledger`) for the serving tier.
from deeplearning4j_tpu.observability.ledger import (  # noqa: E402
    RequestLedger, ledger as request_ledger)
from deeplearning4j_tpu.observability import slo  # noqa: E402,F401
