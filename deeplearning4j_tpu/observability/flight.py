"""FlightRecorder: always-on crash/NaN/preemption forensics.

A fixed-size ring of per-step records fed from the engines'
`_fit_dispatch` choke points — iteration, loss, dispatch seconds and
superstep `k`, compile/jit-cache deltas, h2d bytes, input wait, live
buffer bytes. Recording is designed to stay inside the <2% step budget
(`bench.py obs_overhead` pins it): one enabled check, one dict build, one
deque append; the loss is stored as the raw (possibly device) scalar and
only materialized at dump time, so recording never syncs the step.

A **dump** writes a self-contained bundle directory:

- ``MANIFEST.json``  — reason, exception, env/config/version fingerprint
- ``steps.jsonl``    — the ring, one JSON record per line (oldest first)
- ``trace.json``     — Chrome trace: the span buffer plus the ring's
  steps as ``X`` events (open in ui.perfetto.dev)
- ``metrics.json``   — full registry snapshot (`MetricsRegistry.to_json`)
- ``ledger.jsonl``   — recently closed request-ledger records (who was
  in flight, and whose device-seconds they were — `observability/ledger.py`)
- ``memory.pprof``   — `jax.profiler.device_memory_profile()` when the
  backend provides it (`pprof -http : memory.pprof`)

Dump triggers: NaN loss (the `analysis/runtime.py` guard), uncaught
dispatch exceptions, SIGTERM/SIGINT (preemption — handlers install
lazily on the first recorded step), serving batch-loop failures, and an
explicit ``observability.flight.dump()``. Automatic triggers are
rate-limited per reason so a crash loop cannot fill the disk.

Env knobs (read once at import):

- ``DL4J_TPU_FLIGHT``                — "0"/"false"/"off" disables recording
  (dump() still writes metrics/trace bundles on demand)
- ``DL4J_TPU_FLIGHT_RING``           — ring capacity in steps (default 512)
- ``DL4J_TPU_FLIGHT_DIR``            — bundle root (default
  ``./flight_recordings``)
- ``DL4J_TPU_FLIGHT_SIGNALS``        — "0" skips the SIGTERM/SIGINT hooks
- ``DL4J_TPU_FLIGHT_MIN_INTERVAL_S`` — per-reason auto-dump rate limit
  (default 10 s; explicit dumps ignore it)
- ``DL4J_TPU_FLIGHT_LIVE_EVERY``     — sample live-buffer bytes every Nth
  record (default 8; walking jax.live_arrays() per step is not free)

Inspect a bundle with ``python -m deeplearning4j_tpu.observability.flight
<bundle-dir>``.
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional


def _env_flag(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).lower() not in ("0", "false", "off")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _finite(v):
    """JSON/trace-safe number: non-finite floats become their repr."""
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)
    return v


class FlightRecorder:
    """See module docstring. One instance (`observability.flight`) is the
    process-global recorder; tests build their own."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 dump_dir: Optional[str] = None):
        if capacity is None:
            capacity = _env_int("DL4J_TPU_FLIGHT_RING", 512)
        self.enabled = (_env_flag("DL4J_TPU_FLIGHT")
                        if enabled is None else bool(enabled))
        self.dump_dir = dump_dir or os.environ.get(
            "DL4J_TPU_FLIGHT_DIR", os.path.join(".", "flight_recordings"))
        self.min_interval_s = _env_float("DL4J_TPU_FLIGHT_MIN_INTERVAL_S",
                                         10.0)
        self.live_every = max(1, _env_int("DL4J_TPU_FLIGHT_LIVE_EVERY", 8))
        self._ring: deque = deque(maxlen=max(8, int(capacity)))
        self._lock = threading.Lock()
        self._seq = 0
        self._last_live_bytes: Optional[int] = None
        self._last_counts: Dict[str, float] = {}  # per-engine jit cumulatives
        self._compile_family = None
        self._compiles_prev: Optional[float] = None
        self._last_dump_at: Dict[str, float] = {}  # reason -> monotonic
        self._dumps: List[str] = []
        # Last successfully written bundle's reason, readable by the
        # elastic preemption path: when the signal handler already dumped
        # (reason "signal:SIGTERM"), the graceful-exit path must NOT write
        # a second bundle for the same preemption (satellite contract:
        # exactly one bundle per process per preemption).
        self.last_dump_reason: Optional[str] = None
        self._signals_installed = False
        self._prev_handlers: Dict[int, Any] = {}
        # The installed handler function, exposed so cooperating handlers
        # (ElasticTrainer's preemption hook) can recognize it by identity:
        # chaining INTO it is fatal when its own prev is SIG_DFL (it
        # re-raises to preserve the death-by-signal exit status).
        self.signal_handler: Any = None

    # -------------------------------------------------------------- feeding

    def record_step(self, engine: str, iteration: int, loss=None,
                    seconds: float = 0.0, k: int = 1, h2d_bytes: int = 0,
                    input_wait: Optional[float] = None,
                    jit_hits: Optional[float] = None,
                    jit_misses: Optional[float] = None) -> None:
        """One per-step ring record (called from `_fit_dispatch`'s finally
        block on every training path). Must never raise and never sync."""
        if not self.enabled:
            return
        try:
            self._maybe_install_signals()
            rec = {
                "type": "step",
                "engine": engine,
                "iteration": int(iteration),
                "loss": loss,  # raw scalar; materialized at dump time
                "seconds": float(seconds),
                "k": int(k),
                "h2d_bytes": int(h2d_bytes),
                "t_ns": time.perf_counter_ns(),
                "tid": threading.get_ident() & 0x7FFFFFFF,
            }
            if input_wait is not None:
                rec["input_wait"] = float(input_wait)
            with self._lock:
                self._seq += 1
                rec["seq"] = self._seq
                self._add_deltas(rec, engine, jit_hits, jit_misses)
                if self._seq % self.live_every == 1 or self.live_every == 1:
                    self._last_live_bytes = self._live_buffer_bytes()
                if self._last_live_bytes is not None:
                    rec["live_buffer_bytes"] = self._last_live_bytes
                self._ring.append(rec)
        except Exception:
            pass

    def record_event(self, kind: str, **fields) -> None:
        """Non-step ring event (NaN marker, serving failure, ...)."""
        if not self.enabled:
            return
        try:
            rec = {"type": str(kind), "t_ns": time.perf_counter_ns(),
                   "tid": threading.get_ident() & 0x7FFFFFFF}
            rec.update(fields)
            with self._lock:
                self._seq += 1
                rec["seq"] = self._seq
                self._ring.append(rec)
        except Exception:
            pass

    def _add_deltas(self, rec, engine, jit_hits, jit_misses) -> None:
        """Compile / jit-cache deltas since the previous record (cheap:
        the engine passes its own cumulative counters; the XLA compile
        total is one small registry-family sum)."""
        compiles = self._compiles_total()
        if compiles is not None:
            prev = self._compiles_prev
            if prev is not None:
                rec["compile_delta"] = compiles - prev
            self._compiles_prev = compiles
        for name, cum in (("jit_hits", jit_hits), ("jit_misses", jit_misses)):
            if cum is None:
                continue
            key = f"{engine}.{name}"
            prev = self._last_counts.get(key)
            if prev is not None:
                rec[f"{name}_delta"] = cum - prev
            self._last_counts[key] = cum

    def _compiles_total(self) -> Optional[float]:
        try:
            if self._compile_family is None:
                from deeplearning4j_tpu import observability as _obs

                self._compile_family = _obs.metrics.get_family(
                    "dl4j_xla_compiles_total")
            fam = self._compile_family
            if fam is None:
                return None
            return sum(c.get() for c in fam.children())
        except Exception:
            return None

    def _live_buffer_bytes(self) -> Optional[int]:
        jax = sys.modules.get("jax")  # never import jax just to sample
        if jax is None:
            return None
        try:
            return sum(int(getattr(a, "nbytes", 0) or 0)
                       for a in jax.live_arrays())
        except Exception:
            return None

    # ------------------------------------------------------------- triggers

    def on_crash(self, where: str, exc: BaseException) -> Optional[str]:
        """Uncaught-failure trigger (engine dispatch, serving loops):
        records the event and writes a rate-limited bundle. Never raises."""
        try:
            self.record_event("crash", where=str(where),
                              error=f"{type(exc).__name__}: {exc}")
            return self.dump(reason=f"crash:{where}", exc=exc, force=False)
        except Exception:
            return None

    def _maybe_install_signals(self) -> None:
        if self._signals_installed or not _env_flag("DL4J_TPU_FLIGHT_SIGNALS"):
            return
        if threading.current_thread() is not threading.main_thread():
            return
        import signal

        self._signals_installed = True  # one attempt per process

        def handler(signum, frame):
            try:
                name = signal.Signals(signum).name
            except Exception:
                name = str(signum)
            try:
                self.dump(reason=f"signal:{name}", force=True)
            except Exception:
                pass
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                # restore the default disposition and re-raise so the
                # process still dies with the right signal status
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)

        self.signal_handler = handler
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.getsignal(sig)
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

    # ----------------------------------------------------------------- dump

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Materialized (JSON-ready) copies of the ring, oldest first."""
        with self._lock:
            records = list(self._ring)
        if limit is not None:
            records = records[-int(limit):]
        return [self._materialize(r) for r in records]

    def _materialize(self, rec: dict) -> dict:
        out = dict(rec)
        loss = out.get("loss")
        if loss is not None:
            try:
                out["loss"] = _finite(float(loss))
            except Exception:
                out["loss"] = None
        return out

    def dump(self, reason: str = "manual", exc: Optional[BaseException] = None,
             bundle_dir: Optional[str] = None, force: bool = True
             ) -> Optional[str]:
        """Write a forensics bundle; returns its path (None when a
        rate-limited automatic trigger was suppressed). `force=True`
        (the default for explicit calls) bypasses the per-reason rate
        limit."""
        now = time.monotonic()
        with self._lock:
            if not force:
                last = self._last_dump_at.get(reason)
                if last is not None and now - last < self.min_interval_s:
                    return None
            self._last_dump_at[reason] = now
        try:
            return self._write_bundle(reason, exc, bundle_dir)
        except Exception:
            return None

    def _write_bundle(self, reason, exc, bundle_dir) -> str:
        records = self.snapshot()
        if bundle_dir is None:
            slug = "".join(c if c.isalnum() or c in "-_." else "-"
                           for c in reason)[:60]
            stamp = time.strftime("%Y%m%d-%H%M%S")
            bundle_dir = os.path.join(
                self.dump_dir, f"{stamp}-pid{os.getpid()}-{slug}")
        os.makedirs(bundle_dir, exist_ok=True)

        manifest = self._manifest(reason, exc, len(records))
        with open(os.path.join(bundle_dir, "MANIFEST.json"), "w") as f:
            json.dump(manifest, f, indent=2, default=str)

        with open(os.path.join(bundle_dir, "steps.jsonl"), "w") as f:
            for rec in records:
                f.write(json.dumps(
                    {k: _finite(v) for k, v in rec.items()},
                    default=str) + "\n")

        with open(os.path.join(bundle_dir, "trace.json"), "w") as f:
            json.dump(self._chrome_trace(records), f, default=str)

        try:
            from deeplearning4j_tpu import observability as _obs

            with open(os.path.join(bundle_dir, "metrics.json"), "w") as f:
                json.dump(_obs.metrics.to_json(), f, default=str)
        except Exception:
            pass

        # Join the request-lifecycle ledger: the same bundle that shows
        # WHERE the process was (steps/trace) shows WHICH requests were
        # in flight and who they were billed to.
        try:
            from deeplearning4j_tpu.observability.ledger import (
                ledger as _ledger)

            records = _ledger.snapshot()
            if records:
                with open(os.path.join(bundle_dir, "ledger.jsonl"),
                          "w") as f:
                    for rec in records:
                        f.write(json.dumps(rec, default=str) + "\n")
        except Exception:
            pass

        self._write_pprof(os.path.join(bundle_dir, "memory.pprof"))

        try:
            from deeplearning4j_tpu import observability as _obs

            _obs.metrics.counter(
                "dl4j_flight_dumps_total", "Flight-recorder bundle dumps",
                label_names=("reason",)).labels(
                    reason=reason.split(":", 1)[0]).inc()
        except Exception:
            pass
        with self._lock:
            self._dumps.append(bundle_dir)
            self.last_dump_reason = reason
        return bundle_dir

    def _manifest(self, reason, exc, n_records) -> Dict[str, Any]:
        manifest: Dict[str, Any] = {
            "bundle_format": 1,
            "reason": reason,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "cwd": os.getcwd(),
            "records": n_records,
            "ring_capacity": self._ring.maxlen,
        }
        if exc is not None:
            manifest["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }
        versions: Dict[str, Any] = {
            "python": sys.version.split()[0],
        }
        try:
            import deeplearning4j_tpu

            versions["deeplearning4j_tpu"] = deeplearning4j_tpu.__version__
        except Exception:
            pass
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                versions["jax"] = jax.__version__
                versions["devices"] = [str(d) for d in jax.devices()]
            except Exception:
                pass
        manifest["versions"] = versions
        manifest["env"] = {k: v for k, v in sorted(os.environ.items())
                           if k.startswith(("DL4J_TPU_", "JAX_", "XLA_"))}
        return manifest

    def _chrome_trace(self, records) -> Dict[str, Any]:
        """Span buffer + ring steps as one Chrome trace document."""
        try:
            from deeplearning4j_tpu import observability as _obs

            events = _obs.tracer.events()
            epoch_ns = getattr(_obs.tracer, "_epoch_ns", 0)
        except Exception:
            events, epoch_ns = [], 0
        pid = os.getpid()
        for rec in records:
            dur_us = float(rec.get("seconds", 0.0)) * 1e6
            end_us = (rec.get("t_ns", epoch_ns) - epoch_ns) / 1000.0
            args = {k: _finite(v) for k, v in rec.items()
                    if k not in ("t_ns", "tid", "seconds")}
            if rec.get("type") == "step":
                events.append({
                    "name": f"{rec.get('engine', '?')}.step",
                    "cat": "flight", "ph": "X",
                    "ts": end_us - dur_us, "dur": dur_us,
                    "pid": pid, "tid": rec.get("tid", 0), "args": args,
                })
            else:
                events.append({
                    "name": f"flight.{rec.get('type', 'event')}",
                    "cat": "flight", "ph": "i", "s": "t",
                    "ts": end_us, "pid": pid,
                    "tid": rec.get("tid", 0), "args": args,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def _write_pprof(self, path: str) -> None:
        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            import jax.profiler

            payload = jax.profiler.device_memory_profile()
            if payload:
                with open(path, "wb") as f:
                    f.write(payload)
        except Exception:
            pass

    # ------------------------------------------------------------- plumbing

    def status(self) -> Dict[str, Any]:
        """The `/api/flight` payload."""
        with self._lock:
            dumps = list(self._dumps)
            n = len(self._ring)
        return {
            "enabled": self.enabled,
            "capacity": self._ring.maxlen,
            "records": n,
            "dump_dir": self.dump_dir,
            "dumps": dumps,
            "recent": self.snapshot(limit=20),
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last_counts.clear()
            self._compiles_prev = None
            self._last_live_bytes = None


# The process-global recorder; `observability.flight` re-exports it.
recorder = FlightRecorder()


# ------------------------------------------------------------------ CLI


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def main(argv: Optional[List[str]] = None) -> int:
    """`python -m deeplearning4j_tpu.observability.flight <bundle-dir>`:
    pretty-print a dumped forensics bundle."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.observability.flight",
        description="Pretty-print a flight-recorder bundle directory")
    parser.add_argument("bundle", help="bundle directory (one dump)")
    parser.add_argument("--steps", type=int, default=12,
                        help="how many trailing step records to show")
    args = parser.parse_args(argv)

    mpath = os.path.join(args.bundle, "MANIFEST.json")
    if not os.path.isfile(mpath):
        print(f"not a flight bundle (no MANIFEST.json): {args.bundle}",
              file=sys.stderr)
        return 2
    with open(mpath) as f:
        manifest = json.load(f)
    print(f"flight bundle: {args.bundle}")
    print(f"  reason : {manifest.get('reason')}")
    print(f"  time   : {manifest.get('time')}  pid {manifest.get('pid')}")
    versions = manifest.get("versions", {})
    print("  runtime: " + ", ".join(
        f"{k}={v}" for k, v in versions.items() if k != "devices"))
    exc = manifest.get("exception")
    if exc:
        print(f"  crash  : {exc['type']}: {exc['message']}")
        tb = exc.get("traceback") or []
        for line in "".join(tb[-3:]).rstrip().splitlines():
            print(f"           {line}")

    spath = os.path.join(args.bundle, "steps.jsonl")
    if os.path.isfile(spath):
        with open(spath) as f:
            records = [json.loads(line) for line in f if line.strip()]
        steps = [r for r in records if r.get("type") == "step"]
        others = [r for r in records if r.get("type") != "step"]
        print(f"\n  {len(steps)} step records"
              f" ({len(others)} other events) — last {args.steps}:")
        print("    iter      loss   seconds  k  input_wait  live_hbm")
        for r in steps[-args.steps:]:
            wait = r.get("input_wait")
            print("    {:>6} {:>9} {:>9.4f} {:>2} {:>11} {:>9}".format(
                r.get("iteration", "?"),
                str(r.get("loss"))[:9],
                float(r.get("seconds", 0.0)),
                r.get("k", 1),
                "-" if wait is None else f"{wait:.4f}",
                _fmt_bytes(r.get("live_buffer_bytes"))
                if r.get("live_buffer_bytes") is not None else "-"))
        for r in others[-5:]:
            desc = {k: v for k, v in r.items()
                    if k not in ("t_ns", "tid", "seq")}
            print(f"    event: {desc}")

    mpath = os.path.join(args.bundle, "metrics.json")
    if os.path.isfile(mpath):
        with open(mpath) as f:
            metrics = json.load(f)
        interesting = [n for n in ("dl4j_train_iterations_total",
                                   "dl4j_xla_compiles_total",
                                   "dl4j_program_hbm_bytes",
                                   "dl4j_input_wait_seconds")
                       if n in metrics]
        print(f"\n  metrics.json: {len(metrics)} families"
              + (f" (incl. {', '.join(interesting)})" if interesting else ""))
    tpath = os.path.join(args.bundle, "trace.json")
    if os.path.isfile(tpath):
        with open(tpath) as f:
            trace = json.load(f)
        print(f"  trace.json: {len(trace.get('traceEvents', []))} events "
              "(open in ui.perfetto.dev)")
    ppath = os.path.join(args.bundle, "memory.pprof")
    if os.path.isfile(ppath):
        print(f"  memory.pprof: {os.path.getsize(ppath)} bytes "
              "(pprof -http : memory.pprof)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
