"""Declarative SLOs with multi-window burn-rate alerting over the fleet.

An SLO here is a statement like "99.9% of requests succeed" or "99% of
TTFTs land under 1s", declared once (`Objective`) and evaluated
continuously against the counters the serving tier already exports —
no new instrumentation, just arithmetic over `/fleet/metrics` deltas:

- **availability** objectives read `dl4j_requests_total{outcome}`:
  every non-ok outcome is a bad event;
- **latency** objectives read an SLO histogram
  (`dl4j_serving_ttft_seconds`, `dl4j_serving_itl_seconds`,
  `dl4j_serving_request_seconds{route="predict"}`): a bad event is an
  observation above the threshold bucket, counted exactly from the
  cumulative bucket ladder (thresholds snap to bucket bounds, so no
  interpolation error enters the burn math).

Alerting follows the multi-window burn-rate recipe (Google SRE workbook
ch. 5): burn rate = (bad/total) / error_budget over a window, and an
alert fires only when BOTH windows of a severity pair exceed the
threshold — the short window proves the burn is CURRENT (fast reset
once the incident ends), the long window proves it is SUSTAINED (a
single slow request can't page):

    page:   burn > 14.4 over BOTH  5m and 1h   (2% of a 30d budget/h)
    ticket: burn > 6    over BOTH 30m and 6h   (5% of a 30d budget/6h)

`window_scale` shrinks every window by one factor so tests (and demo
fleets) exercise real multi-window logic in seconds instead of hours.

The engine is pull-based and stateless-per-call except for the sample
ring: each `ingest()` parses one federated exposition (every sample
carries ``worker_id``) and appends one cumulative snapshot per worker;
`evaluate()` differences snapshots at the window edges. Per-worker
evaluation is what makes the page actionable — the alert names the
offending replicas, and the router's `on_page` hook POSTs each one's
`/admin/flight-dump` so the evidence (span ring, recent logs, metrics,
request ledger) is frozen while the incident is live.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.analysis.locktrace import named_lock

#: (severity, short_window_s, long_window_s, burn_threshold) — an alert
#: fires when burn exceeds the threshold over BOTH windows.
BURN_WINDOWS: Tuple[Tuple[str, float, float, float], ...] = (
    ("page", 300.0, 3600.0, 14.4),
    ("ticket", 1800.0, 21600.0, 6.0),
)

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+([^\s]+)')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[str, Dict[str, str],
                                                        float]]]:
    """Parse a (federated) Prometheus text exposition into per-worker
    samples: ``{worker_id: [(name, labels, value), ...]}``. Samples
    without a ``worker_id`` label (a plain single-process scrape) land
    under ``""``."""
    out: Dict[str, List[Tuple[str, Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = ({k: v for k, v in _LABEL_RE.findall(raw_labels)}
                  if raw_labels else {})
        wid = labels.pop("worker_id", "")
        out.setdefault(wid, []).append((name, labels, value))
    return out


class Objective:
    """One declarative SLO.

    `kind="availability"`: `target` is the success-ratio goal (0.999),
    bad events are `family{outcome != "ok"}` increments.

    `kind="latency"`: `target` is the quantile goal (0.99 for a p99
    objective), `threshold_s` the latency bound; bad events are
    histogram observations above the threshold. Pick thresholds on
    WIDE_BUCKETS bounds — the ladder counts them exactly.

    `labels` filters samples (e.g. ``{"route": "predict"}``); label
    keys absent from a sample don't match.
    """

    def __init__(self, name: str, kind: str, family: str, target: float,
                 threshold_s: Optional[float] = None,
                 labels: Optional[Dict[str, str]] = None,
                 description: str = ""):
        if kind not in ("availability", "latency"):
            raise ValueError(f"unknown objective kind {kind!r}")
        if kind == "latency" and threshold_s is None:
            raise ValueError("latency objectives need threshold_s")
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1)")
        self.name = name
        self.kind = kind
        self.family = family
        self.target = float(target)
        self.threshold_s = None if threshold_s is None else float(threshold_s)
        self.labels = dict(labels or {})
        self.description = description

    @property
    def budget(self) -> float:
        """Error budget: the tolerated bad-event fraction."""
        return 1.0 - self.target

    def _match(self, labels: Dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.labels.items())

    def counts(self, samples: List[Tuple[str, Dict[str, str], float]]
               ) -> Tuple[float, float]:
        """(bad, total) cumulative event counts from one worker's
        samples."""
        bad = total = 0.0
        if self.kind == "availability":
            for name, labels, value in samples:
                if name != self.family or not self._match(labels):
                    continue
                total += value
                if labels.get("outcome") != "ok":
                    bad += value
            return bad, total
        # latency: cumulative bucket ladder. good = count(le <= threshold)
        # at the LARGEST such bound; total = the +Inf bucket.
        bucket_name = self.family + "_bucket"
        good_le = -1.0
        good = 0.0
        for name, labels, value in samples:
            if name != bucket_name or not self._match(labels):
                continue
            le = labels.get("le", "")
            if le in ("+Inf", "inf", "Inf"):
                total += value
                continue
            try:
                bound = float(le)
            except ValueError:
                continue
            if bound <= self.threshold_s and bound >= good_le:
                if bound > good_le:
                    good_le, good = bound, 0.0
                good += value
        return max(0.0, total - good), total

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "family": self.family, "target": self.target,
                "threshold_s": self.threshold_s, "labels": self.labels,
                "description": self.description}


def default_objectives() -> List[Objective]:
    """The fleet's stock SLOs (ROADMAP §serving): availability plus the
    three latency surfaces a generation fleet pages on. Thresholds sit
    on WIDE_BUCKETS bounds so the bucket math is exact."""
    return [
        Objective("availability", "availability", "dl4j_requests_total",
                  target=0.999,
                  description="99.9% of requests end ok (any route)"),
        Objective("ttft_p99", "latency", "dl4j_serving_ttft_seconds",
                  target=0.99, threshold_s=1.0,
                  description="99% of first tokens within 1s"),
        Objective("itl_p99", "latency", "dl4j_serving_itl_seconds",
                  target=0.99, threshold_s=0.25,
                  description="99% of inter-token gaps within 250ms"),
        Objective("predict_p99", "latency", "dl4j_serving_request_seconds",
                  target=0.99, threshold_s=1.0,
                  labels={"route": "predict"},
                  description="99% of predicts within 1s"),
    ]


class BurnRateEngine:
    """Ingest federated expositions, evaluate burn rates, raise alerts.

    `window_scale` multiplies every burn window (1.0 = production
    5m/1h/30m/6h; tests pass ~1/600 to page within a second of real
    traffic). `on_page` fires once per evaluation per NEWLY paging
    objective with ``(objective_name, [worker_id, ...])`` — the hook
    the router uses to freeze flight bundles on the offenders; it does
    not re-fire while the same objective stays in page severity, so one
    sustained breach triggers one dump round.
    """

    def __init__(self, objectives: Optional[List[Objective]] = None,
                 window_scale: float = 1.0,
                 on_page: Optional[Callable[[str, List[str]], None]] = None,
                 history_s: Optional[float] = None):
        self.objectives = (default_objectives() if objectives is None
                           else list(objectives))
        self.window_scale = float(window_scale)
        self.on_page = on_page
        self.windows = [(sev, s * self.window_scale, l * self.window_scale,
                         burn) for sev, s, l, burn in BURN_WINDOWS]
        longest = max(l for _, _, l, _ in self.windows)
        self.history_s = (longest * 1.25 if history_s is None
                          else float(history_s))
        # {worker_id: deque[(t, {objective: (bad, total)})]}
        self._rings: Dict[str, deque] = {}
        self._paging: set = set()
        self._lock = named_lock("observability.slo")

    # ------------------------------------------------------------- ingest

    def ingest(self, text: str, now: Optional[float] = None) -> None:
        """Fold one exposition (federated or single-process) into the
        per-worker sample rings."""
        t = time.monotonic() if now is None else float(now)
        parsed = parse_prometheus(text)
        with self._lock:
            for wid, samples in parsed.items():
                counts = {o.name: o.counts(samples)
                          for o in self.objectives}
                ring = self._rings.setdefault(wid, deque())
                ring.append((t, counts))
                while ring and t - ring[0][0] > self.history_s:
                    ring.popleft()

    # ----------------------------------------------------------- evaluate

    @staticmethod
    def _delta(ring: deque, objective: str, t: float,
               window: float) -> Tuple[float, float]:
        """(bad, total) increments over [t - window, t]: newest sample
        minus the oldest sample still inside the window."""
        newest = oldest = None
        for st, counts in ring:
            c = counts.get(objective)
            if c is None:
                continue
            if st >= t - window:
                if oldest is None:
                    oldest = c
                newest = c
        if newest is None or oldest is None or newest is oldest:
            return 0.0, 0.0
        # Counter resets (restart) clamp to zero rather than go negative.
        return (max(0.0, newest[0] - oldest[0]),
                max(0.0, newest[1] - oldest[1]))

    def _burns(self, ring: deque, o: Objective, t: float) -> dict:
        """Per-severity burn rates for one worker ring."""
        out = {}
        for sev, short_w, long_w, threshold in self.windows:
            rates = []
            for w in (short_w, long_w):
                bad, total = self._delta(ring, o.name, t, w)
                rates.append((bad / total / o.budget) if total else 0.0)
            out[sev] = {"short": rates[0], "long": rates[1],
                        "threshold": threshold,
                        "firing": all(r > threshold for r in rates)}
        return out

    def evaluate(self, now: Optional[float] = None) -> dict:
        """The `/fleet/slo` document: every objective's burn rates per
        severity, fleet-wide and per worker, plus the firing alerts.
        Severity = the worst firing pair (page > ticket > ok)."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            rings = {wid: deque(ring) for wid, ring in self._rings.items()}
        doc: dict = {"objectives": [], "alerts": []}
        pages: List[Tuple[str, List[str]]] = []
        now_paging: set = set()
        for o in self.objectives:
            workers = {}
            offenders: Dict[str, List[str]] = {}
            for wid, ring in rings.items():
                burns = self._burns(ring, o, t)
                sev = next((s for s in ("page", "ticket")
                            if burns.get(s, {}).get("firing")), "ok")
                workers[wid] = {"severity": sev, "burns": burns}
                if sev != "ok":
                    offenders.setdefault(sev, []).append(wid)
            severity = next((s for s in ("page", "ticket")
                             if offenders.get(s)), "ok")
            entry = dict(o.to_dict(), severity=severity, workers=workers)
            doc["objectives"].append(entry)
            if severity != "ok":
                doc["alerts"].append({
                    "objective": o.name, "severity": severity,
                    "workers": sorted(offenders[severity])})
            if offenders.get("page"):
                now_paging.add(o.name)
                if o.name not in self._paging:
                    pages.append((o.name, sorted(offenders["page"])))
        with self._lock:
            self._paging = now_paging
        if self.on_page is not None:
            for name, wids in pages:
                try:
                    self.on_page(name, wids)
                except Exception:
                    pass
        doc["severity"] = next(
            (s for s in ("page", "ticket")
             if any(a["severity"] == s for a in doc["alerts"])), "ok")
        return doc

    def report(self, text: str, now: Optional[float] = None) -> dict:
        """ingest + evaluate in one call — the pull-based entry point a
        router GET handler uses: scrape the fleet, fold it in, return
        the current alert state."""
        self.ingest(text, now=now)
        return self.evaluate(now=now)
